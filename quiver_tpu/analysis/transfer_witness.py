"""Runtime device-transfer witness — the dynamic half of quiverlint v3.

QT013 proves over the static call graph that hot paths never coerce a
device value to host; this module watches the transfers the process
*actually* performs.  With ``QUIVER_SANITIZE=1`` in the environment,
``quiver_tpu`` installs the witness right after jax finishes importing
(the lock witness installs *before* — this one needs the array type to
exist), wrapping every device-array-to-host coercion point:

* ``jax.device_get`` — the explicit transfer entry point;
* ``ArrayImpl.item`` / ``ArrayImpl.tolist`` — scalar/list readback;
* ``ArrayImpl.__bool__`` / ``__int__`` / ``__float__`` / ``__index__``
  — the implicit coercions an ``if x:`` or ``int(x)`` performs;
* ``numpy.asarray`` / ``numpy.array`` — materialization.  These are
  wrapped at *module* level because jax arrays satisfy numpy's buffer
  protocol, so a class-level ``__array__`` patch never fires for them
  (``__array__`` is wrapped too, for the dispatch paths that do use
  it).

Every observed transfer is attributed: the
``sanitize_host_transfers_total{site}`` counter ticks, and when a
flight-recorder trace (or the always-on timeline) is live the transfer
lands on it as a ``host_transfer`` event, so a trace of a slow request
shows exactly where it blocked on the device.

A transfer is a *violation* only inside a declared no-sync region
(``with staging.no_sync("serving device loop"):`` — see
:mod:`quiver_tpu.analysis.staging.regions`).  Violations are
**recorded, never raised**: the suite keeps running and the conftest
harness fails the owning test from :func:`drain`, exactly like the lock
witness.  With the env var unset this module is never imported, the
region gate stays a single-global-read no-op, and numpy/jax are
untouched — the zero-overhead contract ``tests/test_transfer_witness.py``
pins.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Callable, List, Optional, Tuple

from .staging import regions

__all__ = [
    "Transfer", "Violation", "drain", "install", "installed",
    "transfers", "uninstall", "violations",
]

_INTERNAL_FILES: Tuple[str, ...] = (__file__,)

_MISSING = object()


class Violation:
    """One recorded sanitizer finding (kind, message, capture stack)."""

    __slots__ = ("kind", "message", "stack", "thread")

    def __init__(self, kind: str, message: str):
        self.kind = kind
        self.message = message
        self.thread = threading.current_thread().name
        self.stack = "".join(traceback.format_stack(sys._getframe(2), 8))

    def __repr__(self):
        return f"Violation({self.kind}: {self.message} [{self.thread}])"


class Transfer:
    """One observed device-to-host transfer (attribution record)."""

    __slots__ = ("site", "where", "region", "thread")

    def __init__(self, site: str, where: str, region: Optional[str]):
        self.site = site
        self.where = where
        self.region = region
        self.thread = threading.current_thread().name

    def __repr__(self):
        tail = f" in no-sync region `{self.region}`" if self.region else ""
        return f"Transfer({self.site} at {self.where}{tail})"


class _State:
    def __init__(self):
        self.lock = threading.Lock()      # guards the two lists
        self.violations: List[Violation] = []
        self.transfers: List[Transfer] = []
        # (owner, name, original-or-_MISSING) restore records
        self.saved: List[Tuple[object, str, object]] = []
        self.tls = threading.local()      # .busy re-entry depth


_state: Optional[_State] = None


def _caller_site() -> str:
    f = sys._getframe(2)
    for _ in range(16):
        if f is None:
            break
        fn = f.f_code.co_filename
        if fn not in _INTERNAL_FILES and "<" not in fn[:1]:
            return f"{fn.rsplit('/', 1)[-1]}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _observe(site: str) -> None:
    """Record one transfer: tick the counter, attribute to any live
    trace/timeline, and flag it when inside a declared no-sync region.
    Never raises."""
    st = _state
    if st is None:
        return
    where = _caller_site()
    region = regions.active()
    t = Transfer(site, where, region)
    with st.lock:
        st.transfers.append(t)
        if region is not None:
            st.violations.append(Violation(
                "in-region-sync",
                f"device-to-host transfer via `{site}` at {where} inside "
                f"no-sync region `{region}` — this path declared it never "
                f"blocks on the device"))
    try:
        from ..telemetry import counter, flightrec, timeline

        counter("sanitize_host_transfers_total", site=site).inc()
        if flightrec.tracing():
            flightrec.event("host_transfer",
                            {"site": site, "where": where,
                             "region": region})
        elif timeline.on():
            timeline.instant("host_transfer", cat="sanitize",
                             attrs={"site": site, "where": where})
    except Exception:
        pass  # telemetry must never break the suite under test


def _busy() -> bool:
    st = _state
    return st is not None and getattr(st.tls, "busy", 0) > 0


class _Busy:
    """Suppress nested observations: ``jax.device_get`` calling
    ``np.asarray`` internally is ONE transfer, not two."""

    __slots__ = ()

    def __enter__(self):
        st = _state
        if st is not None:
            st.tls.busy = getattr(st.tls, "busy", 0) + 1

    def __exit__(self, *exc):
        st = _state
        if st is not None:
            st.tls.busy = getattr(st.tls, "busy", 1) - 1
        return False


_BUSY = _Busy()


def _save(st: _State, owner, name: str) -> object:
    """Record the pre-patch attribute for uninstall.  Distinguishes
    'inherited' from 'own' so restore doesn't pin a copied slot."""
    own = owner.__dict__.get(name, _MISSING) if hasattr(owner, "__dict__") \
        else _MISSING
    orig = getattr(owner, name)
    st.saved.append((owner, name, own if own is not _MISSING else _MISSING))
    return orig


def _wrap_method(st: _State, cls, name: str, site: str) -> bool:
    if getattr(cls, name, None) is None:
        return False
    orig = _save(st, cls, name)

    def wrapped(self, *a, **k):
        if not _busy():
            _observe(site)
        with _BUSY:
            return orig(self, *a, **k)

    wrapped.__name__ = name
    wrapped.__qualname__ = f"{cls.__name__}.{name}"
    try:
        setattr(cls, name, wrapped)
    except (AttributeError, TypeError):
        st.saved.pop()
        return False
    return True


def install() -> None:
    """Wrap the device-to-host coercion points and arm the no-sync
    region gate.  Requires jax importable; idempotent."""
    global _state
    if _state is not None:
        return
    import jax
    import numpy
    from jax._src import array as _jarray

    ArrayImpl = _jarray.ArrayImpl
    st = _State()

    for name, site in (
        ("item", ".item()"),
        ("tolist", ".tolist()"),
        ("__bool__", "bool()"),
        ("__int__", "int()"),
        ("__float__", "float()"),
        ("__index__", "__index__"),
        ("__array__", "__array__"),
    ):
        _wrap_method(st, ArrayImpl, name, site)

    real_device_get = _save(st, jax, "device_get")

    def device_get(x):
        if not _busy():
            _observe("jax.device_get")
        with _BUSY:
            return real_device_get(x)

    jax.device_get = device_get

    def _wrap_np(fn: Callable, site: str) -> Callable:
        def wrapped(*a, **k):
            if a and isinstance(a[0], ArrayImpl) and not _busy():
                _observe(site)
            with _BUSY:
                return fn(*a, **k)

        wrapped.__name__ = getattr(fn, "__name__", site)
        return wrapped

    real_asarray = _save(st, numpy, "asarray")
    real_array = _save(st, numpy, "array")
    numpy.asarray = _wrap_np(real_asarray, "np.asarray")
    numpy.array = _wrap_np(real_array, "np.array")

    _state = st
    regions._ON = True      # arm `staging.no_sync()` region tracking


def uninstall() -> None:
    """Restore every patched attribute and drop recorded state.  The
    region gate disarms with it (``no_sync`` back to shared no-op)."""
    global _state
    st = _state
    if st is None:
        return
    regions._ON = False
    for owner, name, orig in reversed(st.saved):
        if orig is _MISSING:
            # attribute was inherited (or absent) pre-patch: drop ours
            try:
                delattr(owner, name)
            except AttributeError:
                pass
        else:
            setattr(owner, name, orig)
    st.saved.clear()
    _state = None


def installed() -> bool:
    return _state is not None


def violations() -> List[Violation]:
    st = _state
    if st is None:
        return []
    with st.lock:
        return list(st.violations)


def transfers() -> List[Transfer]:
    """The attribution log since install/last drain (tests assert a
    transfer landed on the right trace through this)."""
    st = _state
    if st is None:
        return []
    with st.lock:
        return list(st.transfers)


def drain() -> List[Violation]:
    """Return and clear recorded violations (and the attribution log) —
    the conftest autouse fixture fails the owning test on any."""
    st = _state
    if st is None:
        return []
    with st.lock:
        out = list(st.violations)
        st.violations.clear()
        st.transfers.clear()
        return out
