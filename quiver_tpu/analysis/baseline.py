"""quiverlint baseline — accepted pre-existing findings, committed to git.

The baseline is a multiset of finding fingerprints ``(rule, path, scope,
snippet)``.  Line numbers are deliberately excluded so edits elsewhere
in a file don't churn the baseline; moving or editing the flagged line
itself *does* invalidate the entry, which is the behavior you want — a
touched finding must be re-justified (fix it, suppress it inline, or
re-record the baseline).

Since v2 every entry also carries ``rule_hash``, a digest of the
emitting rule's implementation source (:func:`..rules.rule_fingerprints`).
Editing a rule's logic therefore invalidates its accepted entries under
``--strict-baseline``: the old entry was a judgment about what the *old*
detector reported, and letting it ride silently absorbs whatever the new
logic finds at the same fingerprint.  v1 baselines (no hashes) still
load; their entries simply carry no hash and are exempt from the check,
so the upgrade path is "re-record when convenient, strict once you do".

Workflow::

    python -m quiver_tpu.analysis quiver_tpu bench.py --write-baseline
    git add quiverlint.baseline.json

CI then runs the linter normally: findings matching the baseline are
reported as "baselined" and don't affect the exit code; anything new
fails the run (see ``tests/test_lint_clean.py``).
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

__all__ = ["DEFAULT_BASELINE_NAME", "load", "load_entries", "save",
           "partition", "stale", "hash_mismatches"]

DEFAULT_BASELINE_NAME = "quiverlint.baseline.json"
_VERSION = 2
_ACCEPTED_VERSIONS = (1, 2)


def save(path, findings: Sequence[Finding]) -> None:
    from .rules import rule_fingerprints

    hashes = rule_fingerprints()
    entries = []
    for f in sorted(findings, key=lambda x: (x.path, x.line, x.rule)):
        d = f.to_dict()
        h = hashes.get(f.rule)
        if h:
            d["rule_hash"] = h
        entries.append(d)
    doc = {
        "version": _VERSION,
        "tool": "quiverlint",
        "findings": entries,
    }
    Path(path).write_text(json.dumps(doc, indent=2) + "\n")


def load_entries(path) -> List[Tuple[Finding, Optional[str]]]:
    """(finding, recorded rule hash or None) per baseline entry."""
    doc = json.loads(Path(path).read_text())
    if doc.get("version") not in _ACCEPTED_VERSIONS:
        raise ValueError(
            f"baseline {path}: unsupported version {doc.get('version')!r}")
    return [(Finding.from_dict(d), d.get("rule_hash"))
            for d in doc.get("findings", [])]


def load(path) -> List[Finding]:
    return [f for f, _ in load_entries(path)]


def hash_mismatches(entries: Sequence[Tuple[Finding, Optional[str]]],
                    current: Dict[str, str],
                    ) -> List[Tuple[Finding, str, str]]:
    """Entries recorded under a different rule implementation.

    Returns (finding, recorded hash, current hash) triples; entries
    with no recorded hash (v1 baselines) are exempt.  Under
    ``--strict-baseline`` any mismatch fails the run: the accepted debt
    was a judgment about the *old* detector and must be re-recorded
    (or fixed) now that the logic changed.
    """
    out: List[Tuple[Finding, str, str]] = []
    for f, h in entries:
        cur = current.get(f.rule)
        if h is not None and cur is not None and h != cur:
            out.append((f, h, cur))
    return out


def partition(findings: Sequence[Finding],
              baseline: Sequence[Finding],
              ) -> Tuple[List[Finding], List[Finding]]:
    """Split ``findings`` into (new, baselined) by multiset fingerprint
    match — two identical snippets in one scope need two baseline
    entries, so a *second* copy of an accepted violation still fails."""
    budget = Counter(f.fingerprint() for f in baseline)
    new: List[Finding] = []
    known: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            known.append(f)
        else:
            new.append(f)
    return new, known


def stale(findings: Sequence[Finding],
          baseline: Sequence[Finding]) -> List[Finding]:
    """Baseline entries no longer matched by any current finding.

    A stale entry is accepted debt that has since been fixed (or the
    flagged line rewritten) without the baseline being re-recorded —
    harmless until someone reintroduces the same violation and the dead
    entry silently absorbs it.  ``--strict-baseline`` fails on these;
    multiset semantics mirror :func:`partition` (two identical accepted
    entries need two current findings to both stay live).
    """
    remaining = Counter(f.fingerprint() for f in baseline)
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
    out: List[Finding] = []
    claimed: Counter = Counter()
    for b in baseline:
        fp = b.fingerprint()
        if claimed[fp] < remaining.get(fp, 0):
            claimed[fp] += 1
            out.append(b)
    return out
