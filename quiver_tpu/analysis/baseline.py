"""quiverlint baseline — accepted pre-existing findings, committed to git.

The baseline is a multiset of finding fingerprints ``(rule, path, scope,
snippet)``.  Line numbers are deliberately excluded so edits elsewhere
in a file don't churn the baseline; moving or editing the flagged line
itself *does* invalidate the entry, which is the behavior you want — a
touched finding must be re-justified (fix it, suppress it inline, or
re-record the baseline).

Workflow::

    python -m quiver_tpu.analysis quiver_tpu bench.py --write-baseline
    git add quiverlint.baseline.json

CI then runs the linter normally: findings matching the baseline are
reported as "baselined" and don't affect the exit code; anything new
fails the run (see ``tests/test_lint_clean.py``).
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import List, Sequence, Tuple

from .core import Finding

__all__ = ["DEFAULT_BASELINE_NAME", "load", "save", "partition", "stale"]

DEFAULT_BASELINE_NAME = "quiverlint.baseline.json"
_VERSION = 1


def save(path, findings: Sequence[Finding]) -> None:
    doc = {
        "version": _VERSION,
        "tool": "quiverlint",
        "findings": [f.to_dict() for f in sorted(
            findings, key=lambda x: (x.path, x.line, x.rule))],
    }
    Path(path).write_text(json.dumps(doc, indent=2) + "\n")


def load(path) -> List[Finding]:
    doc = json.loads(Path(path).read_text())
    if doc.get("version") != _VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {doc.get('version')!r}")
    return [Finding.from_dict(d) for d in doc.get("findings", [])]


def partition(findings: Sequence[Finding],
              baseline: Sequence[Finding],
              ) -> Tuple[List[Finding], List[Finding]]:
    """Split ``findings`` into (new, baselined) by multiset fingerprint
    match — two identical snippets in one scope need two baseline
    entries, so a *second* copy of an accepted violation still fails."""
    budget = Counter(f.fingerprint() for f in baseline)
    new: List[Finding] = []
    known: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            known.append(f)
        else:
            new.append(f)
    return new, known


def stale(findings: Sequence[Finding],
          baseline: Sequence[Finding]) -> List[Finding]:
    """Baseline entries no longer matched by any current finding.

    A stale entry is accepted debt that has since been fixed (or the
    flagged line rewritten) without the baseline being re-recorded —
    harmless until someone reintroduces the same violation and the dead
    entry silently absorbs it.  ``--strict-baseline`` fails on these;
    multiset semantics mirror :func:`partition` (two identical accepted
    entries need two current findings to both stay live).
    """
    remaining = Counter(f.fingerprint() for f in baseline)
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
    out: List[Finding] = []
    claimed: Counter = Counter()
    for b in baseline:
        fp = b.fingerprint()
        if claimed[fp] < remaining.get(fp, 0):
            claimed[fp] += 1
            out.append(b)
    return out
