"""quiverlint CLI — ``python -m quiver_tpu.analysis [paths...]``.

Exit codes: 0 = clean (no unsuppressed, un-baselined findings),
1 = new findings, 2 = usage or internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from . import baseline as baseline_mod
from .core import LintConfig, analyze_paths
from .rules import all_rules

__all__ = ["main", "build_parser"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quiver_tpu.analysis",
        description="quiverlint: TPU hot-path static analysis "
                    "(QT001 host sync, QT002 retrace hazards, QT003 lock "
                    "discipline, QT004 import layering, QT005 hygiene; "
                    "v2 whole-program concurrency QT008-QT010; v3 staging "
                    "dataflow QT013 interprocedural sync, QT014 cache-key "
                    "bounds, QT015 collective discipline)",
    )
    p.add_argument("paths", nargs="*", default=["quiver_tpu"],
                   help="files or directories to lint "
                        "(default: quiver_tpu)")
    p.add_argument("--root", default=None,
                   help="directory findings are reported relative to "
                        "(default: CWD); baseline paths anchor here")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: <root>/"
                        f"{baseline_mod.DEFAULT_BASELINE_NAME} if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--strict-baseline", action="store_true",
                   help="also fail when the baseline holds stale entries "
                        "no longer reported (fixed debt must be removed "
                        "from the baseline, not left to absorb the next "
                        "regression), when a baseline entry was recorded "
                        "under a since-edited rule implementation "
                        "(rule-hash mismatch), or when a sync-ok waiver "
                        "no longer suppresses anything")
    p.add_argument("--report-only", action="store_true",
                   help="print findings but always exit 0 (except on "
                        "internal errors) — coverage mode for paths "
                        "outside the enforced set")
    p.add_argument("--write-baseline", action="store_true",
                   help="record current findings as the accepted baseline "
                        "and exit 0")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule codes to run "
                        "(e.g. QT001,QT003)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings (text format)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.code}  {rule.name}: {rule.description}")
        return 0

    root = Path(args.root).resolve() if args.root else Path.cwd()
    config = LintConfig()
    if args.rules:
        config.rules = tuple(
            c.strip().upper() for c in args.rules.split(",") if c.strip())

    result = analyze_paths(args.paths, config=config, root=root)
    for err in result.errors:
        print(f"quiverlint: error: {err}", file=sys.stderr)

    baseline_path = (Path(args.baseline) if args.baseline
                     else root / baseline_mod.DEFAULT_BASELINE_NAME)
    if args.write_baseline:
        baseline_mod.save(baseline_path, result.findings)
        print(f"quiverlint: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    known = []
    new = result.findings
    stale = []
    mismatched = []
    if not args.no_baseline and baseline_path.exists():
        try:
            entries = baseline_mod.load_entries(baseline_path)
        except (ValueError, json.JSONDecodeError, OSError) as e:
            print(f"quiverlint: error: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        accepted = [f for f, _ in entries]
        new, known = baseline_mod.partition(result.findings, accepted)
        if args.strict_baseline:
            from .rules import rule_fingerprints

            stale = baseline_mod.stale(result.findings, accepted)
            mismatched = baseline_mod.hash_mismatches(
                entries, rule_fingerprints())
    stale_sync = result.stale_sync_ok if args.strict_baseline else []

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in known],
            "suppressed": [f.to_dict() for f in result.suppressed],
            "stale": [f.to_dict() for f in stale],
            "rule_hash_mismatch": [
                dict(f.to_dict(), recorded_hash=h, current_hash=cur)
                for f, h, cur in mismatched],
            "stale_sync_ok": [
                {"path": p, "line": ln, "reason": r}
                for p, ln, r in stale_sync],
            "files": result.files,
            "errors": result.errors,
        }, indent=2))
    else:
        for f in new:
            print(f.format())
            if f.snippet:
                print(f"    {f.snippet}")
        for f in stale:
            print(f"stale baseline entry (no longer reported): "
                  f"{f.rule} {f.path} [{f.scope}] {f.snippet!r}")
        for f, h, cur in mismatched:
            print(f"baseline entry recorded under edited rule logic: "
                  f"{f.rule} {f.path} [{f.scope}] (recorded {h}, "
                  f"current {cur}) — re-record the baseline")
        for p, ln, r in stale_sync:
            print(f"stale sync-ok waiver (suppresses nothing): "
                  f"{p}:{ln} [{r}]")
        if args.show_suppressed:
            for f in result.suppressed:
                print(f"suppressed: {f.format()}")
        print(f"quiverlint: {len(new)} new finding(s), "
              f"{len(known)} baselined, {len(result.suppressed)} "
              f"suppressed across {result.files} file(s)"
              + (f", {len(stale)} stale baseline entr"
                 f"{'y' if len(stale) == 1 else 'ies'}"
                 f", {len(mismatched)} rule-hash mismatch(es)"
                 f", {len(stale_sync)} stale sync-ok waiver(s)"
                 if args.strict_baseline else ""))

    if result.errors:
        return 2
    if args.report_only:
        return 0
    return 1 if (new or stale or mismatched or stale_sync) else 0
