"""quiverlint CLI — ``python -m quiver_tpu.analysis [paths...]``.

Exit codes: 0 = clean (no unsuppressed, un-baselined findings),
1 = new findings, 2 = usage or internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from . import baseline as baseline_mod
from .core import LintConfig, analyze_paths
from .rules import all_rules

__all__ = ["main", "build_parser"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m quiver_tpu.analysis",
        description="quiverlint: TPU hot-path static analysis "
                    "(QT001 host sync, QT002 retrace hazards, QT003 lock "
                    "discipline, QT004 import layering, QT005 hygiene)",
    )
    p.add_argument("paths", nargs="*", default=["quiver_tpu"],
                   help="files or directories to lint "
                        "(default: quiver_tpu)")
    p.add_argument("--root", default=None,
                   help="directory findings are reported relative to "
                        "(default: CWD); baseline paths anchor here")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: <root>/"
                        f"{baseline_mod.DEFAULT_BASELINE_NAME} if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--strict-baseline", action="store_true",
                   help="also fail when the baseline holds stale entries "
                        "no longer reported (fixed debt must be removed "
                        "from the baseline, not left to absorb the next "
                        "regression)")
    p.add_argument("--write-baseline", action="store_true",
                   help="record current findings as the accepted baseline "
                        "and exit 0")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule codes to run "
                        "(e.g. QT001,QT003)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings (text format)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.code}  {rule.name}: {rule.description}")
        return 0

    root = Path(args.root).resolve() if args.root else Path.cwd()
    config = LintConfig()
    if args.rules:
        config.rules = tuple(
            c.strip().upper() for c in args.rules.split(",") if c.strip())

    result = analyze_paths(args.paths, config=config, root=root)
    for err in result.errors:
        print(f"quiverlint: error: {err}", file=sys.stderr)

    baseline_path = (Path(args.baseline) if args.baseline
                     else root / baseline_mod.DEFAULT_BASELINE_NAME)
    if args.write_baseline:
        baseline_mod.save(baseline_path, result.findings)
        print(f"quiverlint: wrote {len(result.findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    known = []
    new = result.findings
    stale = []
    if not args.no_baseline and baseline_path.exists():
        try:
            accepted = baseline_mod.load(baseline_path)
        except (ValueError, json.JSONDecodeError, OSError) as e:
            print(f"quiverlint: error: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        new, known = baseline_mod.partition(result.findings, accepted)
        if args.strict_baseline:
            stale = baseline_mod.stale(result.findings, accepted)

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in known],
            "suppressed": [f.to_dict() for f in result.suppressed],
            "stale": [f.to_dict() for f in stale],
            "files": result.files,
            "errors": result.errors,
        }, indent=2))
    else:
        for f in new:
            print(f.format())
            if f.snippet:
                print(f"    {f.snippet}")
        for f in stale:
            print(f"stale baseline entry (no longer reported): "
                  f"{f.rule} {f.path} [{f.scope}] {f.snippet!r}")
        if args.show_suppressed:
            for f in result.suppressed:
                print(f"suppressed: {f.format()}")
        print(f"quiverlint: {len(new)} new finding(s), "
              f"{len(known)} baselined, {len(result.suppressed)} "
              f"suppressed across {result.files} file(s)"
              + (f", {len(stale)} stale baseline entr"
                 f"{'y' if len(stale) == 1 else 'ies'}"
                 if args.strict_baseline else ""))

    if result.errors:
        return 2
    return 1 if (new or stale) else 0
