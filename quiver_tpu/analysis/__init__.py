"""quiverlint — static analysis for the TPU hot-path contract.

Rule catalogue (see ``docs/STATIC_ANALYSIS.md`` for the full write-up):

  QT001  host-sync-in-hot-path   device_get / block_until_ready / host
                                 casts of device values in hot modules
  QT002  retrace-hazard          jit patterns that defeat the executable
                                 cache (fresh lambdas, jit in loops,
                                 shape-affecting traced params, mutable
                                 self capture)
  QT003  lock-discipline         _guarded_by-declared attributes mutated
                                 outside their lock
  QT004  import-layering         import-time dependency on the telemetry
                                 HTTP exporter from library modules
  QT005  library-hygiene         mutable default args, bare except:

Programmatic use::

    from quiver_tpu.analysis import analyze_paths, LintConfig
    result = analyze_paths(["quiver_tpu"], root=repo_root)

Runtime companion: :mod:`quiver_tpu.analysis.retrace_guard` is a pytest
plugin enforcing ``@pytest.mark.retrace_budget(n)`` (it is NOT imported
here — it needs pytest, and the linter must stay stdlib-only).
"""

from .baseline import DEFAULT_BASELINE_NAME
from .core import Finding, LintConfig, LintResult, analyze_paths
from .rules import RULE_CLASSES, all_rules

__all__ = [
    "Finding", "LintConfig", "LintResult", "analyze_paths",
    "all_rules", "RULE_CLASSES", "DEFAULT_BASELINE_NAME",
]
