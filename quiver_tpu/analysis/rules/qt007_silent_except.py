"""QT007 — pipeline threads must not swallow exceptions silently.

The serving and prefetch pipelines are built from daemon threads
(``_worker`` / ``_loop`` / ``worker`` drain functions) whose broad
``except Exception`` blocks are load-bearing: they are what keeps one
malformed payload from killing a stream for every later request.  The
flip side is that a broad handler which merely ``pass``es turns a crash
into a silent wedge — the thread survives but the failure reaches no
metric, no flight record, and no caller.  PR 1's telemetry can only
observe what the handler bothers to report.

This rule pins that contract.  In **hot modules**, a broad except
handler (bare ``except:``, ``except Exception``, ``except
BaseException``) lexically inside a thread-loop-named function
(``*_loop``, ``*_worker``, ``run``, …) must do at least one of:

  * **re-raise** — any ``raise`` in the handler body;
  * **record** — call into ``telemetry`` / ``flightrec`` / ``logging``
    / ``warnings`` (or a ``logger.error(...)``-style method);
  * **forward** — pass the bound exception object to *some* call
    (``self._reject(item, e)``, ``results.put((e, "error"))``,
    ``exc.append(e)``): the object goes somewhere a consumer can
    surface it.

Narrow handlers (``except queue.Empty``) are control flow, not error
swallowing, and are never flagged.  Functions outside the thread-loop
naming convention are left to ordinary review — the rule targets the
long-lived drain loops where a swallowed exception has no caller left
to notice.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from ..core import Finding, ModuleContext, Rule, dotted_call_name

# long-lived drain functions: the last qualname segment decides
_LOOP_FN = re.compile(r"(^|_)(loop|worker|run|serve)$")

_BROAD = {"Exception", "BaseException"}

# calls through these names count as recording the failure
_RECORDING_NAMES = {"telemetry", "flightrec", "logging", "warnings",
                    "log", "logger"}
# logger-style method names (logger.error(...), LOG.exception(...))
_RECORDING_METHODS = {"debug", "info", "warning", "warn", "error",
                      "exception", "critical"}


def _is_broad(expr: Optional[ast.AST]) -> bool:
    if expr is None:  # bare `except:`
        return True
    if isinstance(expr, ast.Tuple):
        return any(_is_broad(e) for e in expr.elts)
    if isinstance(expr, ast.Name):
        return expr.id in _BROAD
    if isinstance(expr, ast.Attribute):  # builtins.Exception
        return expr.attr in _BROAD
    return False


def _is_recording_call(node: ast.Call) -> bool:
    dotted = dotted_call_name(node.func)
    if dotted is None:
        return False
    parts = dotted.split(".")
    if any(p in _RECORDING_NAMES for p in parts):
        return True
    return len(parts) >= 2 and parts[-1] in _RECORDING_METHODS


def _forwards_exception(node: ast.Call, bound: Optional[str]) -> bool:
    if bound is None:
        return False
    for arg in [*node.args, *(kw.value for kw in node.keywords)]:
        for sub in ast.walk(arg):
            if (isinstance(sub, ast.Name) and sub.id == bound
                    and isinstance(sub.ctx, ast.Load)):
                return True
    return False


class SilentExceptRule(Rule):
    code = "QT007"
    name = "silent-pipeline-except"
    description = ("broad except blocks in pipeline threads must "
                   "re-raise, record to telemetry/flightrec/logging, "
                   "or forward the exception object")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.is_hot():
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _is_broad(handler.type):
                    continue
                scope = ctx.scope_of(handler)
                if not _LOOP_FN.search(scope.split(".")[-1]):
                    continue
                if self._records(handler):
                    continue
                caught = ("bare except" if handler.type is None
                          else ast.unparse(handler.type))
                yield ctx.finding(
                    self.code, handler,
                    f"broad handler ({caught}) in pipeline thread "
                    f"function swallows the failure: re-raise, record "
                    f"it (telemetry/flightrec/logging), or forward the "
                    f"exception object to a consumer")

    @staticmethod
    def _records(handler: ast.ExceptHandler) -> bool:
        bound = handler.name
        for stmt in handler.body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Raise):
                    return True
                if isinstance(node, ast.Call) and (
                        _is_recording_call(node)
                        or _forwards_exception(node, bound)):
                    return True
        return False
