"""QT008 — data-race candidates via whole-program root attribution.

QT003 checks that *declared* guarded attributes are mutated under their
lock, lexically, through ``self``.  It cannot see the two failure modes
that actually bite a multi-threaded serving stack:

1. shared state that was **never declared** — an attribute written from
   two different thread roots with no common lock;
2. **cross-object** mutation of a declared attribute
   (``graph._base = ...`` from the compactor) — invisible to a
   self-only lexical rule even when a ``_guarded_by`` contract exists.

This rule reads the :class:`~..concurrency.program.Program` model:

* every function is attributed to the thread roots that reach it over
  the interprocedural call graph ("main" is the synthetic root for
  public entry points; ``threading.Thread(target=...)``, ``Thread``
  subclasses overriding ``run``, and ``pool.submit(fn)`` each seed one);
* an access's lock-held set is its lexical ``with`` nest plus the
  *must-hold* entry set propagated from every call site.

**Undeclared attribute**: flagged when it is written outside the owning
class's ``__init__`` (or a ``@classmethod`` constructor), the union of
roots over all its accesses spans ≥ 2 roots, and no single lock is held
at every write.  Reads are deliberately not required to hold the lock —
the codebase sanctions double-checked reads (same policy as QT003) —
but they *do* count for root attribution, so a worker-side reader of a
main-side unlocked write is flagged.

**Declared attribute**: any write through a non-``self`` receiver must
hold the declared lock (interprocedural context counts); ``self``
writes stay QT003's job so each site is reported exactly once.

One finding per (class, attribute) at the first offending write keeps
baselines and suppressions stable.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Sequence

from ..concurrency import build_program
from ..concurrency.program import MAIN_ROOT, Access
from ..core import Finding, ModuleContext, ProgramRule


class DataRaceRule(ProgramRule):
    code = "QT008"
    name = "data-race-candidate"
    description = ("instance/module state written from >=2 thread roots "
                   "with no common lock (call-graph lock-held context)")

    def check_program(self, ctxs: Sequence[ModuleContext],
                      ) -> Iterator[Finding]:
        prog = build_program(ctxs)
        by_attr: Dict[tuple, List[Access]] = {}
        for acc in prog.accesses:
            by_attr.setdefault((acc.owner, acc.attr), []).append(acc)

        for (owner, attr), accs in sorted(by_attr.items()):
            cls = prog.classes.get(owner)
            if cls is not None and (prog.lock_kind(owner, attr)
                                    or prog.is_sync_attr(owner, attr)):
                continue  # the lock itself, or an Event/Queue-style
                          # internally-synchronized primitive
            guarded = prog.guarded_map(owner) if cls is not None else {}
            if attr in guarded:
                yield from self._check_declared(
                    prog, owner, attr, guarded[attr], accs)
                continue
            yield from self._check_undeclared(prog, owner, attr, accs)

        yield from self._check_requires(prog)

    # -- requires-lock call-site verification --------------------------
    def _check_requires(self, prog) -> Iterator[Finding]:
        """The body of a ``# quiverlint: requires-lock[X._l]`` function
        trusts its directive; this closes the loop by checking every
        resolved call site actually holds the named lock."""
        for e in sorted(prog.call_edges,
                        key=lambda e: (e.caller,
                                       getattr(e.node, "lineno", 0))):
            req = prog.requires.get(e.callee)
            if not req or e.indirect:
                continue
            caller_must = prog.entry_must.get(e.caller) or frozenset()
            held = e.locks | caller_must
            callee = prog.functions[e.callee]
            caller = prog.functions.get(e.caller)
            if caller is None:
                continue
            for lock in sorted(req - held, key=lambda l: l.label):
                ctx = caller.ctx
                node = e.node
                yield Finding(
                    rule=self.code, path=ctx.relpath,
                    line=getattr(node, "lineno", 1),
                    col=getattr(node, "col_offset", 0),
                    scope=ctx.scope_of(node),
                    message=(f"call into `{callee.qual}` (requires-lock "
                             f"`{lock.label}`) without holding "
                             f"`{lock.label}` at the call site"),
                    snippet=ctx.snippet(getattr(node, "lineno", 1)))

    # -- declared contract, cross-object writes ------------------------
    def _check_declared(self, prog, owner, attr, lockname, accs,
                        ) -> Iterator[Finding]:
        for acc in accs:
            if not acc.write or acc.via_self or acc.in_init:
                continue
            held = prog.held_at(acc)
            if any(l.owner == owner and l.attr == lockname for l in held):
                continue
            short = owner.rsplit(":", 1)[-1]
            yield self._finding(
                acc,
                f"`{short}.{attr}` is _guarded_by `{lockname}` but is "
                f"written through a non-self reference without holding "
                f"`{short}.{lockname}`")

    # -- undeclared shared state ---------------------------------------
    def _check_undeclared(self, prog, owner, attr, accs,
                          ) -> Iterator[Finding]:
        writes = [a for a in accs if a.write and not a.in_init]
        if not writes:
            return
        roots = set()
        for acc in accs:
            if not acc.in_init:
                roots |= prog.roots_of.get(acc.func.key, set())
        if len(roots) < 2:
            return
        common = None
        for w in writes:
            held = prog.held_at(w)
            common = held if common is None else (common & held)
            if not common:
                break
        if common:
            return  # every write holds one shared lock
        first = min(writes, key=lambda a: (a.func.ctx.relpath,
                                           a.node.lineno))
        short = owner.rsplit(":", 1)[-1]
        names = sorted(prog.root_labels.get(r, r) for r in roots)
        kind = "attribute" if owner in prog.classes else "module global"
        yield self._finding(
            first,
            f"`{short}.{attr}` ({kind}) is accessed from {len(roots)} "
            f"thread roots ({', '.join(names)}) but its writes share no "
            f"common lock — declare it in _guarded_by and guard the "
            f"writes")

    @staticmethod
    def _finding(acc: Access, message: str) -> Finding:
        ctx = acc.func.ctx
        node = acc.node
        return Finding(
            rule=DataRaceRule.code, path=ctx.relpath, line=node.lineno,
            col=node.col_offset, scope=ctx.scope_of(node),
            message=message, snippet=ctx.snippet(node.lineno))
