"""QT004 — import layering: hot paths must not import the exporter stack.

``quiver_tpu.telemetry.export`` pulls in ``http.server``; a module-level
import anywhere in the library would make every sampler/feature/serving
import pay for (and depend on) the HTTP stack, and would couple the data
plane to the observability plane.  The endpoint is opt-in at call time
(``InferenceServer.expose_metrics``) via a function-local import.

This generalizes PR 1's ad-hoc subprocess test
(``test_hot_paths_never_import_http_exporter``) into a static rule over
the whole package: any *import-time* import (module level, or class
body — both execute on import) of a forbidden module is a finding;
function-local lazy imports are the sanctioned pattern and pass.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..core import Finding, ModuleContext, Rule, _match_any


def _function_spans(tree: ast.AST) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _resolve_from(node: ast.ImportFrom, module: str) -> Optional[str]:
    """Absolute dotted module for a possibly-relative ``from X import``."""
    if node.level == 0:
        return node.module
    parts = module.split(".")
    # for a module (not a package __init__), level-1 is its package
    base = parts[: len(parts) - node.level] if len(parts) >= node.level \
        else []
    if node.module:
        base = base + [node.module]
    return ".".join(base) if base else None


def _forbidden(name: Optional[str], forbidden: Tuple[str, ...]) -> bool:
    if not name:
        return False
    return any(name == f or name.startswith(f + ".") for f in forbidden)


class ImportLayeringRule(Rule):
    code = "QT004"
    name = "import-layering"
    description = ("library modules must not import the telemetry HTTP "
                   "exporter (or http.server) at import time; use a "
                   "function-local import at the opt-in call site")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if _match_any(ctx.relpath, ctx.config.layering_exempt):
            return
        forb = ctx.config.layering_forbidden
        inside_fn = set()
        for fn in _function_spans(ctx.tree):
            for sub in ast.walk(fn):
                inside_fn.add(id(sub))
        for node in ast.walk(ctx.tree):
            if id(node) in inside_fn:
                continue
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if _forbidden(alias.name, forb):
                        yield ctx.finding(
                            self.code, node,
                            f"import-time import of `{alias.name}` from a "
                            "library module; import it inside the opt-in "
                            "function instead")
            elif isinstance(node, ast.ImportFrom):
                base = _resolve_from(node, ctx.module)
                if _forbidden(base, forb):
                    yield ctx.finding(
                        self.code, node,
                        f"import-time import from `{base}` in a library "
                        "module; import it inside the opt-in function "
                        "instead")
                    continue
                for alias in node.names:
                    full = f"{base}.{alias.name}" if base else alias.name
                    if _forbidden(full, forb):
                        yield ctx.finding(
                            self.code, node,
                            f"import-time import of `{full}` in a library "
                            "module; import it inside the opt-in function "
                            "instead")
