"""QT015 — collective discipline inside ``shard_map`` / ``pmap`` bodies.

The mesh tier's correctness story rests on three structural facts
(docs/SHARDING.md):

  1. every collective names an axis the enclosing :class:`Mesh`
     actually declares — a typo'd axis name surfaces at trace time at
     best, or silently binds a different mesh dimension at worst;
  2. the halo combines are *bit-exact*: cross-shard reductions of
     float payloads use the ``pmax``-sentinel formulation, never
     ``psum`` (float addition is order-sensitive across shard
     layouts); ``psum`` is reserved for integer counts;
  3. one executable serves all N shards — a collective whose operand
     shape is data-dependent per shard (boolean-mask subscripts,
     ``nonzero`` / ``unique``) breaks SPMD shape agreement.

QT015 checks all three statically.  It finds every ``shard_map`` /
``pmap`` call site, resolves the body callable through PR 7's
:class:`Program`, and walks the body's collectives
(``jax.lax.psum`` / ``pmax`` / ... ).  Axis-name operands resolve
through locals, closures, constructor-frozen ``self`` attributes and
cross-module constants (``SHARD_AXIS``); declared axes are harvested
from every ``Mesh(...)`` / ``make_mesh(...)`` construction in the
program.  The float-``psum`` check applies only inside
``LintConfig.bitexact_modules`` (default: the mesh tier), where a
``psum`` operand must be *provably integer* — an ``.astype(jnp.int32)``,
an integer literal, a comparison, or a composition of those.

Unresolvable axis names or operands are skipped, not flagged: this
rule's findings must each be actionable.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..core import Finding, ModuleContext, ProgramRule, dotted_call_name

_COLLECTIVES = {
    "psum", "pmax", "pmin", "pmean", "all_gather", "ppermute",
    "axis_index", "all_to_all", "psum_scatter", "pshuffle",
}
# collectives taking a reduced operand whose shape must agree per shard
_REDUCING = _COLLECTIVES - {"axis_index"}
_WRAPPERS = {"shard_map", "pmap"}
_INT_PREFIXES = ("int", "uint", "bool")
_SHAPE_POLYMORPHIC = {"nonzero", "unique", "flatnonzero", "argwhere"}


def _leaf(dotted: Optional[str]) -> Optional[str]:
    return dotted.split(".")[-1] if dotted else None


class CollectiveDisciplineRule(ProgramRule):
    code = "QT015"
    name = "collective-discipline"
    description = ("shard_map/pmap body collectives: undeclared axis "
                   "names, float psum in bit-exactness-contract modules, "
                   "per-shard data-dependent operand shapes")

    def check_program(self, ctxs: Sequence[ModuleContext],
                      ) -> Iterator[Finding]:
        from ..concurrency import build_program

        prog = build_program(ctxs)
        axes = _declared_axes(prog, ctxs)
        bitexact = tuple(getattr(ctxs[0].config, "bitexact_modules", ())
                         if ctxs else ())

        bodies: List = []          # FuncInfo of each collective body
        seen: Set[str] = set()
        for fi in prog.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                if _leaf(dotted_call_name(node.func)) not in _WRAPPERS:
                    continue
                callee = prog.resolve_callable(fi, node.args[0])
                if callee is None or callee in seen:
                    continue
                body = prog.functions.get(callee)
                if body is not None:
                    seen.add(callee)
                    bodies.append(body)

        for body in bodies:
            hot = _match_any(body.ctx.relpath, bitexact)
            for node in ast.walk(body.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = dotted_call_name(node.func)
                leaf = _leaf(dotted)
                if leaf not in _COLLECTIVES or not dotted or \
                        "lax" not in dotted.split("."):
                    continue
                yield from self._check_axis(prog, body, node, leaf, axes)
                if leaf == "psum" and hot and node.args:
                    yield from self._check_psum(prog, body, node)
                if leaf in _REDUCING and node.args:
                    yield from self._check_shape(body, node, leaf)

    # -- axis names ------------------------------------------------------

    def _check_axis(self, prog, body, node: ast.Call, leaf: str,
                    axes: Set[str]) -> Iterator[Finding]:
        if not axes:
            return      # no Mesh declared anywhere in the linted set
        axis_expr: Optional[ast.AST] = None
        if len(node.args) > 1:
            axis_expr = node.args[1]
        elif leaf == "axis_index" and node.args:
            axis_expr = node.args[0]
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                axis_expr = kw.value
        if axis_expr is None:
            return
        for name in _axis_strings(prog, body, axis_expr):
            if name not in axes:
                yield body.ctx.finding(
                    self.code, node,
                    f"collective `{leaf}` names axis '{name}' but no "
                    f"Mesh in the program declares it (declared: "
                    f"{', '.join(sorted(axes))})")

    # -- bit-exactness ---------------------------------------------------

    def _check_psum(self, prog, body, node: ast.Call) -> Iterator[Finding]:
        operand = node.args[0]
        if _provably_int(body, operand, set()):
            return
        yield body.ctx.finding(
            self.code, node,
            f"`psum` over `{ast.unparse(operand)}` in a bit-exactness-"
            f"contract module: float psum is reduction-order-sensitive "
            f"across shard layouts — use the pmax-sentinel combine for "
            f"payloads, or make integer counts provable with "
            f"`.astype(jnp.int32)`")

    # -- shape agreement -------------------------------------------------

    def _check_shape(self, body, node: ast.Call,
                     leaf: str) -> Iterator[Finding]:
        operand = node.args[0]
        reason = _shape_data_dependent(body, operand)
        if reason:
            yield body.ctx.finding(
                self.code, node,
                f"`{leaf}` operand `{ast.unparse(operand)}` has a "
                f"data-dependent per-shard shape ({reason}) — SPMD "
                f"collectives need every shard to present the same "
                f"shape; pad to a static bucket first")


# ---------------------------------------------------------------------------
# declared axes: every Mesh(...) / make_mesh(...) construction

_MESH_CTORS = {"Mesh", "make_mesh", "build_mesh"}


def _declared_axes(prog, ctxs: Sequence[ModuleContext]) -> Set[str]:
    axes: Set[str] = set()
    for fi in prog.functions.values():
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if _leaf(dotted_call_name(node.func)) not in _MESH_CTORS:
                continue
            exprs = list(node.args) + [kw.value for kw in node.keywords
                                       if kw.arg in ("axis_names", None)]
            for e in exprs:
                axes.update(_axis_strings(prog, fi, e))
    # module-level Mesh constructions (rare but legal)
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and _leaf(dotted_call_name(node.func)) in _MESH_CTORS):
                for e in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    for sub in ast.walk(e):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str):
                            axes.add(sub.value)
    return axes


def _axis_strings(prog, fi, expr: ast.AST,
                  depth: int = 0) -> Iterator[str]:
    """Every axis-name string ``expr`` can denote, resolved through
    locals, closures, ctor-frozen self attributes and module constants.
    Yields nothing when unresolvable (callers must skip, not flag)."""
    if depth > 8:
        return
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, str):
            yield expr.value
        return
    if isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            yield from _axis_strings(prog, fi, e, depth + 1)
        return
    if isinstance(expr, ast.Name):
        f = fi
        while f is not None:
            for v in _local_values(f, expr.id):
                yield from _axis_strings(prog, f, v, depth + 1)
                return
            f = getattr(f, "parent", None)
        yield from _module_const_strings(prog, fi.ctx, expr.id)
        return
    if isinstance(expr, ast.Attribute):
        recv_cls = None
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and fi.cls is not None:
            recv_cls = fi.cls.key
        else:
            recv_cls = prog.receiver_class(fi, expr.value)
        if recv_cls is not None:
            for ci in prog._mro(recv_cls):
                for m in ci.methods.values():
                    for node in ast.walk(m.node):
                        if not isinstance(node, ast.Assign):
                            continue
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                    and t.attr == expr.attr):
                                yield from _axis_strings(
                                    prog, m, node.value, depth + 1)
        return


def _local_values(fi, name: str) -> Iterator[ast.AST]:
    from ..staging.dataflow import ordered_nodes

    for node in ordered_nodes(fi.node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    yield node.value


def _module_const_strings(prog, ctx, name: str) -> Iterator[str]:
    mod = prog.modules.get(ctx.module)
    if mod is not None and name in mod.from_names:
        m, a = mod.from_names[name]
        target = prog.modules.get(m) or prog.modules.get(f"{m}.{a}")
        if target is not None:
            yield from _module_body_strings(target.ctx, a)
            return
    yield from _module_body_strings(ctx, name)


def _module_body_strings(ctx, name: str) -> Iterator[str]:
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id == name \
                        and isinstance(stmt.value, ast.Constant) \
                        and isinstance(stmt.value.value, str):
                    yield stmt.value.value


# ---------------------------------------------------------------------------
# provably-integer operands

def _provably_int(fi, expr: ast.AST, visited: Set[str]) -> bool:
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, (int, bool)) \
            and not isinstance(expr.value, float)
    if isinstance(expr, ast.Compare):
        return True                                # bool array
    if isinstance(expr, ast.BoolOp):
        return all(_provably_int(fi, v, visited) for v in expr.values)
    if isinstance(expr, ast.BinOp):
        return (_provably_int(fi, expr.left, visited)
                and _provably_int(fi, expr.right, visited))
    if isinstance(expr, ast.UnaryOp):
        return _provably_int(fi, expr.operand, visited)
    if isinstance(expr, ast.Subscript):
        return _provably_int(fi, expr.value, visited)
    if isinstance(expr, ast.Call):
        # .astype(jnp.int32) on any receiver, including subscripts the
        # dotted-name walk can't cross
        if (isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "astype" and expr.args):
            return _int_dtype(expr.args[0])
        dotted = dotted_call_name(expr.func)
        leaf = _leaf(dotted)
        if dotted and dotted.startswith(("jnp.", "np.", "jax.numpy.")):
            if leaf and leaf.startswith(_INT_PREFIXES):
                return True                        # jnp.int32(x) etc.
            for kw in expr.keywords:
                if kw.arg == "dtype" and _int_dtype(kw.value):
                    return True
            if leaf == "where" and len(expr.args) == 3:
                return (_provably_int(fi, expr.args[1], visited)
                        and _provably_int(fi, expr.args[2], visited))
            if leaf in ("sum", "count_nonzero", "argmax", "argmin",
                        "searchsorted", "arange", "argsort") \
                    and expr.args:
                if leaf == "sum":
                    return _provably_int(fi, expr.args[0], visited)
                return leaf != "arange" or all(
                    _provably_int(fi, a, visited) for a in expr.args)
        return False
    if isinstance(expr, ast.Name):
        if expr.id in visited:
            return False
        visited.add(expr.id)
        f = fi
        while f is not None:
            vals = list(_local_values(f, expr.id))
            if vals:
                return all(_provably_int(f, v, visited) for v in vals)
            f = getattr(f, "parent", None)
        return False
    return False


def _int_dtype(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value.startswith(_INT_PREFIXES)
    dotted = dotted_call_name(expr)
    leaf = _leaf(dotted)
    return bool(leaf) and leaf.startswith(_INT_PREFIXES)


# ---------------------------------------------------------------------------
# per-shard shape dependence

def _shape_data_dependent(fi, operand: ast.AST) -> Optional[str]:
    for sub in ast.walk(operand):
        if isinstance(sub, ast.Call):
            leaf = _leaf(dotted_call_name(sub.func))
            if leaf in _SHAPE_POLYMORPHIC:
                return f"`{leaf}()` yields a data-dependent length"
            if leaf == "where" and len(sub.args) == 1:
                return "single-argument `where()` yields a " \
                       "data-dependent length"
        if isinstance(sub, ast.Subscript) and _is_mask_slice(fi,
                                                            sub.slice):
            return "boolean-mask subscript selects a data-dependent " \
                   "row count"
    return None


def _is_mask_slice(fi, sl: ast.AST) -> bool:
    if isinstance(sl, ast.Compare):
        return True
    if isinstance(sl, ast.Name):
        f = fi
        while f is not None:
            for v in _local_values(f, sl.id):
                return isinstance(v, ast.Compare)
            f = getattr(f, "parent", None)
    return False


def _match_any(relpath: str, patterns: Tuple[str, ...]) -> bool:
    return any(fnmatch.fnmatch(relpath, p) for p in patterns)
