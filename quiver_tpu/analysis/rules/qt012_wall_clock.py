"""QT012 — wall-clock duration measurement in a hot path.

``time.time()`` is the WALL clock: NTP slews and steps it, a suspended
VM jumps it, and leap-second smears bend it — a duration computed from
it can come out negative or wildly wrong, and those durations feed the
latency histograms, the QoS ladder's burn rates, and the perf gate.
Durations in hot modules must come from ``time.perf_counter()`` (or
``time.monotonic()`` for coarse deadlines).

``time.time()`` stays legitimate as a *timestamp* (log records,
``t_wall`` fields, absolute deadlines built by addition): the rule
flags only its use in a subtraction — the duration idiom — either
directly (``time.time() - t0``) or through a name assigned from it in
the same function (``t0 = time.time(); ...; now - t0``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import Finding, ModuleContext, Rule, dotted_call_name

_WALL_CALLS = {"time.time"}


def _imports_bare_time(tree: ast.AST) -> bool:
    """True when ``from time import time`` is in scope, so a bare
    ``time()`` call is the wall clock too."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time" and alias.asname is None:
                    return True
    return False


def _is_wall_call(node: ast.AST, bare: bool) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_call_name(node.func)
    return name in _WALL_CALLS or (bare and name == "time")


def _wall_names(fn: ast.AST, bare: bool) -> Set[str]:
    """Names assigned (directly) from a wall-clock call in ``fn``."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_wall_call(node.value, bare):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class WallClockRule(Rule):
    code = "QT012"
    name = "wall-clock-in-hot-path"
    description = ("time.time() used to measure a duration in a hot "
                   "module (use time.perf_counter())")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.is_hot():
            return
        bare = _imports_bare_time(ctx.tree)
        seen: Set[int] = set()  # nested defs appear under two quals
        for qual, fn in ctx.functions:
            names = None  # computed lazily: most functions are clean
            for node in ast.walk(fn):
                if (not isinstance(node, ast.BinOp)
                        or not isinstance(node.op, ast.Sub)
                        or id(node) in seen):
                    continue
                sides = (node.left, node.right)
                direct = any(_is_wall_call(s, bare) for s in sides)
                if not direct:
                    if names is None:
                        names = _wall_names(fn, bare)
                    if not any(isinstance(s, ast.Name) and s.id in names
                               for s in sides):
                        continue
                seen.add(id(node))
                yield ctx.finding(
                    self.code, node,
                    "duration computed from the wall clock "
                    "(`time.time()` subtraction); use "
                    "`time.perf_counter()` — NTP steps make this "
                    "negative or wrong, and it feeds latency metrics",
                    scope=qual)
