"""QT013 — interprocedural host sync.

QT001 is deliberately local: it tracks names assigned from ``jnp.*`` /
``jax.*`` calls *within one function* and flags host casts of those.
The syncs that actually bite in this codebase cross function
boundaries — ``out = self._fused_forward(padded)`` returns a live
device array from three calls away, and the ``np.asarray(out)`` on the
next line is invisible to QT001.  QT013 reads the solved staging
dataflow (:mod:`..staging.dataflow`) instead: any value whose
residency fixpoint is DEVICE *and* whose device-ness originated in a
hot module (the sampler -> gather -> serve pipeline) is flagged at
every coercion point —

  * host casts: ``int()`` / ``float()`` / ``bool()``,
  * materializers: ``np.asarray()`` / ``np.array()``,
  * sync methods: ``.item()`` / ``.tolist()``,
  * implicit bool: ``if x:`` / ``while x:`` / ``not x`` / ``x and y``
    / ``assert x`` — each one compiles to ``bool(x)``, a blocking
    device round-trip jax will happily perform for you.

Intentional syncs at a design boundary (a serving response leaving the
process, a bench harness checksum) carry a written waiver::

    out = np.asarray(dev)  # quiverlint: sync-ok[response boundary]

``sync-ok`` is audited: a waiver that no longer suppresses anything is
*stale* and fails ``--strict-baseline`` (see ``analyze_paths``), so
the escape hatch can't outlive the sync it excused.

Hot modules stay QT001's territory for purely-local flows (a name
assigned from ``jnp.*`` in the same function) so one sync is never
reported twice under two codes.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..core import (
    Finding,
    ModuleContext,
    ProgramRule,
    dotted_call_name,
)

_CASTS = {"int", "float", "bool"}
_MATERIALIZE = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_SYNC_METHODS = {"item", "tolist"}


class InterproceduralHostSyncRule(ProgramRule):
    code = "QT013"
    name = "interprocedural-host-sync"
    description = ("host coercion (cast / np.asarray / .item / implicit "
                   "bool) of a device value that crossed a function "
                   "boundary from a hot-path producer")

    def check_program(self, ctxs: Sequence[ModuleContext],
                      ) -> Iterator[Finding]:
        from ..staging.dataflow import DEVICE, build_dataflow
        from .qt001_host_sync import _is_device_call, _tracked_names

        df = build_dataflow(ctxs)
        out: List[Finding] = []
        seen: Set[Tuple[str, int, int, str]] = set()

        for fi in df.prog.functions.values():
            ctx = fi.ctx
            hot = ctx.is_hot()
            tracked: Optional[Set[str]] = None  # QT001's local set, lazy

            def local_territory(arg: ast.AST) -> bool:
                """True when QT001 already owns this sync (hot module,
                purely local device provenance).  Mirrors QT001's own
                ownership test: any device call or tracked name
                anywhere inside the coerced expression."""
                nonlocal tracked
                if not hot:
                    return False
                if any(_is_device_call(s) for s in ast.walk(arg)):
                    return True
                if tracked is None:
                    tracked = _tracked_names(fi.node)
                return any(isinstance(s, ast.Name) and s.id in tracked
                           for s in ast.walk(arg))

            def emit(node: ast.AST, arg: ast.AST, kind: str, msg: str,
                     env: Dict) -> None:
                v = df.classify(fi, arg, env)
                if v is None or v.cls != DEVICE or not v.hot:
                    return
                if local_territory(arg):
                    return
                key = (ctx.relpath, node.lineno, node.col_offset, kind)
                if key in seen:
                    return
                seen.add(key)
                out.append(ctx.finding(self.code, node, msg))

            def visit(node: ast.AST, env: Dict) -> None:
                if isinstance(node, ast.Call):
                    name = dotted_call_name(node.func)
                    if name in _CASTS and node.args:
                        emit(node, node.args[0], "cast",
                             f"`{name}()` of a device value produced in a "
                             f"hot path forces a blocking device->host "
                             f"sync (crossed a function boundary; waive "
                             f"an intentional boundary with "
                             f"`# quiverlint: sync-ok[reason]`)", env)
                    elif name in _MATERIALIZE and node.args:
                        emit(node, node.args[0], "cast",
                             f"`{name}()` materializes a hot-path device "
                             f"value on host — a full transfer per call "
                             f"(waive an intentional response boundary "
                             f"with `# quiverlint: sync-ok[reason]`)", env)
                    elif (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _SYNC_METHODS
                          and not node.args):
                        emit(node, node.func.value, "cast",
                             f"`.{node.func.attr}()` syncs a hot-path "
                             f"device value to host", env)
                elif isinstance(node, (ast.If, ast.While)):
                    emit(node, node.test, "bool",
                         "implicit bool() of a hot-path device value — "
                         "branching on device data blocks on a transfer; "
                         "hoist the decision to host metadata or shape "
                         "logic", env)
                elif isinstance(node, ast.Assert):
                    emit(node, node.test, "bool",
                         "assert on a hot-path device value forces an "
                         "implicit bool() sync", env)
                elif (isinstance(node, ast.UnaryOp)
                      and isinstance(node.op, ast.Not)):
                    emit(node, node.operand, "bool",
                         "`not` on a hot-path device value forces an "
                         "implicit bool() sync", env)
                elif isinstance(node, ast.IfExp):
                    emit(node, node.test, "bool",
                         "conditional expression on a hot-path device "
                         "value forces an implicit bool() sync", env)

            df.replay(fi, visit)

        yield from out
