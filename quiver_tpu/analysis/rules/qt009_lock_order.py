"""QT009 — lock-order inversions over the acquisition-order graph.

The program model records every ``with <lock>:`` acquisition together
with the locks already held there — lexically, plus the *may-hold*
entry set propagated through the call graph (an inversion exists if
*any* path nests the pair).  Those pairs form a directed graph over
lock identities (``Class.attr`` / ``module.name``); a cycle is a
deadlock candidate and every strongly connected component with more
than one lock (or a non-reentrant self-edge) is reported once, with the
offending acquisition chain spelled out.

Re-entrant acquisition of an ``RLock``/``Condition`` by design is not
an inversion; re-acquiring a plain ``Lock`` you already hold is an
instant self-deadlock and is flagged even without a second lock.

The runtime complement (``QUIVER_SANITIZE=1``,
:mod:`quiver_tpu.analysis.witness`) checks the same order relation
dynamically and can be pre-seeded with this rule's edges via
:func:`quiver_tpu.analysis.concurrency.canonical_lock_edges`.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence, Tuple

from ..concurrency import build_program
from ..concurrency.program import LockId
from ..core import Finding, ModuleContext, ProgramRule


class LockOrderRule(ProgramRule):
    code = "QT009"
    name = "lock-order-inversion"
    description = ("cyclic lock-acquisition order (deadlock candidate) "
                   "across the call graph; plain-Lock re-entry")

    def check_program(self, ctxs: Sequence[ModuleContext],
                      ) -> Iterator[Finding]:
        prog = build_program(ctxs)
        edges: Dict[Tuple[LockId, LockId], object] = {}
        for held, acquired, acq in prog.order_edges():
            edges.setdefault((held, acquired), acq)

        # self-edges: re-acquiring a non-reentrant Lock
        for (a, b), acq in sorted(
                edges.items(), key=lambda kv: self._sort_key(kv[1])):
            if a == b:
                yield self._finding(
                    acq,
                    f"`{a.label}` is a non-reentrant Lock acquired while "
                    f"already held on this path — instant self-deadlock "
                    f"(use an RLock or restructure the callers)")

        graph: Dict[LockId, List[LockId]] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, []).append(b)
        for cycle in self._cycles(graph):
            chain = []
            for i, lock in enumerate(cycle):
                nxt = cycle[(i + 1) % len(cycle)]
                acq = edges.get((lock, nxt))
                site = self._site(acq) if acq is not None else "?"
                chain.append(f"{lock.label} -> {nxt.label} at {site}")
            rep = edges[(cycle[0], cycle[1 % len(cycle)])]
            yield self._finding(
                rep,
                "lock-order inversion (deadlock candidate): "
                + "; ".join(chain))

    # -- cycle enumeration ---------------------------------------------
    @staticmethod
    def _cycles(graph: Dict[LockId, List[LockId]],
                ) -> List[List[LockId]]:
        """One representative cycle per strongly connected component
        with >= 2 locks (iterative Tarjan, then a path walk)."""
        index: Dict[LockId, int] = {}
        low: Dict[LockId, int] = {}
        on: Dict[LockId, bool] = {}
        stack: List[LockId] = []
        sccs: List[List[LockId]] = []
        counter = [0]

        def strongconnect(root: LockId) -> None:
            work = [(root, iter(graph.get(root, ())))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on[root] = True
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on[w] = True
                        work.append((w, iter(graph.get(w, ()))))
                        advanced = True
                        break
                    elif on.get(w):
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    u = work[-1][0]
                    low[u] = min(low[u], low[v])
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on[w] = False
                        comp.append(w)
                        if w == v:
                            break
                    if len(comp) > 1:
                        sccs.append(comp)

        nodes = sorted(graph, key=lambda l: (l.owner, l.attr))
        for n in nodes:
            if n not in index:
                strongconnect(n)

        cycles = []
        for comp in sccs:
            comp_set = set(comp)
            start = min(comp, key=lambda l: (l.owner, l.attr))
            # walk edges inside the SCC until we loop back to start
            path = [start]
            seen = {start}
            cur = start
            while True:
                nxt = None
                for cand in graph.get(cur, ()):
                    if cand == start and len(path) > 1:
                        nxt = start
                        break
                    if cand in comp_set and cand not in seen:
                        nxt = cand
                        break
                if nxt is None or nxt == start:
                    break
                path.append(nxt)
                seen.add(nxt)
                cur = nxt
            cycles.append(path)
        return cycles

    # -- formatting ----------------------------------------------------
    @staticmethod
    def _site(acq) -> str:
        return (f"{acq.func.ctx.relpath}:{acq.node.lineno} "
                f"({acq.func.qual})")

    @staticmethod
    def _sort_key(acq) -> Tuple[str, int]:
        return (acq.func.ctx.relpath, acq.node.lineno)

    @staticmethod
    def _finding(acq, message: str) -> Finding:
        ctx = acq.func.ctx
        node = acq.node
        return Finding(
            rule=LockOrderRule.code, path=ctx.relpath, line=node.lineno,
            col=node.col_offset, scope=ctx.scope_of(node),
            message=message, snippet=ctx.snippet(node.lineno))
