"""QT014 — unbounded executable-cache key.

Every distinct key inserted into a :class:`ProgramCache`
(``recovery/registry.py``) is one compiled XLA executable held for the
life of the process.  The repo's standing invariant is "0 new
executables steady-state" (``retrace_budget``, ``seal()``), but those
are *runtime* guards: they fire after warmup, on silicon, one blowup at
a time.  QT014 bounds the key's cardinality symbolically at lint time.

For each insertion site (``self._cache[key] = fn`` or
``cache.setdefault(key, fn)`` on an attribute initialised from
``program_cache(...)`` / ``ProgramCache(...)``), the key expression is
decomposed into components and each component must trace — through
locals, parameters (meet over resolved call sites), constructor-only
instance attributes, dataclass/NamedTuple fields (meet over
constructor sites) — to something finite:

  * a literal / bool / comparison,
  * a constructor-frozen config attribute (``self.n_shards``),
  * a bucket helper (``_pow2_bucket`` / ``_fresh_bucket`` /
    ``_fanout_bucket`` / ``_next_bucket`` — extendable via
    ``LintConfig.bucket_helpers``) or any function carrying a
    ``# quiverlint: bucketed[reason]`` directive on its def line,
  * ``len()`` / arithmetic / subscripts of such values.

A component fed by unbucketed runtime data — a raw batch size, a raw
delta count, a float, a tenant string — is a finding, because it is
exactly the retrace blowup ``seal()`` only reports after it happened.
An intentional raw key (a path whose callers all pad upstream) takes a
justified ``# quiverlint: ignore[QT014]`` on the insertion line.

Everything resolves over PR 7's :class:`Program` (call graph, classes)
plus the staging dataflow's instance typing for receiver attributes.
Unresolvable components are conservatively *unbounded*: an opaque key
is precisely the situation the rule exists for.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..core import Finding, ModuleContext, ProgramRule, dotted_call_name

_CACHE_FACTORIES = {"program_cache", "ProgramCache"}
# builtins through which boundedness propagates (result enumerable when
# every argument is)
_TRANSPARENT = {
    "len", "int", "bool", "str", "min", "max", "abs", "round", "tuple",
    "sorted", "frozenset", "hash",
}
# array metadata that is finite per deployment vs. per request
_BOUNDED_ATTRS = {"dtype", "ndim"}

_BUCKETED_RE = re.compile(r"#\s*quiverlint:\s*bucketed\[([^\]]*)\]")


def _has_bucketed_directive(ctx: ModuleContext, node: ast.AST) -> bool:
    """``# quiverlint: bucketed[reason]`` on the def line or the line
    directly above it blesses the function's result as bucketed."""
    for ln in (node.lineno - 1, node.lineno):
        if 1 <= ln <= len(ctx.lines) and _BUCKETED_RE.search(
                ctx.lines[ln - 1]):
            return True
    return False


class UnboundedExecutableKeyRule(ProgramRule):
    code = "QT014"
    name = "unbounded-executable-key"
    description = ("ProgramCache key component fed by unbucketed runtime "
                   "data — every distinct value compiles and retains a "
                   "fresh executable")

    def check_program(self, ctxs: Sequence[ModuleContext],
                      ) -> Iterator[Finding]:
        from ..staging.dataflow import build_dataflow

        df = build_dataflow(ctxs)
        prog = df.prog
        bucket_helpers = set(
            getattr(ctxs[0].config, "bucket_helpers", ()) if ctxs else ())

        # -- pass 1: which attributes hold executable caches ------------
        cache_attrs: Set[Tuple[str, str]] = set()   # (clskey, attr)
        subsystems: Dict[Tuple[str, str], str] = {}
        for fi in prog.functions.values():
            if fi.cls is None:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                name = dotted_call_name(node.value.func)
                if not name or name.split(".")[-1] not in _CACHE_FACTORIES:
                    continue
                for t in node.targets:
                    attr = _self_attr_name(t)
                    if attr:
                        cache_attrs.add((fi.cls.key, attr))
                        if (node.value.args and isinstance(
                                node.value.args[0], ast.Constant)):
                            subsystems[(fi.cls.key, attr)] = str(
                                node.value.args[0].value)

        if not cache_attrs:
            return

        bound = _Boundedness(df, bucket_helpers)

        # -- pass 2: insertion sites -----------------------------------
        for fi in prog.functions.values():
            for node in ast.walk(fi.node):
                key_expr, attr_key = self._insertion(fi, node, cache_attrs,
                                                     prog)
                if key_expr is None:
                    continue
                subsystem = subsystems.get(attr_key, attr_key[1])
                for comp, why in bound.unbounded_components(fi, key_expr):
                    src = _unparse(comp)
                    yield fi.ctx.finding(
                        self.code, node,
                        f"ProgramCache['{subsystem}'] key component "
                        f"`{src}` is not provably bounded ({why}) — every "
                        f"distinct value compiles a fresh executable; "
                        f"bucket it (pow2/quarter-octave helper or a "
                        f"`# quiverlint: bucketed[...]` directive) or "
                        f"justify with ignore[QT014]")

    def _insertion(self, fi, node: ast.AST,
                   cache_attrs: Set[Tuple[str, str]], prog,
                   ) -> Tuple[Optional[ast.AST],
                              Optional[Tuple[str, str]]]:
        """(key expression, cache identity) when ``node`` inserts into a
        known cache, else (None, None)."""
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    ak = self._cache_of(fi, t.value, cache_attrs, prog)
                    if ak is not None:
                        return t.slice, ak
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "setdefault" and node.args):
            ak = self._cache_of(fi, node.func.value, cache_attrs, prog)
            if ak is not None:
                return node.args[0], ak
        return None, None

    def _cache_of(self, fi, recv: ast.AST,
                  cache_attrs: Set[Tuple[str, str]], prog,
                  ) -> Optional[Tuple[str, str]]:
        attr = _self_attr_name(recv)
        if attr is None or fi.cls is None:
            return None
        for ci in prog._mro(fi.cls.key):
            if (ci.key, attr) in cache_attrs:
                return (ci.key, attr)
        return None


def _self_attr_name(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse covers all exprs we see
        return f"<expr at line {getattr(node, 'lineno', '?')}>"


class _Boundedness:
    """Symbolic cardinality check over the program model."""

    def __init__(self, df, bucket_helpers: Set[str]):
        self.df = df
        self.prog = df.prog
        self.bucket_helpers = {
            "_pow2_bucket", "_fresh_bucket", "_fanout_bucket",
            "_next_bucket", "_pow2", "pow2_bucket",
        } | bucket_helpers
        self._assigns: Dict[str, Dict[str, List[ast.AST]]] = {}
        self._callers: Optional[Dict[str, List]] = None
        self._ctor_sites: Optional[Dict[str, List]] = None

    # -- public --------------------------------------------------------

    def unbounded_components(self, fi, key_expr: ast.AST,
                             ) -> Iterator[Tuple[ast.AST, str]]:
        comps = (key_expr.elts if isinstance(key_expr, ast.Tuple)
                 else [key_expr])
        for comp in comps:
            ok, why = self.bounded(fi, comp, set())
            if not ok:
                yield comp, why

    # -- core recursion -------------------------------------------------

    def bounded(self, fi, expr: ast.AST,
                visited: Set) -> Tuple[bool, str]:
        if isinstance(expr, ast.Constant):
            return True, ""
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for e in expr.elts:
                ok, why = self.bounded(fi, e, visited)
                if not ok:
                    return ok, why
            return True, ""
        if isinstance(expr, ast.Compare):
            return True, ""                     # comparisons are bools
        if isinstance(expr, ast.BoolOp):
            for e in expr.values:
                ok, why = self.bounded(fi, e, visited)
                if not ok:
                    return ok, why
            return True, ""
        if isinstance(expr, ast.IfExp):
            ok, why = self.bounded(fi, expr.body, visited)
            if not ok:
                return ok, why
            return self.bounded(fi, expr.orelse, visited)
        if isinstance(expr, ast.BinOp):
            ok, why = self.bounded(fi, expr.left, visited)
            if not ok:
                return ok, why
            return self.bounded(fi, expr.right, visited)
        if isinstance(expr, ast.UnaryOp):
            return self.bounded(fi, expr.operand, visited)
        if isinstance(expr, ast.Subscript):
            # indexing a bounded structure yields a bounded value
            return self.bounded(fi, expr.value, visited)
        if isinstance(expr, ast.JoinedStr):
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    ok, why = self.bounded(fi, v.value, visited)
                    if not ok:
                        return False, f"f-string over {why}"
            return True, ""
        if isinstance(expr, ast.Name):
            return self._bounded_name(fi, expr.id, visited)
        if isinstance(expr, ast.Attribute):
            return self._bounded_attr(fi, expr, visited)
        if isinstance(expr, ast.Call):
            return self._bounded_call(fi, expr, visited)
        return False, f"opaque expression `{_unparse(expr)}`"

    # -- names ----------------------------------------------------------

    def _bounded_name(self, fi, name: str,
                      visited: Set) -> Tuple[bool, str]:
        key = ("name", fi.key, name)
        if key in visited:
            return True, ""                     # cycle: optimistic
        visited.add(key)

        assigns = self._local_assigns(fi).get(name)
        if assigns:
            for value in assigns:
                ok, why = self.bounded(fi, value, visited)
                if not ok:
                    return False, why
            return True, ""
        # enclosing defs (closures)
        f = fi.parent
        while f is not None:
            assigns = self._local_assigns(f).get(name)
            if assigns:
                for value in assigns:
                    ok, why = self.bounded(f, value, visited)
                    if not ok:
                        return False, why
                return True, ""
            f = f.parent
        if self._is_param(fi, name):
            return self._bounded_param(fi, name, visited)
        if self._module_constant(fi.ctx, name):
            return True, ""
        return False, f"`{name}` has no bounded definition in scope"

    def _local_assigns(self, fi) -> Dict[str, List[ast.AST]]:
        from ..staging.dataflow import ordered_nodes

        cached = self._assigns.get(fi.key)
        if cached is not None:
            return cached
        out: Dict[str, List[ast.AST]] = {}
        for node in ordered_nodes(fi.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    self._record_target(out, t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self._record_target(out, node.target, node.value)
            elif isinstance(node, ast.For):
                # element of a bounded iterable is bounded
                self._record_target(out, node.target, node.iter)
            elif isinstance(node, ast.NamedExpr):
                self._record_target(out, node.target, node.value)
        self._assigns[fi.key] = out
        return out

    @staticmethod
    def _record_target(out: Dict[str, List[ast.AST]], target: ast.AST,
                       value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            out.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                _Boundedness._record_target(out, e, value)
        elif isinstance(target, ast.Starred):
            _Boundedness._record_target(out, target.value, value)

    @staticmethod
    def _is_param(fi, name: str) -> bool:
        args = getattr(fi.node, "args", None)
        if args is None:
            return False
        every = (list(args.args) + list(args.kwonlyargs)
                 + list(args.posonlyargs))
        return any(a.arg == name for a in every)

    def _bounded_param(self, fi, name: str,
                       visited: Set) -> Tuple[bool, str]:
        """Meet over every resolved call site's argument."""
        key = ("param", fi.key, name)
        if key in visited:
            return True, ""
        visited.add(key)
        edges = self._caller_edges().get(fi.key, [])
        if not edges:
            return False, (f"parameter `{name}` of {fi.qual} has no "
                           f"resolvable call sites")
        args = fi.node.args
        names = [a.arg for a in args.args]
        offset = 1 if names and names[0] in ("self", "cls") else 0
        try:
            pos = names.index(name) - offset
        except ValueError:
            pos = None
        checked = False
        for caller_fi, call in edges:
            arg_expr = None
            if pos is not None and pos >= 0 and pos < len(call.args):
                a = call.args[pos]
                if not isinstance(a, ast.Starred):
                    arg_expr = a
            for kw in call.keywords:
                if kw.arg == name:
                    arg_expr = kw.value
            if arg_expr is None:
                # defaulted at this site: bounded iff the default is
                d = self._default_for(fi, name)
                if d is None:
                    return False, (f"argument `{name}` unresolvable at a "
                                   f"call site of {fi.qual}")
                arg_expr = d
            checked = True
            ok, why = self.bounded(caller_fi, arg_expr, visited)
            if not ok:
                return False, (f"argument `{name}` of {fi.qual} fed by "
                               f"{why}")
        if not checked:
            return False, f"parameter `{name}` of {fi.qual} never bound"
        return True, ""

    @staticmethod
    def _default_for(fi, name: str) -> Optional[ast.AST]:
        args = fi.node.args
        pos_args = list(args.args)
        defaults = list(args.defaults)
        for a, d in zip(reversed(pos_args), reversed(defaults)):
            if a.arg == name:
                return d
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if a.arg == name and d is not None:
                return d
        return None

    def _caller_edges(self) -> Dict[str, List]:
        """callee funckey -> [(caller FuncInfo, Call node)] over every
        resolvable call in the program."""
        if self._callers is not None:
            return self._callers
        out: Dict[str, List] = {}
        for fi in self.prog.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.prog.resolve_callable(fi, node.func)
                if callee is not None:
                    out.setdefault(callee, []).append((fi, node))
        self._callers = out
        return out

    # -- attributes ------------------------------------------------------

    def _bounded_attr(self, fi, expr: ast.Attribute,
                      visited: Set) -> Tuple[bool, str]:
        if expr.attr in _BOUNDED_ATTRS:
            return True, ""
        src = _unparse(expr)
        attr = _self_attr_name(expr)
        if attr is not None and fi.cls is not None:
            return self._bounded_field(fi.cls.key, attr, src, visited)
        # non-self receiver: use the staging dataflow's instance typing
        v = self.df.classify(fi, expr.value)
        if v is not None and v.inst is not None:
            return self._bounded_field(v.inst, expr.attr, src, visited)
        clskey = self.prog.receiver_class(fi, expr.value)
        if clskey is not None:
            return self._bounded_field(clskey, expr.attr, src, visited)
        # a bounded receiver denotes finitely many objects (e.g. the
        # process-frozen config via a bucketed[] factory): its
        # attribute loads are bounded too
        ok, _ = self.bounded(fi, expr.value, visited)
        if ok:
            return True, ""
        return False, f"`{src}` has an unresolvable receiver"

    def _bounded_field(self, clskey: str, attr: str, src: str,
                       visited: Set) -> Tuple[bool, str]:
        key = ("field", clskey, attr)
        if key in visited:
            return True, ""
        visited.add(key)
        sites: List[Tuple] = []      # (owning fi, value expr)
        ctor_only = True
        for ci in self.prog._mro(clskey):
            # class-level assignment (annotated or not) is a frozen default
            for stmt in ci.node.body:
                if (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)
                        and stmt.target.id == attr
                        and stmt.value is not None):
                    sites.append((None, stmt.value))
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == attr:
                            sites.append((None, stmt.value))
            for mname, m in ci.methods.items():
                for node in ast.walk(m.node):
                    targets: List[ast.AST] = []
                    value: Optional[ast.AST] = None
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif isinstance(node, ast.AnnAssign) \
                            and node.value is not None:
                        targets, value = [node.target], node.value
                    for t in targets:
                        if _self_attr_name(t) == attr:
                            sites.append((m, value))
                            if mname not in ("__init__", "__post_init__"):
                                ctor_only = False
        if sites:
            if not ctor_only:
                return False, (f"`{src}` is reassigned outside the "
                               f"constructor")
            for m, value in sites:
                if m is None:
                    ok, why = self.bounded_classlevel(value)
                else:
                    ok, why = self.bounded(m, value, visited)
                if not ok:
                    return False, f"`{src}` <- {why}"
            return True, ""
        # dataclass / NamedTuple field: meet over constructor sites
        ci = self.prog.classes.get(clskey)
        if ci is not None and any(
                isinstance(s, ast.AnnAssign)
                and isinstance(s.target, ast.Name) and s.target.id == attr
                for s in ci.node.body):
            return self._bounded_ctor_field(clskey, attr, src, visited)
        return False, f"`{src}` is never assigned anywhere visible"

    def bounded_classlevel(self, value: ast.AST) -> Tuple[bool, str]:
        for sub in ast.walk(value):
            if isinstance(sub, (ast.Name, ast.Call, ast.Attribute)):
                return False, "non-literal class-level default"
        return True, ""

    def _bounded_ctor_field(self, clskey: str, attr: str, src: str,
                            visited: Set) -> Tuple[bool, str]:
        ci = self.prog.classes[clskey]
        fields = [s.target.id for s in ci.node.body
                  if isinstance(s, ast.AnnAssign)
                  and isinstance(s.target, ast.Name)]
        try:
            pos = fields.index(attr)
        except ValueError:
            return False, f"`{src}` not a declared field"
        sites = self._class_ctor_sites().get(clskey, [])
        if not sites:
            return False, f"`{src}`: no visible constructor site"
        for caller_fi, call in sites:
            arg_expr = None
            if pos < len(call.args) and not isinstance(call.args[pos],
                                                       ast.Starred):
                arg_expr = call.args[pos]
            for kw in call.keywords:
                if kw.arg == attr:
                    arg_expr = kw.value
            if arg_expr is None:
                continue        # defaulted — class-level default, finite
            ok, why = self.bounded(caller_fi, arg_expr, visited)
            if not ok:
                return False, f"`{src}` <- {why}"
        return True, ""

    def _class_ctor_sites(self) -> Dict[str, List]:
        if self._ctor_sites is not None:
            return self._ctor_sites
        out: Dict[str, List] = {}
        for fi in self.prog.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = dotted_call_name(node.func)
                if not dotted:
                    continue
                clskey = self.prog._resolve_class_name(fi.ctx, dotted)
                if clskey is not None:
                    out.setdefault(clskey, []).append((fi, node))
        self._ctor_sites = out
        return out

    # -- module-level constants -----------------------------------------

    @staticmethod
    def _module_constant(ctx, name: str) -> bool:
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return all(
                            isinstance(sub, (ast.Constant, ast.Tuple,
                                             ast.List, ast.Load))
                            or isinstance(sub, ast.expr_context)
                            for sub in ast.walk(stmt.value))
        return False

    # -- calls -----------------------------------------------------------

    def _bounded_call(self, fi, call: ast.Call,
                      visited: Set) -> Tuple[bool, str]:
        dotted = dotted_call_name(call.func)
        leaf = dotted.split(".")[-1] if dotted else None
        if leaf in self.bucket_helpers:
            return True, ""
        if dotted in _TRANSPARENT:
            for a in call.args:
                ok, why = self.bounded(fi, a, visited)
                if not ok:
                    return False, f"`{dotted}()` of {why}"
            return True, ""
        callee = self.prog.resolve_callable(fi, call.func)
        if callee is None and isinstance(call.func, ast.Name):
            callee = self._deferred_import_key(fi, call.func.id)
        if callee is not None:
            m = self.prog.functions.get(callee)
            if m is not None:
                if _has_bucketed_directive(m.ctx, m.node):
                    return True, ""
                return self._bounded_returns(m, visited)
        src = _unparse(call)
        return False, f"opaque call `{src}`"

    def _deferred_import_key(self, fi, name: str) -> Optional[str]:
        """Resolve ``name`` bound by a function-level ``from X import``
        (the repo's deferred-import idiom for cycle breaking) to a
        program funckey."""
        from ..core import _dotted_module
        from ..staging.dataflow import ordered_nodes

        f = fi
        while f is not None:
            for n in ordered_nodes(f.node):
                if not isinstance(n, ast.ImportFrom):
                    continue
                for alias in n.names:
                    if (alias.asname or alias.name) != name:
                        continue
                    here = _dotted_module(f.ctx.relpath).split(".")
                    if f.ctx.relpath.endswith("__init__.py"):
                        pkg = here
                    else:
                        pkg = here[:-1]
                    if n.level:
                        pkg = pkg[: len(pkg) - (n.level - 1)]
                        base = pkg
                    else:
                        base = []
                    mod = ".".join(base + (n.module.split(".")
                                           if n.module else []))
                    return f"{mod}:{alias.name}"
            f = getattr(f, "parent", None)
        return None

    def _bounded_returns(self, fi, visited: Set) -> Tuple[bool, str]:
        from ..staging.dataflow import ordered_nodes

        key = ("ret", fi.key)
        if key in visited:
            return True, ""
        visited.add(key)
        rets = [n for n in ordered_nodes(fi.node)
                if isinstance(n, ast.Return) and n.value is not None]
        if not rets:
            return False, f"`{fi.qual}()` returns nothing bounded"
        for r in rets:
            ok, why = self.bounded(fi, r.value, visited)
            if not ok:
                return False, f"`{fi.qual}()` may return {why}"
        return True, ""
