"""QT001 — host sync in a hot path.

Every ``jax.device_get`` / ``.block_until_ready()`` / host cast of a
device value inside the sampling -> gather -> serve pipeline stalls the
dispatch queue for a full device round-trip; at serving rates that is
the difference between "as fast as the hardware allows" and a host-bound
pipeline (the GNNSampler / SALIENT data-layer tax).  Sync points that
are part of the design (timing probes, A/B serialization baselines) get
an inline ``# quiverlint: ignore[QT001]`` with a justification.

Detection is deliberately local and conservative:

  * any call to ``jax.device_get`` / ``jax.block_until_ready`` or any
    ``<expr>.block_until_ready()`` in a hot module is flagged outright;
  * ``np.asarray`` / ``np.array`` / ``int`` / ``float`` / ``bool`` are
    flagged only when the argument is *known* to be a device value — a
    name assigned (possibly through arithmetic) from a ``jnp.*`` /
    ``jax.*`` call in the same function, or a direct ``jnp.*``/``jax.*``
    call expression.  Host-side numpy stays unflagged.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import Finding, ModuleContext, Rule, dotted_call_name

_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_CASTS = {"int", "float", "bool"}
_MATERIALIZE = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_DEVICE_ROOTS = {"jnp", "jax"}


def _is_device_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_call_name(node.func)
    return bool(name) and name.split(".", 1)[0] in _DEVICE_ROOTS


# numpy-array methods that keep a host value host when chained onto a
# materializer: np.asarray(x).copy() etc.
_HOST_CHAIN = {"copy", "astype", "ravel", "item", "tolist", "reshape"}


def _materialized(value: ast.AST) -> bool:
    """True if ``value`` is a host materialization at its root — e.g.
    ``np.asarray(x)``, ``int(x)``, ``np.asarray(x).copy()``.  Such an
    assignment yields a HOST value: downstream casts of it are free."""
    node = value
    while True:
        if (isinstance(node, ast.Call) and isinstance(node.func,
                                                      ast.Attribute)
                and node.func.attr in _HOST_CHAIN):
            node = node.func.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            break
    if isinstance(node, ast.Call):
        name = dotted_call_name(node.func)
        return name in _MATERIALIZE or name in _CASTS
    return False


def _target_names(target: ast.AST) -> Set[str]:
    """Plain names (re)bound by an assignment target.  Attribute and
    subscript targets bind no local name (`self.x = jnp...` must not
    mark `self` as a device value)."""
    if isinstance(target, ast.Name):
        return {target.id}
    if isinstance(target, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in target.elts:
            out |= _target_names(e)
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return set()


def _tracked_names(fn: ast.AST) -> Set[str]:
    """Names assigned from jnp./jax. calls in ``fn``, propagated through
    arithmetic and intermediate calls to a fixed point
    (``g = branch * (1.0 + mean(g))``); a name rebound from a
    materializer (``np.asarray(...)``) is host, not device."""
    tracked: Set[str] = set()
    assigns = [n for n in ast.walk(fn) if isinstance(n, (ast.Assign,
                                                         ast.AugAssign))]

    def mentions(expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if _is_device_call(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in tracked:
                return True
        return False

    changed = True
    while changed:
        changed = False
        for a in assigns:
            if _materialized(a.value) or not mentions(a.value):
                continue
            targets = a.targets if isinstance(a, ast.Assign) else [a.target]
            for t in targets:
                for name in _target_names(t):
                    if name not in tracked:
                        tracked.add(name)
                        changed = True
    return tracked


class HostSyncRule(Rule):
    code = "QT001"
    name = "host-sync-in-hot-path"
    description = ("device_get / block_until_ready / host casts of device "
                   "values inside hot-path modules")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.is_hot():
            return
        for qual, fn in ctx.functions:
            tracked = None  # computed lazily: most functions are clean
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_call_name(node.func) or ""
                if name in _SYNC_CALLS:
                    yield ctx.finding(
                        self.code, node,
                        f"explicit host sync `{name}` in hot path "
                        "(blocks the dispatch queue per batch)",
                        scope=qual)
                    continue
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "block_until_ready"):
                    yield ctx.finding(
                        self.code, node,
                        "`.block_until_ready()` in hot path (host sync)",
                        scope=qual)
                    continue
                if name in _CASTS or name in _MATERIALIZE:
                    if not node.args:
                        continue
                    arg = node.args[0]
                    # the inner device_get was already flagged above
                    if any(dotted_call_name(s.func) in _SYNC_CALLS
                           for s in ast.walk(arg)
                           if isinstance(s, ast.Call)):
                        continue
                    if tracked is None:
                        tracked = _tracked_names(fn)
                    direct = any(_is_device_call(s) for s in ast.walk(arg))
                    via_name = any(isinstance(s, ast.Name)
                                   and s.id in tracked
                                   for s in ast.walk(arg))
                    if direct or via_name:
                        yield ctx.finding(
                            self.code, node,
                            f"`{name}(...)` materializes a device value on "
                            "host in a hot path (implicit device_get)",
                            scope=qual)
