"""QT003 — lock discipline via class-level ``_guarded_by`` declarations.

The serving pipeline and the metrics registry are thread soups by
design: batcher workers, sampler workers, the device loop, and the
metrics endpoint all share object state.  A class declares its contract
as a literal map::

    class InferenceServer:
        _guarded_by = {"_fused_fns": "_lock"}

and this rule enforces that every *mutation* of a declared attribute
(``self._fused_fns[...] = ...``, ``self._fused_fns.pop(...)``,
rebinding, augmented assignment) happens lexically inside a
``with self._lock:`` block naming the declared lock.  ``__init__`` and
``__post_init__`` are exempt (construction happens-before publication),
and so are ``@classmethod`` bodies — the alternate-constructor idiom
builds an instance named ``self`` before publication, and a classmethod
has no real ``self`` to mutate otherwise.  Reads are not
checked: the codebase intentionally uses double-checked locking on
CPython where a racy read is benign (e.g. ``MetricsRegistry._get``).
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, Optional

from ..core import Finding, ModuleContext, Rule

# method names that mutate the common containers in place
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "appendleft", "clear", "update", "setdefault", "add", "discard",
    "__setitem__", "sort", "reverse",
}


def _guarded_map(cls: ast.ClassDef) -> Optional[Dict[str, str]]:
    """Parse a literal class-level ``_guarded_by = {"attr": "lock"}``."""
    for stmt in cls.body:
        target = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            target, value = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            target, value = stmt.target.id, stmt.value
        if target != "_guarded_by":
            continue
        if not isinstance(value, ast.Dict):
            return None
        out: Dict[str, str] = {}
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and isinstance(v, ast.Constant) \
                    and isinstance(k.value, str) and isinstance(v.value, str):
                out[k.value] = v.value
        return out
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _with_locks(stmt: ast.With) -> FrozenSet[str]:
    names = set()
    for item in stmt.items:
        attr = _self_attr(item.context_expr)
        if attr:
            names.add(attr)
    return frozenset(names)


class LockDisciplineRule(Rule):
    code = "QT003"
    name = "lock-discipline"
    description = ("attributes declared in a class-level _guarded_by map "
                   "must only be mutated under `with self.<lock>`")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                guarded = _guarded_map(node)
                if guarded:
                    yield from self._check_class(ctx, node, guarded)

    def _check_class(self, ctx: ModuleContext, cls: ast.ClassDef,
                     guarded: Dict[str, str]) -> Iterator[Finding]:
        qual_base = ctx.scope_of(cls)
        cls_qual = (f"{qual_base}.{cls.name}"
                    if qual_base != "<module>" else cls.name)
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name in ("__init__", "__post_init__"):
                    continue
                if any(isinstance(d, ast.Name) and d.id == "classmethod"
                       for d in stmt.decorator_list):
                    continue  # alternate constructor: pre-publication
                yield from self._walk(
                    ctx, stmt, guarded, frozenset(),
                    f"{cls_qual}.{stmt.name}")

    def _walk(self, ctx: ModuleContext, node: ast.AST,
              guarded: Dict[str, str], locks: FrozenSet[str],
              scope: str) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_locks = locks
            if isinstance(child, ast.With):
                child_locks = locks | _with_locks(child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def's body runs later, outside any with-block
                # active at its definition site
                child_locks = frozenset()
            yield from self._mutations(ctx, child, guarded, locks, scope)
            yield from self._walk(ctx, child, guarded, child_locks, scope)

    def _mutations(self, ctx: ModuleContext, node: ast.AST,
                   guarded: Dict[str, str], locks: FrozenSet[str],
                   scope: str) -> Iterator[Finding]:
        hits = []  # (attr, node)
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                attr = _self_attr(t)
                if attr is None and isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                if attr in guarded:
                    hits.append((attr, node))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr in guarded:
                hits.append((attr, node))
        for attr, n in hits:
            lock = guarded[attr]
            if lock not in locks:
                yield ctx.finding(
                    self.code, n,
                    f"`self.{attr}` is declared _guarded_by "
                    f"`self.{lock}` but is mutated outside `with "
                    f"self.{lock}:`",
                    scope=scope)
