"""QT011 — recovery-tier writes must flow through the blessed helpers.

The durability tier's whole value is that *every* persisted byte is
either a checksummed record (``blockio.write_record`` — torn tails and
bit rot are detectable) or an atomically published file
(``blockio.atomic_publish`` — readers never see a half-written
hybrid).  A bare ``open(path, "w")`` anywhere else under
``quiver_tpu/recovery/`` silently reopens the exact failure modes the
tier exists to close: a crash mid-write leaves an unframed,
unverifiable file that replay can neither trust nor skip.

The rule is structural, not advisory: inside the durability scope
(``config.durability_scope``, default ``quiver_tpu/recovery/*.py``)
any write-mode ``open``/``os.fdopen`` call — or one whose mode the
linter cannot prove is read-only — and any ``Path.write_text`` /
``Path.write_bytes`` call is a finding.  ``blockio.py`` itself is the
one exempt module (``config.durability_exempt``): it is where the raw
writes are *supposed* to live, behind the two audited primitives.

Read-mode opens pass: replay and checkpoint loading read freely; it is
only the mutation side that must be mediated.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from ..core import Finding, ModuleContext, Rule, _match_any, dotted_call_name

# any of these characters in an open() mode string means bytes can be
# written through the returned handle
_WRITE_MODE = re.compile(r"[wax+]")

_OPENERS = {"open", "io.open", "os.fdopen"}
_PATH_WRITERS = {"write_text", "write_bytes"}


def _mode_arg(node: ast.Call) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == "mode":
            return kw.value
    if len(node.args) >= 2:
        return node.args[1]
    return None


class DurabilityRule(Rule):
    code = "QT011"
    name = "durable-write-path"
    description = ("recovery-tier modules must persist bytes through "
                   "blockio.write_record / blockio.atomic_publish, not "
                   "bare write-mode open()/write_text()/write_bytes()")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not _match_any(ctx.relpath, ctx.config.durability_scope):
            return
        if _match_any(ctx.relpath, ctx.config.durability_exempt):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_call_name(node.func)
            if dotted in _OPENERS:
                mode = _mode_arg(node)
                if mode is None:
                    continue  # default "r": read-only
                if isinstance(mode, ast.Constant) and isinstance(
                        mode.value, str):
                    if not _WRITE_MODE.search(mode.value):
                        continue
                    why = f"write-mode open ({mode.value!r})"
                else:
                    why = "open() with a mode the linter cannot prove " \
                          "read-only"
                yield ctx.finding(
                    self.code, node,
                    f"{why} in a durability-scope module: persist "
                    "through blockio.write_record / "
                    "blockio.atomic_publish (or blockio.append_open "
                    "for WAL segments) so the bytes are checksummed "
                    "or atomically published")
            elif dotted and dotted.split(".")[-1] in _PATH_WRITERS:
                yield ctx.finding(
                    self.code, node,
                    f"`{dotted}` bypasses the durable write helpers: "
                    "use blockio.atomic_publish so readers never see "
                    "a torn file")
