"""QT002 — retrace hazards.

XLA executables are keyed by (function identity, abstract shapes, static
values).  Three Python-side patterns silently defeat that cache and turn
a "compile once, serve forever" pipeline into a compile-per-call one:

  * a fresh ``lambda`` passed to ``jax.jit`` at a call site — every call
    makes a new function object, so the jit cache can never hit (unless
    the caller caches the wrapped result; if it provably does, suppress
    with a justification or restructure to ``jax.jit(self._method)``);
  * any ``jax.jit(...)`` call inside a loop body — one traced program
    per iteration;
  * a jit-decorated function whose *traced* parameter flows into a shape
    (``jnp.zeros(n)``, ``x.reshape(b, -1)``, ``jax.random.split(key,
    n)``): every distinct value is a distinct shape signature, i.e. a
    recompile.  Mark it in ``static_argnames`` (and bucket its values)
    or derive the size from an input array's shape;
  * a jit-decorated function reading ``self.<attr>``: instance state is
    captured at trace time, so later mutation is silently ignored (and
    ``jit`` directly on a method retraces per instance).  Bind the
    needed values to locals before the ``def`` — see
    ``InferenceServer._fused_forward`` for the idiom.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from ..core import Finding, ModuleContext, Rule, dotted_call_name

_JIT_NAMES = {"jax.jit", "jit"}

# func dotted name -> which positional args are shape-like
# ("first" = arg 0 incl. tuple elements, "all" = every positional arg,
# "second" = arg 1)
_SHAPE_FUNCS = {
    "jnp.zeros": "first", "jnp.ones": "first", "jnp.empty": "first",
    "jnp.full": "first", "jnp.eye": "all", "jnp.arange": "all",
    "jnp.broadcast_to": "second", "jnp.tile": "second",
    "jax.numpy.zeros": "first", "jax.numpy.ones": "first",
    "jax.numpy.arange": "all", "jax.random.split": "second",
}


def _is_jit(func: ast.AST) -> bool:
    return dotted_call_name(func) in _JIT_NAMES


def _jit_decoration(dec: ast.AST) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) if ``dec`` is a jit decorator
    (bare ``@jax.jit``, ``@jax.jit(...)``, or ``@partial(jax.jit, ...)``),
    else None."""
    if _is_jit(dec):
        return set(), set()
    if not isinstance(dec, ast.Call):
        return None
    kwargs = None
    if _is_jit(dec.func):
        kwargs = dec.keywords
    elif (dotted_call_name(dec.func) in ("functools.partial", "partial")
          and dec.args and _is_jit(dec.args[0])):
        kwargs = dec.keywords
    if kwargs is None:
        return None
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in kwargs:
        if kw.arg == "static_argnames":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    names.add(c.value)
        elif kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    nums.add(c.value)
    return names, nums


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [x.arg for x in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


def _shape_name_uses(fn: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """(param-candidate name, node) pairs where a bare Name appears in a
    shape position inside ``fn``."""

    def names_in(expr: ast.AST) -> Iterator[str]:
        if isinstance(expr, ast.Name):
            yield expr.id
        elif isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                if isinstance(e, ast.Name):
                    yield e.id

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_call_name(node.func)
        spec = _SHAPE_FUNCS.get(name or "")
        if spec:
            if spec == "first" and node.args:
                picked = [node.args[0]]
            elif spec == "second" and len(node.args) > 1:
                picked = [node.args[1]]
            elif spec == "all":
                picked = list(node.args)
            else:
                picked = []
            for arg in picked:
                for n in names_in(arg):
                    yield n, node
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "reshape"):
            for arg in node.args:
                for n in names_in(arg):
                    yield n, node


class RetraceRule(Rule):
    code = "QT002"
    name = "retrace-hazard"
    description = ("jit call-site and signature patterns that defeat the "
                   "executable cache (fresh closures, jit in loops, "
                   "shape-affecting traced params, mutable self capture)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        yield from self._call_sites(ctx.tree, ctx, in_loop=False)
        yield from self._decorated(ctx)

    # -- jax.jit(...) call sites --------------------------------------
    def _call_sites(self, node: ast.AST, ctx: ModuleContext,
                    in_loop: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop or isinstance(child, (ast.For,
                                                          ast.While))
            if isinstance(child, ast.Call) and _is_jit(child.func):
                if in_loop:
                    yield ctx.finding(
                        self.code, child,
                        "jax.jit(...) inside a loop: one fresh traced "
                        "program per iteration; hoist and cache it")
                elif child.args and isinstance(child.args[0], ast.Lambda):
                    yield ctx.finding(
                        self.code, child,
                        "fresh lambda passed to jax.jit: each evaluation "
                        "creates a new function object, so the jit cache "
                        "never hits; jit a named function instead")
            yield from self._call_sites(child, ctx, child_in_loop)

    # -- @jax.jit-decorated defs --------------------------------------
    def _decorated(self, ctx: ModuleContext) -> Iterator[Finding]:
        for qual, fn in ctx.functions:
            statics: Optional[Set[str]] = None
            for dec in fn.decorator_list:
                got = _jit_decoration(dec)
                if got is not None:
                    names, nums = got
                    params = _param_names(fn)
                    statics = set(names)
                    statics.update(params[i] for i in nums
                                   if i < len(params))
                    break
            if statics is None:
                continue
            params = set(_param_names(fn))
            reported: Set[str] = set()
            for name, node in _shape_name_uses(fn):
                if name in params and name not in statics \
                        and name not in reported:
                    reported.add(name)
                    yield ctx.finding(
                        self.code, node,
                        f"traced parameter `{name}` flows into a shape: "
                        "every distinct value recompiles; add it to "
                        "static_argnames (and bucket its values) or derive "
                        "the size from an input array's shape",
                        scope=qual)
            if "self" in params:
                yield ctx.finding(
                    self.code, fn,
                    "jax.jit on a method traces `self` as an argument "
                    "(retraces per instance); jit a free function or a "
                    "closure over explicit locals",
                    scope=qual)
                continue
            for node in ast.walk(fn):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    yield ctx.finding(
                        self.code, node,
                        f"jit-traced function reads `self.{node.attr}`: "
                        "instance state is baked in at trace time and "
                        "later mutation is ignored; bind it to a local "
                        "before the def",
                        scope=qual)
                    break
