"""QT006 — metric-name hygiene at telemetry factory call sites.

The registry addresses metrics by flat ``name{k=v,...}`` strings and the
Prometheus exposition inherits them verbatim, so naming mistakes are
forever: a dynamic name (f-string with a batch size in it) explodes
cardinality, a missing unit suffix makes dashboards guess whether
``feature_gather`` is seconds or bytes, and a computed label key defeats
the catalogue in docs/OBSERVABILITY.md.  This rule pins the contract at
every ``telemetry.counter/gauge/histogram`` call:

  * the metric name is a **literal** ``snake_case`` string (never an
    f-string, concatenation, or variable);
  * the name carries a unit suffix: ``_total`` (counts), ``_seconds``
    (durations), ``_bytes`` (sizes), ``_state`` (enum gauges),
    ``_level`` (ordinal gauges — the QoS degradation ladder),
    ``_lsn`` (log-sequence-number watermarks — WAL shipping lag),
    ``_rows`` (row-count gauges — mesh frontier ownership),
    ``_members`` (membership-count gauges — fleet shard groups),
    ``_replicas`` (replica-count gauges — autoscaler targets),
    ``_rps`` (request-rate gauges — autoscaler predictions), or
    ``_epoch`` (election-epoch ordinals — leader fencing);
  * label keys are literal keyword arguments — ``**labels`` expansion
    hides the key set from static inspection and is flagged.

Matched call sites: dotted calls through a ``telemetry`` module object
(``telemetry.counter(...)``) and bare calls to factories imported from a
telemetry module (``from . import counter`` inside the package).
Registry-internal plumbing (``self.counter(name, **labels)`` in
``merge``) is deliberately NOT matched — it forwards names that were
already validated at their facade call site.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from ..core import Finding, ModuleContext, Rule, dotted_call_name

_FACTORIES = {"counter", "gauge", "histogram"}
_UNIT_SUFFIXES = ("_total", "_seconds", "_bytes", "_state", "_level",
                  "_lsn", "_rows", "_members", "_replicas", "_rps",
                  "_epoch")
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
# factory kwargs that are API options, not metric labels
_OPTION_KWARGS = {"bounds", "help"}


class MetricNameRule(Rule):
    code = "QT006"
    name = "metric-name-hygiene"
    description = ("telemetry metric names must be literal snake_case "
                   "with a _total/_seconds/_bytes unit suffix and "
                   "literal label keys")

    def _bare_aliases(self, ctx: ModuleContext) -> Set[str]:
        """Names bound by ``from <...telemetry> import counter/...`` —
        including relative imports inside the telemetry package itself."""
        parts = ctx.relpath.replace("\\", "/").split("/")
        in_telemetry_pkg = "telemetry" in parts
        out: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            mod = node.module or ""
            from_telemetry = (
                mod.split(".")[-1] == "telemetry"
                or (node.level > 0 and not mod and in_telemetry_pkg)
            )
            if not from_telemetry:
                continue
            for alias in node.names:
                if alias.name in _FACTORIES:
                    out.add(alias.asname or alias.name)
        return out

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        bare = self._bare_aliases(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_call_name(node.func)
            if dotted is None:
                continue
            parts = dotted.split(".")
            if len(parts) >= 2:
                if parts[-1] not in _FACTORIES or parts[-2] != "telemetry":
                    continue
            elif parts[0] not in bare:
                continue
            factory = parts[-1]
            yield from self._check_call(ctx, node, factory)

    def _check_call(self, ctx: ModuleContext, node: ast.Call,
                    factory: str) -> Iterator[Finding]:
        if not node.args:
            return  # keyword-only name is not an idiom here; nothing to pin
        name_arg = node.args[0]
        if isinstance(name_arg, ast.JoinedStr):
            yield ctx.finding(
                self.code, name_arg,
                f"metric name passed to `{factory}` is an f-string: "
                "dynamic names explode label-free cardinality; use a "
                "literal name and put the variable part in a label")
            return
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)):
            yield ctx.finding(
                self.code, name_arg,
                f"metric name passed to `{factory}` is not a literal "
                "string: names must be statically auditable (the metric "
                "catalogue in docs/OBSERVABILITY.md is built from them)")
            return
        name = name_arg.value
        if not _SNAKE.match(name):
            yield ctx.finding(
                self.code, name_arg,
                f"metric name {name!r} is not snake_case "
                "([a-z][a-z0-9_]*)")
        elif not name.endswith(_UNIT_SUFFIXES):
            yield ctx.finding(
                self.code, name_arg,
                f"metric name {name!r} lacks a unit suffix: counts end "
                "in _total, durations in _seconds, sizes in _bytes, "
                "enum gauges in _state, ordinal gauges in _level")
        for kw in node.keywords:
            if kw.arg is None:
                yield ctx.finding(
                    self.code, kw.value,
                    f"`**` label expansion on `{factory}({name!r}, ...)`: "
                    "label keys must be literal keyword arguments so the "
                    "key set is statically auditable")
            elif kw.arg not in _OPTION_KWARGS and not _SNAKE.match(kw.arg):
                yield ctx.finding(
                    self.code, kw.value,
                    f"label key {kw.arg!r} on `{factory}({name!r}, ...)` "
                    "is not snake_case")
