"""QT010 — every discovered thread root must be reaped.

PR 5 gave the runtime `resilience.shutdown.join_and_reap`, which joins
worker threads against a shared deadline and ticks
``serving_thread_leak_total{component}`` for stragglers — but nothing
kept new thread roots honest about using it.  This rule closes the gap
between the static thread-root inventory (the same one QT008/QT009 use
for reachability) and that runtime metric:

* a ``threading.Thread(...)`` creation site is flagged unless its owner
  (the enclosing class, else the enclosing module) calls
  ``join_and_reap`` somewhere;
* a ``threading.Thread`` *subclass* is flagged at its ``class``
  statement under the same ownership test (its ``stop`` should reap
  itself: ``join_and_reap([self], ...)``);
* a ``pool.submit(...)`` owner passes by either calling
  ``join_and_reap`` or referencing ``shutdown`` (executor lifecycles
  are reaped by ``Executor.shutdown``); submitting to a pool received
  as a *parameter* is never flagged — a borrowed executor's worker
  lifecycle belongs to the caller that owns the pool.

Deliberate leaks (a daemon with process lifetime) are suppressed inline
with a justification: ``# quiverlint: ignore[QT010] -- <why>``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Sequence, Set

from ..concurrency import build_program
from ..concurrency.program import SpawnSite
from ..core import Finding, ModuleContext, ProgramRule


class ThreadReapRule(ProgramRule):
    code = "QT010"
    name = "unreaped-thread-root"
    description = ("thread roots must be joined via resilience.shutdown."
                   "join_and_reap (executors: .shutdown), or suppressed "
                   "with a justification")

    def check_program(self, ctxs: Sequence[ModuleContext],
                      ) -> Iterator[Finding]:
        prog = build_program(ctxs)
        for spawn in sorted(
                prog.spawns,
                key=lambda s: (s.ctx.relpath,
                               getattr(s.node, "lineno", 0))):
            if spawn.borrowed:
                continue
            owner = (spawn.owner_class.node if spawn.owner_class is not None
                     else spawn.ctx.tree)
            refs = _referenced_names(owner)
            if "join_and_reap" in refs:
                continue
            if spawn.kind == "submit" and "shutdown" in refs:
                continue
            where = (spawn.owner_class.name if spawn.owner_class is not None
                     else spawn.ctx.module)
            if spawn.kind == "thread-subclass":
                msg = (f"`{where}` subclasses threading.Thread but never "
                       f"reaps itself via resilience.shutdown."
                       f"join_and_reap — leaked workers bypass "
                       f"serving_thread_leak_total")
            elif spawn.kind == "submit":
                msg = (f"executor work submitted in `{where}` with no "
                       f"join_and_reap/shutdown in scope — pool threads "
                       f"outlive the owner unreaped")
            else:
                msg = (f"thread spawned in `{where}` but join_and_reap "
                       f"is never called there — stop paths leak "
                       f"workers past serving_thread_leak_total")
            yield self._finding(spawn, msg)

    @staticmethod
    def _finding(spawn: SpawnSite, message: str) -> Finding:
        ctx = spawn.ctx
        node = spawn.node
        return Finding(
            rule=ThreadReapRule.code, path=ctx.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            scope=ctx.scope_of(node), message=message,
            snippet=ctx.snippet(getattr(node, "lineno", 1)))


def _referenced_names(owner: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(owner):
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
        elif isinstance(node, ast.Name):
            out.add(node.id)
    return out
