"""QT005 — library hygiene: mutable default args and bare ``except:``.

Both are classic slow-motion serving bugs: a mutable default is one
shared object across every call (a stats dict default becomes global
state the first time two requests touch it), and a bare ``except:``
swallows ``KeyboardInterrupt``/``SystemExit``, turning an operator's
Ctrl-C into a hung worker thread.  Library code catches concrete
exception types; lanes that must survive arbitrary request errors say
so explicitly (``except Exception``), and the rare intentional case
carries a suppression with its justification.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, ModuleContext, Rule

_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "deque",
                  "defaultdict", "OrderedDict", "Counter"}


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in _MUTABLE_CTORS
    return False


class HygieneRule(Rule):
    code = "QT005"
    name = "library-hygiene"
    description = "mutable default arguments and bare except: clauses"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for qual, fn in ctx.functions:
            args = fn.args
            defaults = list(args.defaults) + [d for d in args.kw_defaults
                                              if d is not None]
            for d in defaults:
                if _is_mutable_default(d):
                    yield ctx.finding(
                        self.code, d,
                        f"mutable default argument in `{fn.name}`: one "
                        "shared object across every call; default to None "
                        "and construct inside",
                        scope=qual)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield ctx.finding(
                    self.code, node,
                    "bare `except:` swallows KeyboardInterrupt/SystemExit; "
                    "catch Exception (or the concrete types) instead")
