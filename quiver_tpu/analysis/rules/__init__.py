"""quiverlint rule registry — one module per rule, ordered by code."""

from __future__ import annotations

from typing import List

from ..core import Rule
from .qt001_host_sync import HostSyncRule
from .qt002_retrace import RetraceRule
from .qt003_locks import LockDisciplineRule
from .qt004_layering import ImportLayeringRule
from .qt005_hygiene import HygieneRule
from .qt006_metric_names import MetricNameRule
from .qt007_silent_except import SilentExceptRule
from .qt008_races import DataRaceRule
from .qt009_lock_order import LockOrderRule
from .qt010_thread_reap import ThreadReapRule
from .qt011_durability import DurabilityRule
from .qt012_wall_clock import WallClockRule

__all__ = ["all_rules", "RULE_CLASSES"]

RULE_CLASSES = (HostSyncRule, RetraceRule, LockDisciplineRule,
                ImportLayeringRule, HygieneRule, MetricNameRule,
                SilentExceptRule, DataRaceRule, LockOrderRule,
                ThreadReapRule, DurabilityRule, WallClockRule)


def all_rules() -> List[Rule]:
    return [cls() for cls in RULE_CLASSES]
