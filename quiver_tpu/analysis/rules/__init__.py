"""quiverlint rule registry — one module per rule, ordered by code."""

from __future__ import annotations

import hashlib
import inspect
from typing import Dict, List

from ..core import Rule
from .qt001_host_sync import HostSyncRule
from .qt002_retrace import RetraceRule
from .qt003_locks import LockDisciplineRule
from .qt004_layering import ImportLayeringRule
from .qt005_hygiene import HygieneRule
from .qt006_metric_names import MetricNameRule
from .qt007_silent_except import SilentExceptRule
from .qt008_races import DataRaceRule
from .qt009_lock_order import LockOrderRule
from .qt010_thread_reap import ThreadReapRule
from .qt011_durability import DurabilityRule
from .qt012_wall_clock import WallClockRule
from .qt013_staging_sync import InterproceduralHostSyncRule
from .qt014_cache_keys import UnboundedExecutableKeyRule
from .qt015_collectives import CollectiveDisciplineRule

__all__ = ["all_rules", "rule_fingerprints", "RULE_CLASSES"]

RULE_CLASSES = (HostSyncRule, RetraceRule, LockDisciplineRule,
                ImportLayeringRule, HygieneRule, MetricNameRule,
                SilentExceptRule, DataRaceRule, LockOrderRule,
                ThreadReapRule, DurabilityRule, WallClockRule,
                InterproceduralHostSyncRule, UnboundedExecutableKeyRule,
                CollectiveDisciplineRule)


def all_rules() -> List[Rule]:
    return [cls() for cls in RULE_CLASSES]


_FINGERPRINTS: Dict[str, str] = {}


def rule_fingerprints() -> Dict[str, str]:
    """rule code -> short hash of the rule's *implementation source*.

    Stamped into the baseline (v2) so that editing a rule's logic
    invalidates its accepted entries: a finding grandfathered under the
    old detector must be re-justified once the detector changes,
    instead of a stale fingerprint silently absorbing whatever the new
    logic reports (see ``baseline.py`` and ``--strict-baseline``).
    Source hashing deliberately includes docstrings/comments: a rule
    edit is a rule edit.
    """
    if not _FINGERPRINTS:
        for cls in RULE_CLASSES:
            src = inspect.getsource(inspect.getmodule(cls))
            digest = hashlib.blake2b(src.encode("utf-8"),
                                     digest_size=8).hexdigest()
            _FINGERPRINTS[cls.code] = digest
    return dict(_FINGERPRINTS)
