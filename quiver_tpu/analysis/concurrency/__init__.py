"""Whole-program concurrency model for quiverlint (QT008/QT009/QT010).

The per-file rule framework (:mod:`..core`) sees one module at a time;
data races and lock-order inversions are whole-program properties.  This
package builds a single :class:`~.program.Program` over every analyzed
file — an interprocedural call graph with thread-root discovery, a
lock-held context propagated through it, per-root reachability, and a
lock-acquisition-order graph — and the QT008/QT009/QT010 rules read it.

Everything stays stdlib-only AST analysis (same contract as the rest of
quiverlint: no jax, no device, runs in CI in well under a second).

The runtime complement is :mod:`quiver_tpu.analysis.witness` — a
lock-witness sanitizer enabled by ``QUIVER_SANITIZE=1`` that checks the
same two properties (guarded writes, acquisition order) dynamically.
:func:`canonical_lock_edges` exports the static order graph in the
witness's label vocabulary so the sanitizer can pre-seed its order
relation and flag a single reversed acquisition even when the forward
order never executes in that process.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from ..core import ModuleContext
from .program import (
    Access,
    ClassInfo,
    FuncInfo,
    LockId,
    Program,
    SpawnSite,
)

__all__ = [
    "Access", "ClassInfo", "FuncInfo", "LockId", "Program", "SpawnSite",
    "build_program", "canonical_lock_edges",
]

# One-slot memo: within one analyze_paths() run the three program rules
# each receive the identical context list, so the expensive build runs
# once.  Keyed by object identity — a fresh run parses fresh contexts.
_CACHE_KEY: Tuple[int, ...] = ()
_CACHE_VAL: Program = None  # type: ignore[assignment]


def build_program(ctxs: Sequence[ModuleContext]) -> Program:
    """Build (or reuse) the whole-program model for ``ctxs``."""
    global _CACHE_KEY, _CACHE_VAL
    key = tuple(id(c) for c in ctxs)
    if key != _CACHE_KEY or _CACHE_VAL is None:
        _CACHE_VAL = Program(list(ctxs))
        _CACHE_KEY = key
    return _CACHE_VAL


def canonical_lock_edges(ctxs: Sequence[ModuleContext],
                         ) -> List[Tuple[str, str]]:
    """Static acquisition-order edges as (held_label, acquired_label)
    pairs, e.g. ``("StreamingGraph._lock", "CSRTopo._lock")`` — the
    vocabulary the runtime witness uses for its own order graph."""
    prog = build_program(ctxs)
    out = []
    for held, acquired, _site in prog.order_edges():
        out.append((held.label, acquired.label))
    return sorted(set(out))
