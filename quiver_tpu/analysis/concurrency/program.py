"""The whole-program model behind QT008/QT009/QT010.

Built from the same :class:`~quiver_tpu.analysis.core.ModuleContext`
objects the per-file rules consume, in four passes:

1. **Index** — every module's imports, classes (with bases), functions
   (including nested defs and methods), module-level locks.
2. **Types** — a deliberately shallow type environment: parameter
   annotations (including quoted forward references and
   ``Optional[...]``), ``x = ClassName(...)`` constructor assignments,
   and ``self.attr = <typed value>`` instance-attribute types; plus
   per-class lock attributes (``self._lock = threading.Lock()``) and
   merged ``_guarded_by`` contracts.
3. **Facts** — one walk per function collecting call edges (with the
   lexical lock set at each call site), thread spawns
   (``threading.Thread(target=...)``, ``Thread`` subclasses overriding
   ``run``, ``<pool>.submit(fn)``), attribute/global accesses with the
   locks lexically held, and lock acquisitions (``with <lock>:``) with
   the locks already held.
4. **Fixpoints** — per-root reachability over the call graph ("main" is
   a synthetic root seeded by every public entry point that is not a
   thread body), a *must-hold* entry-lock set per function
   (intersection over call sites — used by QT008 to credit callers'
   locks), and a *may-hold* set (union — used by QT009 so an order edge
   exists if any path holds A when B is acquired).

Precision notes (documented in docs/STATIC_ANALYSIS.md): resolution is
name-based and first-order — no flow sensitivity, no aliasing beyond
the type environment above, callable arguments (``register(cb)``) add a
conservative call edge from the registration site.  The design goal is
the same as QT001-007: catch the structural violations that matter in
this codebase with near-zero false positives, and let the runtime
witness (``QUIVER_SANITIZE=1``) cover what static analysis cannot.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from ..core import ModuleContext

__all__ = [
    "Access", "CallEdge", "ClassInfo", "FuncInfo", "LockId", "Program",
    "SpawnSite", "MAIN_ROOT",
]

MAIN_ROOT = "main"

# threading constructors that create a lock-like object; the kind
# matters to QT009 (re-entrant acquisition of an RLock/Condition is not
# a self-deadlock, re-acquiring a plain Lock is).
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# Methods that build the instance before publication: writes inside them
# are construction, not shared-state mutation (dataclasses run
# ``__post_init__`` inside ``__init__``).
_INIT_NAMES = ("__init__", "__post_init__")

# method names that mutate the common containers in place (kept in sync
# with qt003_locks; duplicated so the concurrency package has no import
# edge into the per-file rules).
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "popitem",
    "appendleft", "clear", "update", "setdefault", "add", "discard",
    "__setitem__", "sort", "reverse",
}


@dataclass(frozen=True)
class LockId:
    """A lock identity: (owning class key | module, attribute name)."""

    owner: str   # "pkg.mod:Class" for instance locks, "pkg.mod" for globals
    attr: str
    kind: str = "lock"

    @property
    def label(self) -> str:
        short = self.owner.rsplit(":", 1)[-1].rsplit(".", 1)[-1]
        return f"{short}.{self.attr}"

    def __repr__(self):
        return f"LockId({self.label})"


@dataclass
class ClassInfo:
    key: str                       # "pkg.mod:Qual.Class"
    name: str                      # local qualname within the module
    node: ast.ClassDef
    ctx: ModuleContext
    base_names: List[str] = field(default_factory=list)   # raw dotted
    base_keys: List[str] = field(default_factory=list)    # resolved
    methods: Dict[str, "FuncInfo"] = field(default_factory=dict)
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr->kind
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr->clskey
    sync_attrs: Set[str] = field(default_factory=set)  # Event/Queue/...
    guarded: Dict[str, str] = field(default_factory=dict)     # own decl
    is_thread_subclass: bool = False


@dataclass
class FuncInfo:
    key: str                       # "pkg.mod:qualname"
    qual: str                      # qualname within the module
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    ctx: ModuleContext
    cls: Optional[ClassInfo] = None          # innermost enclosing class
    parent: Optional["FuncInfo"] = None      # enclosing def for nested
    local_types: Dict[str, str] = field(default_factory=dict)
    nested: Dict[str, str] = field(default_factory=dict)  # name->funckey
    requires_raw: List[str] = field(default_factory=list)  # directives

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]

    @property
    def label(self) -> str:
        cls = f"{self.cls.name}." if self.cls else ""
        mod = self.ctx.relpath
        return f"{mod}:{self.qual}"


@dataclass
class CallEdge:
    caller: str
    callee: str
    locks: FrozenSet[LockId]       # lexical locks held at the call site
    node: ast.AST
    indirect: bool = False         # callable passed as an argument


@dataclass
class SpawnSite:
    kind: str                      # "thread" | "submit" | "thread-subclass"
    func: Optional[FuncInfo]       # creating function (None for subclass)
    target: Optional[str]          # funckey the new thread runs, if known
    node: ast.AST
    ctx: ModuleContext
    owner_class: Optional[ClassInfo]
    borrowed: bool = False         # submit on a pool the owner doesn't own

    @property
    def root_id(self) -> str:
        if self.kind == "thread-subclass" and self.target:
            return self.target
        where = self.func.key if self.func else self.ctx.module
        return f"{where}@{getattr(self.node, 'lineno', 0)}"

    @property
    def label(self) -> str:
        if self.target:
            short = self.target.rsplit(":", 1)[-1]
        else:
            short = "<unresolved>"
        return f"{self.kind}:{short}"


@dataclass
class Access:
    owner: str                     # class key, or module for globals
    attr: str
    write: bool
    func: FuncInfo
    node: ast.AST
    locks: FrozenSet[LockId]       # lexical locks at the access
    in_init: bool                  # inside the owner class's __init__
    via_self: bool                 # receiver is `self` (vs cross-object)


@dataclass
class _Acquisition:
    func: FuncInfo
    lock: LockId
    held_before: FrozenSet[LockId]   # lexical locks already held
    node: ast.AST


class _ModuleIndex:
    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.imports: Dict[str, str] = {}        # alias -> dotted module
        self.from_names: Dict[str, Tuple[str, str]] = {}  # name->(mod,attr)
        self.functions: Dict[str, str] = {}      # top-level name -> funckey
        self.classes: Dict[str, str] = {}        # local qualname -> clskey
        self.module_locks: Dict[str, str] = {}   # name -> kind
        self.globals_written: Set[str] = set()


class Program:
    """Whole-program concurrency facts over a list of module contexts."""

    def __init__(self, ctxs: Sequence[ModuleContext]):
        self.ctxs = list(ctxs)
        self.modules: Dict[str, _ModuleIndex] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.class_by_name: Dict[str, List[str]] = {}
        self.call_edges: List[CallEdge] = []
        self.spawns: List[SpawnSite] = []
        self.accesses: List[Access] = []
        self.acquisitions: List[_Acquisition] = []
        self._callers: Dict[str, List[CallEdge]] = {}
        self._callees: Dict[str, List[CallEdge]] = {}
        self._index()
        self._collect_types()
        self._collect_facts()
        self._fixpoints()

    # ------------------------------------------------------------------
    # pass 1: index modules, classes, functions

    def _index(self) -> None:
        for ctx in self.ctxs:
            mod = _ModuleIndex(ctx)
            self.modules[ctx.module] = mod
            for stmt in ctx.tree.body:
                if isinstance(stmt, ast.Import):
                    for a in stmt.names:
                        mod.imports[a.asname or a.name.split(".")[0]] = \
                            a.name if a.asname else a.name.split(".")[0]
                        if a.asname:
                            mod.imports[a.asname] = a.name
                elif isinstance(stmt, ast.ImportFrom):
                    base = self._resolve_from(ctx, stmt)
                    for a in stmt.names:
                        if a.name == "*":
                            continue
                        mod.from_names[a.asname or a.name] = (base, a.name)
                elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    kind = _lock_ctor_kind(stmt.value)
                    if kind:
                        mod.module_locks[stmt.targets[0].id] = kind
            self._index_scope(ctx, mod, ctx.tree, qual="", cls=None,
                              parent=None)
        # resolve class bases now every class exists
        for ci in self.classes.values():
            for raw in ci.base_names:
                key = self._resolve_class_name(ci.ctx, raw)
                if key:
                    ci.base_keys.append(key)
                if raw.split(".")[-1] == "Thread":
                    ci.is_thread_subclass = True
        # inherited thread-ness (one level of fixpoint is plenty here,
        # but iterate to closure for deep towers)
        changed = True
        while changed:
            changed = False
            for ci in self.classes.values():
                if ci.is_thread_subclass:
                    continue
                for bk in ci.base_keys:
                    base = self.classes.get(bk)
                    if base is not None and base.is_thread_subclass:
                        ci.is_thread_subclass = True
                        changed = True

    @staticmethod
    def _resolve_from(ctx: ModuleContext, stmt: ast.ImportFrom) -> str:
        if not stmt.level:
            return stmt.module or ""
        parts = ctx.module.split(".")
        # level 1 = the containing package: a plain module drops its
        # leaf, a package __init__ *is* its own package
        drop = stmt.level
        if ctx.relpath.endswith("__init__.py"):
            drop -= 1
        if drop:
            parts = parts[: len(parts) - drop]
        if stmt.module:
            parts.append(stmt.module)
        return ".".join(parts)

    def _index_scope(self, ctx: ModuleContext, mod: _ModuleIndex,
                     node: ast.AST, qual: str, cls: Optional[ClassInfo],
                     parent: Optional[FuncInfo]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                q = f"{qual}.{child.name}" if qual else child.name
                ci = ClassInfo(key=f"{ctx.module}:{q}", name=q, node=child,
                               ctx=ctx)
                for b in child.bases:
                    dotted = _dotted(b)
                    if dotted:
                        ci.base_names.append(
                            self._canon_base(mod, dotted))
                self.classes[ci.key] = ci
                self.class_by_name.setdefault(
                    child.name, []).append(ci.key)
                if not qual:
                    mod.classes[q] = ci.key
                self._index_scope(ctx, mod, child, q, ci, parent)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qual}.{child.name}" if qual else child.name
                fi = FuncInfo(key=f"{ctx.module}:{q}", qual=q, node=child,
                              ctx=ctx, cls=cls, parent=parent,
                              requires_raw=_requires_directives(ctx, child))
                self.functions[fi.key] = fi
                if cls is not None and parent is None \
                        and child.name not in cls.methods:
                    cls.methods[child.name] = fi
                if not qual:
                    mod.functions[child.name] = fi.key
                if parent is not None:
                    parent.nested[child.name] = fi.key
                self._index_scope(ctx, mod, child, q, cls, fi)
            else:
                self._index_scope(ctx, mod, child, qual, cls, parent)

    def _canon_base(self, mod: _ModuleIndex, dotted: str) -> str:
        head = dotted.split(".")[0]
        if head in mod.from_names and "." not in dotted:
            m, a = mod.from_names[head]
            return f"{m}.{a}"
        return dotted

    # ------------------------------------------------------------------
    # name resolution helpers

    def _resolve_class_name(self, ctx: ModuleContext,
                            name: str) -> Optional[str]:
        """Resolve a (possibly dotted / quoted) class name to a key."""
        mod = self.modules[ctx.module]
        name = name.strip()
        parts = name.split(".")
        local = mod.classes.get(name)
        if local:
            return local
        if parts[0] in mod.from_names:
            m, a = mod.from_names[parts[0]]
            target = self.modules.get(m)
            rest = ".".join([a] + parts[1:])
            if target and rest in target.classes:
                return target.classes[rest]
            # "from x import y" where y is a module
            sub = self.modules.get(f"{m}.{a}")
            if sub and parts[1:] and ".".join(parts[1:]) in sub.classes:
                return sub.classes[".".join(parts[1:])]
        if parts[0] in mod.imports and len(parts) > 1:
            sub = self.modules.get(mod.imports[parts[0]])
            if sub and ".".join(parts[1:]) in sub.classes:
                return sub.classes[".".join(parts[1:])]
        # quoted forward reference to a class defined elsewhere: accept a
        # program-wide unique simple-name match (annotations are the
        # sanctioned way to teach the analyzer cross-module types)
        if len(parts) == 1:
            hits = self.class_by_name.get(name, [])
            if len(hits) == 1:
                return hits[0]
        return None

    def _annotation_class(self, ctx: ModuleContext,
                          ann: Optional[ast.AST]) -> Optional[str]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return self._resolve_class_name(ctx, ann.value)
        if isinstance(ann, ast.Subscript):
            base = _dotted(ann.value) or ""
            if base.split(".")[-1] in ("Optional", "Annotated"):
                inner = ann.slice
                if isinstance(inner, ast.Tuple) and inner.elts:
                    inner = inner.elts[0]
                return self._annotation_class(ctx, inner)
            return None
        dotted = _dotted(ann)
        if dotted:
            return self._resolve_class_name(ctx, dotted)
        return None

    def _mro(self, key: str) -> Iterator[ClassInfo]:
        seen: Set[str] = set()
        stack = [key]
        while stack:
            k = stack.pop(0)
            if k in seen:
                continue
            seen.add(k)
            ci = self.classes.get(k)
            if ci is None:
                continue
            yield ci
            stack.extend(ci.base_keys)

    def lookup_method(self, clskey: str, name: str) -> Optional[FuncInfo]:
        for ci in self._mro(clskey):
            if name in ci.methods:
                return ci.methods[name]
        return None

    def guarded_map(self, clskey: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for ci in self._mro(clskey):
            for k, v in ci.guarded.items():
                out.setdefault(k, v)
        return out

    def lock_kind(self, clskey: str, attr: str) -> Optional[str]:
        for ci in self._mro(clskey):
            if attr in ci.lock_attrs:
                return ci.lock_attrs[attr]
        return None

    def is_sync_attr(self, clskey: str, attr: str) -> bool:
        return any(attr in ci.sync_attrs for ci in self._mro(clskey))

    # ------------------------------------------------------------------
    # pass 2: shallow type environment

    def _collect_types(self) -> None:
        for ci in self.classes.values():
            g = _literal_guarded(ci.node)
            if g:
                ci.guarded = g
        for fi in self.functions.values():
            node = fi.node
            args = getattr(node, "args", None)
            if args is not None:
                for a in list(args.args) + list(args.kwonlyargs):
                    t = self._annotation_class(fi.ctx, a.annotation)
                    if t:
                        fi.local_types[a.arg] = t
            for stmt in _own_statements(node):
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    t = self._annotation_class(fi.ctx, stmt.annotation)
                    if t:
                        fi.local_types[stmt.target.id] = t
                if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                    continue
                target, value = stmt.targets[0], stmt.value
                vtype = self._value_class(fi, value)
                if isinstance(target, ast.Name):
                    kind = _lock_ctor_kind(value)
                    if kind is None and vtype:
                        fi.local_types[target.id] = vtype
                elif _self_attr(target) and fi.cls is not None:
                    attr = _self_attr(target)
                    kind = _lock_ctor_kind(value)
                    if kind:
                        fi.cls.lock_attrs.setdefault(attr, kind)
                    elif _is_sync_ctor(value):
                        fi.cls.sync_attrs.add(attr)
                    elif vtype:
                        fi.cls.attr_types.setdefault(attr, vtype)
                    elif isinstance(value, ast.Name) \
                            and value.id in fi.local_types:
                        fi.cls.attr_types.setdefault(
                            attr, fi.local_types[value.id])

    def _value_class(self, fi: FuncInfo, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            dotted = _dotted(value.func)
            if dotted:
                return self._resolve_class_name(fi.ctx, dotted)
        if isinstance(value, ast.Name):
            return fi.local_types.get(value.id)
        return None

    # ------------------------------------------------------------------
    # pass 3: per-function facts

    def _collect_facts(self) -> None:
        for fi in self.functions.values():
            _FactWalker(self, fi).run()
        for ci in self.classes.values():
            if ci.is_thread_subclass and "run" in ci.methods:
                self.spawns.append(SpawnSite(
                    kind="thread-subclass", func=None,
                    target=ci.methods["run"].key, node=ci.node, ctx=ci.ctx,
                    owner_class=ci))
        for e in self.call_edges:
            self._callers.setdefault(e.callee, []).append(e)
            self._callees.setdefault(e.caller, []).append(e)

    def receiver_class(self, fi: FuncInfo, expr: ast.AST) -> Optional[str]:
        """Type of a receiver expression: self / typed local / typed
        self-attribute, looked up through the enclosing-def chain."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fi.cls is not None:
                return fi.cls.key
            f: Optional[FuncInfo] = fi
            while f is not None:
                if expr.id in f.local_types:
                    return f.local_types[expr.id]
                f = f.parent
            return None
        attr = _self_attr(expr)
        if attr and fi.cls is not None:
            for ci in self._mro(fi.cls.key):
                if attr in ci.attr_types:
                    return ci.attr_types[attr]
        return None

    def _module_key(self, fi: FuncInfo, dotted: str) -> Optional[str]:
        """Resolve a dotted receiver to a program module key, handling
        both ``import pkg.mod`` and ``from pkg import mod`` spellings."""
        mod = self.modules[fi.ctx.module]
        if dotted in mod.from_names:
            m, a = mod.from_names[dotted]
            key = f"{m}.{a}"
            if key in self.modules:
                return key
            return None
        key = mod.imports.get(dotted, dotted)
        return key if key in self.modules else None

    def resolve_lock(self, fi: FuncInfo, expr: ast.AST) -> Optional[LockId]:
        """``with <expr>:`` — is expr a known lock?"""
        if isinstance(expr, ast.Name):
            mod = self.modules[fi.ctx.module]
            if expr.id in mod.module_locks:
                return LockId(fi.ctx.module, expr.id,
                              mod.module_locks[expr.id])
            if expr.id in mod.from_names:
                m, a = mod.from_names[expr.id]
                target = self.modules.get(m)
                if target and a in target.module_locks:
                    return LockId(m, a, target.module_locks[a])
            return None
        if isinstance(expr, ast.Attribute):
            owner = self.receiver_class(fi, expr.value)
            if owner:
                kind = self.lock_kind(owner, expr.attr)
                if kind:
                    return LockId(owner, expr.attr, kind)
            # module-level lock referenced through an import alias
            dotted = _dotted(expr.value)
            if dotted:
                mkey = self._module_key(fi, dotted)
                target = self.modules.get(mkey) if mkey else None
                if target and expr.attr in target.module_locks:
                    return LockId(mkey, expr.attr,
                                  target.module_locks[expr.attr])
        return None

    def resolve_callable(self, fi: FuncInfo,
                         expr: ast.AST) -> Optional[str]:
        """Function key a callable expression refers to, if resolvable."""
        if isinstance(expr, ast.Name):
            f: Optional[FuncInfo] = fi
            while f is not None:
                if expr.id in f.nested:
                    return f.nested[expr.id]
                f = f.parent
            mod = self.modules[fi.ctx.module]
            if expr.id in mod.functions:
                return mod.functions[expr.id]
            if expr.id in mod.from_names:
                m, a = mod.from_names[expr.id]
                target = self.modules.get(m)
                if target and a in target.functions:
                    return target.functions[a]
                if target and a in target.classes:
                    init = self.lookup_method(target.classes[a], "__init__")
                    return init.key if init else None
            if expr.id in mod.classes:
                init = self.lookup_method(mod.classes[expr.id], "__init__")
                return init.key if init else None
            return None
        if isinstance(expr, ast.Attribute):
            owner = self.receiver_class(fi, expr.value)
            if owner:
                m = self.lookup_method(owner, expr.attr)
                return m.key if m else None
            dotted = _dotted(expr.value)
            if dotted:
                mkey = self._module_key(fi, dotted)
                target = self.modules.get(mkey) if mkey else None
                if target:
                    if expr.attr in target.functions:
                        return target.functions[expr.attr]
                    if expr.attr in target.classes:
                        init = self.lookup_method(
                            target.classes[expr.attr], "__init__")
                        return init.key if init else None
                    if expr.attr in target.from_names:
                        # one re-export hop (package __init__ facades)
                        m2, a2 = target.from_names[expr.attr]
                        t2 = self.modules.get(m2)
                        if t2 and a2 in t2.functions:
                            return t2.functions[a2]
        return None

    # ------------------------------------------------------------------
    # pass 4: roots, reachability, entry-lock fixpoints

    def _fixpoints(self) -> None:
        # requires-lock directives: the annotated function's entry set is
        # guaranteed to hold the named locks (trusted in the body; call
        # sites are verified by QT008)
        self.requires: Dict[str, FrozenSet[LockId]] = {}
        for k, fi in self.functions.items():
            locks: Set[LockId] = set()
            for raw in fi.requires_raw:
                cname, _, attr = raw.rpartition(".")
                if not cname or not attr:
                    continue
                clskey = self._resolve_class_name(fi.ctx, cname)
                if clskey is None:
                    continue
                kind = self.lock_kind(clskey, attr) or "lock"
                locks.add(LockId(clskey, attr, kind))
            if locks:
                self.requires[k] = frozenset(locks)

        root_targets: Dict[str, str] = {}
        for s in self.spawns:
            if s.target and s.target in self.functions:
                root_targets.setdefault(s.target, s.root_id)
        thread_bodies = set(root_targets)

        # main seeds: every public entry point that is not a thread body
        main_seeds = [
            k for k, f in self.functions.items()
            if k not in thread_bodies and not (
                f.name.startswith("_") and not f.name.startswith("__"))
            and not (f.cls is not None and f.cls.is_thread_subclass
                     and f.name == "run")
            and f.parent is None
        ]

        def reach(seeds: Sequence[str]) -> Set[str]:
            seen: Set[str] = set()
            stack = list(seeds)
            while stack:
                k = stack.pop()
                if k in seen:
                    continue
                seen.add(k)
                for e in self._callees.get(k, ()):
                    if e.callee not in seen:
                        stack.append(e.callee)
            return seen

        self.roots_of: Dict[str, Set[str]] = {k: set()
                                              for k in self.functions}
        for fk in reach(main_seeds):
            self.roots_of[fk].add(MAIN_ROOT)
        self.root_labels: Dict[str, str] = {MAIN_ROOT: "main"}
        for s in self.spawns:
            if not s.target or s.target not in self.functions:
                continue
            rid = s.root_id
            self.root_labels[rid] = \
                f"{self.functions[s.target].qual} [{s.kind}]"
            for fk in reach([s.target]):
                self.roots_of[fk].add(rid)

        # entry-lock fixpoints: must (intersection) and may (union)
        self.entry_must: Dict[str, Optional[FrozenSet[LockId]]] = {
            k: None for k in self.functions}
        self.entry_may: Dict[str, FrozenSet[LockId]] = {
            k: frozenset() for k in self.functions}
        empty: FrozenSet[LockId] = frozenset()
        for k in list(thread_bodies) + main_seeds:
            self.entry_must[k] = self.requires.get(k, empty)
        for k in self.requires:  # annotated helpers keep their floor
            if self.entry_must[k] is None:
                self.entry_must[k] = self.requires[k]
        for _ in range(40):  # call-graph depth bound; converges far sooner
            changed = False
            for e in self.call_edges:
                caller_must = self.entry_must.get(e.caller)
                if caller_must is not None:
                    contrib = caller_must | e.locks
                    cur = self.entry_must.get(e.callee)
                    nxt = contrib if cur is None else (cur & contrib)
                    nxt |= self.requires.get(e.callee, empty)
                    if nxt != cur:
                        self.entry_must[e.callee] = nxt
                        changed = True
                may = self.entry_may.get(e.caller, empty) | e.locks
                cur_may = self.entry_may.get(e.callee, empty)
                if not may <= cur_may:
                    self.entry_may[e.callee] = cur_may | may
                    changed = True
            if not changed:
                break

    # ------------------------------------------------------------------
    # consumers

    def held_at(self, acc: Access) -> FrozenSet[LockId]:
        entry = self.entry_must.get(acc.func.key) or frozenset()
        return acc.locks | entry

    def order_edges(self) -> List[Tuple[LockId, LockId, _Acquisition]]:
        """(held, acquired, site) for every acquisition made while some
        other lock may be held (lexically or via any caller).

        Cross-lock edges use the *may* entry set (an inversion exists if
        any path nests the pair); the self-edge — re-acquiring a
        non-reentrant ``Lock`` you already hold, an instant deadlock —
        uses lexical + *must* context only, so a public helper that is
        merely callable both ways doesn't false-positive."""
        out = []
        for acq in self.acquisitions:
            may = self.entry_may.get(acq.func.key, frozenset())
            must = self.entry_must.get(acq.func.key) or frozenset()
            for held in acq.held_before | may:
                if held != acq.lock:
                    out.append((held, acq.lock, acq))
            if acq.lock.kind == "lock" \
                    and acq.lock in (acq.held_before | must):
                out.append((acq.lock, acq.lock, acq))
        return out


class _FactWalker:
    """One pass over a single function body (nested defs excluded —
    they are separate FuncInfos) collecting calls, spawns, accesses and
    acquisitions with the lexical lock set threaded through."""

    def __init__(self, prog: Program, fi: FuncInfo):
        self.prog = prog
        self.fi = fi
        self.globals_decl: Set[str] = set()
        # locals constructed in this body (``x = SomeClass(...)``): they
        # are pre-publication, so writes through them are construction
        self.fresh: Set[str] = set()
        self.in_init = (fi.name in _INIT_NAMES and fi.cls is not None
                        and fi.parent is None)

    def run(self) -> None:
        for stmt in self.fi.node.body:
            self._walk(stmt, frozenset())

    # -- statement/expression walk with lock context -------------------
    def _walk(self, node: ast.AST, locks: FrozenSet[LockId]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate FuncInfo / class scope
        if isinstance(node, ast.Global):
            self.globals_decl.update(node.names)
            return
        if isinstance(node, ast.With):
            inner = locks
            for item in node.items:
                self._visit_expr(item.context_expr, locks)
                lid = self.prog.resolve_lock(self.fi, item.context_expr)
                if lid is not None:
                    self.prog.acquisitions.append(_Acquisition(
                        func=self.fi, lock=lid, held_before=locks,
                        node=item.context_expr))
                    inner = inner | {lid}
            for child in node.body:
                self._walk(child, inner)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                self._record_store(t, locks,
                                   augmented=isinstance(node, ast.AugAssign))
            if node.value is not None:
                self._visit_expr(node.value, locks)
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if self._is_fresh_ctor(node.value):
                    self.fresh.add(name)
                else:
                    self.fresh.discard(name)
            return
        if isinstance(node, ast.expr):
            self._visit_expr(node, locks)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, locks)

    def _visit_expr(self, node: ast.AST, locks: FrozenSet[LockId]) -> None:
        if isinstance(node, ast.Call):
            self._visit_call(node, locks)
            return
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            self._record_attr_access(node, locks, write=False)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self._record_global_access(node, locks, write=False)
        if isinstance(node, ast.Lambda):
            return  # opaque; a lambda thread target stays unresolved
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, locks)
            else:
                self._walk(child, locks)

    # -- stores --------------------------------------------------------
    def _record_store(self, target: ast.AST, locks: FrozenSet[LockId],
                      augmented: bool = False) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._record_store(el, locks)
            return
        node = target
        if isinstance(node, ast.Subscript):
            self._visit_expr(node.slice, locks)
            node = node.value
        if isinstance(node, ast.Attribute):
            self._record_attr_access(node, locks, write=True)
            self._visit_expr(node.value, locks)
        elif isinstance(node, ast.Name):
            self._record_global_access(node, locks, write=True)

    def _record_attr_access(self, node: ast.Attribute,
                            locks: FrozenSet[LockId], write: bool) -> None:
        owner = self.prog.receiver_class(self.fi, node.value)
        if owner is None:
            return
        via_self = (isinstance(node.value, ast.Name)
                    and node.value.id == "self")
        owner_cls = self.fi.cls
        in_init = False
        if write:
            f = self.fi
            while f is not None:
                if f.parent is None and f.name in _INIT_NAMES \
                        and f.cls is not None and f.cls.key == owner:
                    in_init = True
                    break
                f = f.parent
            # alternate constructors build self before publication too
            if not in_init and self.fi.parent is None \
                    and owner_cls is not None and owner_cls.key == owner \
                    and _is_constructor_like(self.fi.node):
                in_init = True
        # a local built here is pre-publication regardless of its class
        if isinstance(node.value, ast.Name) and node.value.id in self.fresh:
            in_init = True
        self.prog.accesses.append(Access(
            owner=owner, attr=node.attr, write=write, func=self.fi,
            node=node, locks=locks, in_init=in_init, via_self=via_self))

    def _record_global_access(self, node: ast.Name,
                              locks: FrozenSet[LockId], write: bool) -> None:
        mod = self.prog.modules[self.fi.ctx.module]
        if write:
            if node.id not in self.globals_decl:
                return
            mod.globals_written.add(node.id)
        elif node.id not in mod.globals_written \
                and node.id not in self.globals_decl:
            return
        self.prog.accesses.append(Access(
            owner=self.fi.ctx.module, attr=node.id, write=write,
            func=self.fi, node=node, locks=locks, in_init=False,
            via_self=False))

    def _is_fresh_ctor(self, value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        dotted = _dotted(value.func)
        if not dotted:
            return False
        key = self.prog._resolve_class_name(self.fi.ctx, dotted)
        return key is not None and key in self.prog.classes

    # -- calls ---------------------------------------------------------
    def _visit_call(self, node: ast.Call, locks: FrozenSet[LockId]) -> None:
        prog, fi = self.prog, self.fi
        dotted = _dotted(node.func)
        is_thread_ctor = dotted is not None and (
            dotted in ("threading.Thread", "Thread")
            and self._names_threading(dotted))
        if is_thread_ctor:
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = prog.resolve_callable(fi, kw.value)
            prog.spawns.append(SpawnSite(
                kind="thread", func=fi, target=target, node=node,
                ctx=fi.ctx, owner_class=fi.cls))
            for a in node.args:
                self._visit_expr(a, locks)
            for kw in node.keywords:
                self._visit_expr(kw.value, locks)
            return
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "submit" and node.args:
            target = prog.resolve_callable(fi, node.args[0])
            prog.spawns.append(SpawnSite(
                kind="submit", func=fi, target=target, node=node,
                ctx=fi.ctx, owner_class=fi.cls,
                borrowed=_receiver_is_param(fi, node.func.value)))
            if target:
                # pool workers run the submitted fn with a fresh stack;
                # root seeding (not a call edge) models the lock context
                pass
            for a in node.args[1:]:
                self._visit_expr(a, locks)
            self._visit_expr(node.func.value, locks)
            return
        # mutator calls count as writes on the receiver attribute
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS \
                and isinstance(node.func.value, ast.Attribute):
            self._record_attr_access(node.func.value, locks, write=True)
        callee = prog.resolve_callable(fi, node.func)
        if callee:
            prog.call_edges.append(CallEdge(
                caller=fi.key, callee=callee, locks=locks, node=node))
        # conservative: a function reference passed as an argument may
        # be invoked by the callee (callbacks, functools.partial, jit)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, (ast.Name, ast.Attribute)):
                ref = prog.resolve_callable(fi, arg)
                if ref and ref != callee:
                    prog.call_edges.append(CallEdge(
                        caller=fi.key, callee=ref, locks=locks,
                        node=arg, indirect=True))
        for a in node.args:
            self._visit_expr(a, locks)
        for kw in node.keywords:
            self._visit_expr(kw.value, locks)
        if isinstance(node.func, ast.Attribute):
            self._visit_expr(node.func.value, locks)

    def _names_threading(self, dotted: str) -> bool:
        if dotted == "threading.Thread":
            mod = self.prog.modules[self.fi.ctx.module]
            return mod.imports.get("threading", "threading") == "threading"
        mod = self.prog.modules[self.fi.ctx.module]
        src = mod.from_names.get("Thread")
        return src is not None and src[0] == "threading"


# ---------------------------------------------------------------------------
# small AST helpers


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _receiver_is_param(fi: FuncInfo, recv: ast.AST) -> bool:
    """True when ``recv`` names a parameter of the enclosing def (or of
    an enclosing def, for closures) — a pool passed in is owned by the
    caller, so its worker lifecycle is not this scope's to reap."""
    if not isinstance(recv, ast.Name):
        return False
    cur: Optional[FuncInfo] = fi
    while cur is not None:
        a = cur.node.args
        names = [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        if recv.id in names:
            return True
        cur = cur.parent
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _lock_ctor_kind(value: ast.AST) -> Optional[str]:
    if isinstance(value, ast.BoolOp):
        # ``self._lock = lock or threading.Lock()`` (injected-lock idiom)
        for v in value.values:
            kind = _lock_ctor_kind(v)
            if kind:
                return kind
        return None
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted(value.func)
    if not dotted:
        return None
    head, _, leaf = dotted.rpartition(".")
    if leaf in _LOCK_CTORS and head in ("", "threading"):
        return _LOCK_CTORS[leaf]
    return None


# Internally-synchronized stdlib primitives: mutating them without a
# user lock is safe by contract, so QT008 must not treat e.g.
# ``self._stop.clear()`` (an Event) as an unguarded write.
_SYNC_CTORS = {
    "Event": ("", "threading"),
    "Semaphore": ("", "threading"),
    "BoundedSemaphore": ("", "threading"),
    "Barrier": ("", "threading"),
    "Queue": ("", "queue"),
    "SimpleQueue": ("", "queue"),
    "LifoQueue": ("", "queue"),
    "PriorityQueue": ("", "queue"),
}


def _is_sync_ctor(value: ast.AST) -> bool:
    if isinstance(value, ast.BoolOp):
        return any(_is_sync_ctor(v) for v in value.values)
    if not isinstance(value, ast.Call):
        return False
    dotted = _dotted(value.func)
    if not dotted:
        return False
    head, _, leaf = dotted.rpartition(".")
    return leaf in _SYNC_CTORS and head in _SYNC_CTORS[leaf]


def _literal_guarded(cls: ast.ClassDef) -> Optional[Dict[str, str]]:
    for stmt in cls.body:
        target = value = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            target, value = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            target, value = stmt.target.id, stmt.value
        if target != "_guarded_by" or not isinstance(value, ast.Dict):
            continue
        out: Dict[str, str] = {}
        for k, v in zip(value.keys, value.values):
            if isinstance(k, ast.Constant) and isinstance(v, ast.Constant) \
                    and isinstance(k.value, str) and isinstance(v.value, str):
                out[k.value] = v.value
        return out
    return None


_REQUIRES_RE = re.compile(
    r"#\s*quiverlint:\s*requires-lock\[([A-Za-z0-9_.\s,]+)\]")


def _requires_directives(ctx: ModuleContext, node: ast.AST) -> List[str]:
    """``# quiverlint: requires-lock[Class._lock]`` on the ``def`` line
    (or the comment line directly above it): the function's contract is
    that every caller already holds the named lock — the analyzer
    assumes it inside the body and verifies it at resolved call sites.
    """
    out: List[str] = []
    lineno = getattr(node, "lineno", 0)
    for ln in (lineno - 1, lineno):  # line above, then the def line
        if 1 <= ln <= len(ctx.lines):
            m = _REQUIRES_RE.search(ctx.lines[ln - 1])
            if m:
                out.extend(p.strip() for p in m.group(1).split(",")
                           if p.strip())
    return out


def _own_statements(node: ast.AST) -> Iterator[ast.AST]:
    """All descendant statements of a def, not descending into nested
    defs or classes (those are separate scopes)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def _is_constructor_like(node: ast.AST) -> bool:
    """classmethod constructors (``from_*`` / decorated classmethod that
    returns an instance) build objects before publication, like
    __init__."""
    decos = getattr(node, "decorator_list", [])
    for d in decos:
        if isinstance(d, ast.Name) and d.id == "classmethod":
            return True
    return False
