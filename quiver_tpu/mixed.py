"""Adaptive TPU+CPU mixed sampling — TPU-native ``MixedGraphSageSampler``.

Reference parity: ``srcs/python/quiver/pyg/sage_sampler.py:180-376``
(``SampleJob`` abstract task list, worker process pool, per-epoch feedback
``decide_task_num`` re-splitting the task budget by measured device vs CPU
sample time).

TPU-first redesign: CPU sampling runs in **threads**, not processes — the
native sampler (``cpp/csrc/quiver_cpu.cpp``) holds no GIL during its call,
so a thread pool gets full parallelism without pickling graphs across
process boundaries (the whole reason the reference needed its IPC
machinery).  Device sampling stays on the main thread feeding the TPU; the
feedback loop is the same time-ratio heuristic as the reference.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Generic, Iterator, List, Sequence, TypeVar

import numpy as np

from . import telemetry
from .resilience.shutdown import join_and_reap
from .sampler import GraphSageSampler, SampledBatch
from .utils.topology import CSRTopo

T_co = TypeVar("T_co", covariant=True)

__all__ = ["SampleJob", "MixedGraphSageSampler", "RangeSampleJob"]


class SampleJob(Generic[T_co]):
    """Abstract indexable task list (parity: sage_sampler.py:180-195)."""

    def __getitem__(self, index) -> T_co:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError


class RangeSampleJob(SampleJob):
    """Seed ids chunked into fixed-size batches."""

    def __init__(self, ids: np.ndarray, batch_size: int, seed: int = 0):
        self.ids = np.asarray(ids)
        self.batch_size = batch_size
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return (len(self.ids) + self.batch_size - 1) // self.batch_size

    def __getitem__(self, i):
        return self.ids[i * self.batch_size: (i + 1) * self.batch_size]

    def shuffle(self):
        self._rng.shuffle(self.ids)


class MixedGraphSageSampler:
    """Iterate a :class:`SampleJob`, splitting work TPU/CPU adaptively.

    Modes (parity with the reference's four): ``"TPU_CPU_MIXED"``
    (default; aliases ``UVA_CPU_MIXED``/``GPU_CPU_MIXED`` accepted),
    ``"TPU_ONLY"`` (aliases ``UVA_ONLY``/``GPU_ONLY``), ``"CPU_ONLY"``.

    Iterating yields ``(SampledBatch, source)`` per task, where source is
    ``"tpu"`` or ``"cpu"``.
    """

    _ALIASES = {
        "UVA_CPU_MIXED": "TPU_CPU_MIXED", "GPU_CPU_MIXED": "TPU_CPU_MIXED",
        "UVA_ONLY": "TPU_ONLY", "GPU_ONLY": "TPU_ONLY",
    }

    def __init__(self, csr_topo: CSRTopo, sizes: Sequence[int],
                 sample_job: SampleJob, device=None,
                 mode: str = "TPU_CPU_MIXED", num_workers: int = 4,
                 frontier_caps=None):
        mode = self._ALIASES.get(mode, mode)
        assert mode in ("TPU_CPU_MIXED", "TPU_ONLY", "CPU_ONLY"), mode
        if num_workers < 1 and mode != "TPU_ONLY":
            # with 0 workers the CPU lane cannot run: mixed mode would
            # silently degenerate (avg_cpu_time stays None, feedback never
            # engages) and CPU_ONLY would crash mid-epoch in array_split
            if mode == "CPU_ONLY":
                raise ValueError("CPU_ONLY requires num_workers >= 1")
            import warnings
            warnings.warn(
                "TPU_CPU_MIXED with num_workers=0 cannot run a CPU lane; "
                "falling back to TPU_ONLY", stacklevel=2
            )
            mode = "TPU_ONLY"
        self.mode = mode
        self.job = sample_job
        self.num_workers = num_workers
        self.tpu_sampler = (
            GraphSageSampler(csr_topo, sizes, device=device, mode="TPU",
                             frontier_caps=frontier_caps)
            if mode != "CPU_ONLY" else None
        )
        self.cpu_sampler = (
            GraphSageSampler(csr_topo, sizes, mode="CPU")
            if mode != "TPU_ONLY" else None
        )
        # feedback state (parity: decide_task_num, sage_sampler.py:272-288)
        self.avg_tpu_time = None
        self.avg_cpu_time = None

    def _decide_cpu_share(self, n_tasks: int) -> int:
        if self.mode == "CPU_ONLY":
            return n_tasks
        if self.mode == "TPU_ONLY":
            return 0
        if self.avg_tpu_time is None or self.avg_cpu_time is None:
            # seeding epoch(s): both lanes must get measured or the
            # feedback loop can never engage — at least one CPU task
            # whenever there are two or more (a 2-task job previously
            # seeded 0 CPU tasks, left avg_cpu_time None forever, and
            # the next epoch's steady-state path raised on the None)
            if n_tasks < 2:
                return 0
            return min(self.num_workers, max(1, n_tasks // 4))
        # steady state: give CPU workers the share that equalizes finish time
        tpu_rate = 1.0 / max(self.avg_tpu_time, 1e-9)
        cpu_rate = self.num_workers / max(self.avg_cpu_time, 1e-9)
        share = n_tasks * cpu_rate / (tpu_rate + cpu_rate)
        return int(min(share, n_tasks))

    def __iter__(self) -> Iterator:
        self.job.shuffle()
        n = len(self.job)
        cpu_share = self._decide_cpu_share(n)
        cpu_tasks = list(range(n - cpu_share, n))
        tpu_tasks = list(range(0, n - cpu_share))
        results: "queue.Queue" = queue.Queue()
        cpu_times: List[float] = []
        stop = threading.Event()

        def cpu_worker(task_ids):
            for t in task_ids:
                if stop.is_set():
                    return
                try:
                    t0 = time.perf_counter()
                    batch = self.cpu_sampler.sample(self.job[t])
                    dt = time.perf_counter() - t0
                    cpu_times.append(dt)
                    telemetry.counter("mixed_tasks_total", lane="cpu").inc()
                    telemetry.histogram("mixed_task_seconds",
                                        lane="cpu").observe(dt)
                    results.put((batch, "cpu"))
                except BaseException as e:  # surface to the consumer
                    results.put((e, "error"))

        threads = []
        if cpu_tasks and self.cpu_sampler is not None:
            chunks = np.array_split(np.asarray(cpu_tasks), self.num_workers)
            for c in chunks:
                if len(c) == 0:
                    continue
                th = threading.Thread(target=cpu_worker, args=(c.tolist(),),
                                      daemon=True)
                th.start()
                threads.append(th)

        tpu_times: List[float] = []
        produced = 0
        try:
            for t in tpu_tasks:
                t0 = time.perf_counter()
                batch = self.tpu_sampler.sample(self.job[t])
                # the adaptive CPU/TPU split needs the true TPU wall time,
                # so this lane times to completion on purpose
                # quiverlint: ignore[QT001]
                batch.n_id.block_until_ready()
                dt = time.perf_counter() - t0
                tpu_times.append(dt)
                telemetry.counter("mixed_tasks_total", lane="tpu").inc()
                telemetry.histogram("mixed_task_seconds",
                                    lane="tpu").observe(dt)
                yield batch, "tpu"
                produced += 1
                while not results.empty():
                    item = results.get_nowait()
                    if item[1] == "error":
                        raise item[0]
                    yield item
                    produced += 1
            while produced < n:
                item = results.get(timeout=300)
                if item[1] == "error":
                    raise item[0]
                yield item
                produced += 1
        finally:
            stop.set()
            join_and_reap(threads, 5.0, component="mixed.cpu_workers")
        if tpu_times:
            self.avg_tpu_time = float(np.mean(tpu_times))
            telemetry.gauge("mixed_avg_task_seconds", lane="tpu").set(
                self.avg_tpu_time)
        if cpu_times:
            self.avg_cpu_time = float(np.mean(cpu_times))
            telemetry.gauge("mixed_avg_task_seconds", lane="cpu").set(
                self.avg_cpu_time)
