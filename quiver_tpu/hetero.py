"""Heterogeneous graph topology + sampler (R-GAT / mag240m-class workloads).

Reference parity: the reference's mag240m benchmark samples a heterogeneous
graph through PyG/DGL hetero loaders on top of quiver's feature store
(``/root/reference/benchmarks/ogbn-mag240m/``); quiver itself is
type-agnostic.  Here hetero sampling is first-class: one CSR per relation,
per-relation fanouts, and the same dedup-free positional frontier scheme as
the homogeneous TPU pipeline (``sampler.py``) — per node type.

A relation is ``(src_type, name, dst_type)`` and its CSR rows are DST
nodes with neighbor lists of SRC nodes (we sample sources for targets,
message flow src -> dst).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from flax import struct

from .ops.sample import sample_neighbors
from .utils.topology import CSRTopo

__all__ = ["HeteroCSRTopo", "HeteroGraphSageSampler", "HeteroLayerBlock",
           "HeteroSampledBatch", "HeteroFeature"]

Relation = Tuple[str, str, str]


@struct.dataclass
class HeteroLayerBlock:
    """One (relation, hop) bipartite block; ``relation`` is static pytree
    metadata so batches cross jit boundaries."""

    nbr_local: jax.Array   # [T, k] positions into the SRC type's frontier
    mask: jax.Array        # [T, k]
    num_targets: jax.Array  # valid targets (prefix of DST frontier)
    relation: Relation = struct.field(pytree_node=False)


@struct.dataclass
class HeteroSampledBatch:
    # per node type: padded frontier ids + validity
    n_id: Dict[str, jax.Array]
    n_id_mask: Dict[str, jax.Array]
    # layers[l] = list of HeteroLayerBlock for hop l, OUTERMOST first
    layers: Tuple[Tuple[HeteroLayerBlock, ...], ...]
    batch_size: int = struct.field(pytree_node=False)
    seed_type: str = struct.field(pytree_node=False)


class HeteroCSRTopo:
    """Dict of per-relation CSRs + per-type node counts."""

    def __init__(self, relations: Dict[Relation, CSRTopo],
                 node_counts: Dict[str, int]):
        self.relations = dict(relations)
        self.node_counts = dict(node_counts)
        for (s, _, d), topo in self.relations.items():
            assert s in self.node_counts and d in self.node_counts, (s, d)
            assert topo.node_count <= self.node_counts[d], (
                f"relation rows ({topo.node_count}) exceed {d} count"
            )

    @classmethod
    def from_edge_index_dict(cls, edge_index_dict: Dict[Relation, np.ndarray],
                             node_counts: Dict[str, int]):
        rels = {}
        for rel, ei in edge_index_dict.items():
            s, _, d = rel
            ei = np.asarray(ei)
            # rows = dst, neighbors = src
            rels[rel] = CSRTopo(edge_index=np.stack([ei[1], ei[0]]),
                                node_count=node_counts[d])
        return cls(rels, node_counts)

    def node_types(self) -> List[str]:
        return list(self.node_counts)

    def to_device(self, device=None):
        for topo in self.relations.values():
            topo.to_device(device)
        return self


class HeteroFeature:
    """Per-node-type feature stores with one batch-level lookup.

    Thin dict-of-:class:`quiver_tpu.Feature` with the ergonomics the
    hetero pipeline needs: ``hf.lookup(batch)`` returns the feature dict
    for every type's (padded) frontier, empty types included.
    """

    def __init__(self, features: Dict[str, "Feature"]):
        self.features = dict(features)

    @classmethod
    def from_cpu_tensors(cls, tensors: Dict[str, np.ndarray],
                         device_cache_size="1G", **kwargs):
        from .feature import Feature

        return cls({
            t: Feature(device_cache_size=device_cache_size,
                       **kwargs).from_cpu_tensor(x)
            for t, x in tensors.items()
        })

    def __getitem__(self, key):
        node_type, ids = key
        return self.features[node_type][ids]

    def lookup(self, batch: "HeteroSampledBatch") -> Dict[str, jax.Array]:
        out = {}
        for t, f in self.features.items():
            n_id = batch.n_id.get(t)
            if n_id is None or n_id.shape[0] == 0:
                out[t] = jnp.zeros((0, f.dim), jnp.float32)
            else:
                out[t] = f[np.asarray(n_id)]
        return out


class HeteroGraphSageSampler:
    """Multi-hop hetero sampler with per-relation fanouts.

    Args:
      topo: :class:`HeteroCSRTopo`.
      sizes: per-hop fanout dict ``{relation: k}`` or list of such dicts
        (one per hop); a plain int applies to every relation.
      seed_type: node type of the seeds (e.g. ``"paper"``).

    The frontier of each node type grows by appending sampled sources
    (positional relabel, no dedup) — each hop emits one block per relation
    whose DST type currently has a frontier.
    """

    def __init__(self, topo: HeteroCSRTopo, sizes, num_hops: int = None,
                 seed_type: str = "paper", device=None,
                 gather_mode: str = "auto", sample_rng: str = "auto"):
        self.topo = topo
        from .config import resolve_gather_mode, resolve_sample_rng

        self.gather_mode = resolve_gather_mode(gather_mode, sample_rng)
        self.sample_rng = resolve_sample_rng(sample_rng, self.gather_mode)
        if isinstance(sizes, (list, tuple)):
            self.hop_sizes = [self._norm(s) for s in sizes]
        else:
            assert num_hops is not None, "need num_hops with scalar sizes"
            self.hop_sizes = [self._norm(sizes)] * num_hops
        self.seed_type = seed_type
        self.device = device
        from .recovery.registry import program_cache

        self._jitted = program_cache("hetero", owner=self)
        topo.to_device(device)

    def _norm(self, s) -> Dict[Relation, int]:
        if isinstance(s, int):
            return {rel: s for rel in self.topo.relations}
        return dict(s)

    def _pipeline(self, seeds, key):
        nt = self.topo.node_types()
        frontiers = {
            t: (jnp.zeros((0,), jnp.int32), jnp.zeros((0,), bool))
            for t in nt
        }
        frontiers[self.seed_type] = (
            seeds.astype(jnp.int32),
            jnp.ones((seeds.shape[0],), bool),
        )
        all_layers = []
        for hop, hop_size in enumerate(self.hop_sizes):
            blocks = []
            # snapshot: sample for the frontier as it stood at hop start
            snap = {t: frontiers[t] for t in nt}
            for rel, k in hop_size.items():
                s_t, _, d_t = rel
                dst_ids, dst_mask = snap[d_t]
                if dst_ids.shape[0] == 0:
                    continue
                indptr, indices = self.topo.relations[rel].to_device(
                    self.device
                )
                key, sub = jax.random.split(key)
                out = sample_neighbors(indptr, indices, dst_ids, k, sub,
                                       seed_mask=dst_mask,
                                       gather_mode=self.gather_mode,
                                       sample_rng=self.sample_rng)
                src_ids, src_mask = frontiers[s_t]
                base = src_ids.shape[0]
                t_len = dst_ids.shape[0]
                pos = (base
                       + jnp.arange(t_len, dtype=jnp.int32)[:, None] * k
                       + jnp.arange(k, dtype=jnp.int32)[None, :])
                blocks.append(HeteroLayerBlock(
                    relation=rel,
                    nbr_local=jnp.where(out.mask, pos, 0),
                    mask=out.mask,
                    num_targets=dst_mask.sum().astype(jnp.int32),
                ))
                frontiers[s_t] = (
                    jnp.concatenate(
                        [src_ids,
                         jnp.where(out.mask, out.nbrs, 0).reshape(-1)]
                    ),
                    jnp.concatenate([src_mask, out.mask.reshape(-1)]),
                )
            all_layers.append(tuple(blocks))
        n_id = {t: frontiers[t][0] for t in nt}
        n_mask = {t: frontiers[t][1] for t in nt}
        return n_id, n_mask, tuple(all_layers[::-1])

    def sample(self, input_nodes, key=None) -> HeteroSampledBatch:
        seeds = jnp.asarray(np.asarray(input_nodes), jnp.int32)
        B = seeds.shape[0]
        if B not in self._jitted:
            # jit the bound method directly — a fresh lambda here would
            # defeat jax's executable cache if this dict were ever reset
            # quiverlint: ignore[QT014] -- hetero keys on raw B by
            # design: seed counts come from the caller's loader, which
            # fixes the batch size; padding here would ripple through
            # every per-type frontier shape.  seal()/retrace_budget
            # guard the steady state.
            self._jitted[B] = jax.jit(self._pipeline)
        if key is None:
            from .utils.rng import make_key

            key = make_key(np.random.randint(0, 2**31 - 1))
        n_id, n_mask, layers = self._jitted[B](seeds, key)
        return HeteroSampledBatch(
            n_id=n_id, n_id_mask=n_mask, batch_size=B,
            seed_type=self.seed_type, layers=layers,
        )
