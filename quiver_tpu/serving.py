"""GNN serving pipeline — TPU-native batcher / hybrid sampler / server.

Reference parity: ``srcs/python/quiver/serving.py`` —
``RequestBatcher`` (:10-98, workload-aware ``auto_despatch`` routing by
summed per-node ``neighbour_num`` vs a threshold), ``HybridSampler``
(:101-147, CPU sampler workers), ``InferenceServer`` / ``_Debug``
(:150-360, sample→feature→model loops + tp99 accounting).

TPU-first redesign: the reference shards the pipeline over *processes* with
``mp.Manager().Queue``s because CUDA contexts and the GIL force it to.  Here
the single-controller model inverts that: stages are **threads** sharing one
process (the native CPU sampler and XLA release the GIL), queues are
``queue.Queue``, and the device stage uses **bucketed batch shapes** (pad to
the next power of two) so every request size hits a cached jit executable —
the TPU answer to CUDA's any-shape kernel launches.  Routing keeps the same
mechanism: requests whose expected expansion is small run on the CPU
sampler (low latency, no device round-trip), big ones batch onto the TPU.

Fault tolerance (docs/RESILIENCE.md): requests carry absolute deadlines
checked at every stage boundary; the batcher lanes are
:class:`~quiver_tpu.resilience.BoundedLane`s that shed under overload;
each server lane sits behind a :class:`~quiver_tpu.resilience.
CircuitBreaker` and fails over to the other lane (device→CPU via an
inline ``cpu_sampler`` pass, CPU→device via the bucketed forward); and
the named ``chaos.point(...)`` call sites let the chaos suite inject
faults deterministically.  A request is always *answered* — with its
result or a typed resilience error — never silently dropped.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from . import telemetry
from .analysis.staging import no_sync
from .resilience import chaos
from .resilience.breaker import CircuitBreaker
from .resilience.deadline import deadline_for, deadline_scope, \
    shed_if_expired
from .resilience.errors import LaneUnavailable
from .resilience.lanes import BoundedLane, WeightedFairLane
from .resilience.qos import qos_from_config
from .resilience.shutdown import join_and_reap
from .telemetry import flightrec
from .telemetry import timeline as _timeline

__all__ = [
    "RequestBatcher", "HybridSampler", "InferenceServer",
    "InferenceServer_Debug", "ServingRequest", "calibrate_threshold",
]

_STOP = object()

# named fault-injection call sites (no-ops unless a chaos plan is
# installed — one module-global read + None check per fire)
_CHAOS_DEVICE = chaos.point("serving.device_lane")
_CHAOS_CPU = chaos.point("serving.cpu_lane")
_CHAOS_SAMPLER = chaos.point("serving.hybrid_sampler")


@dataclass
class ServingRequest:
    ids: np.ndarray
    client: int
    seq: int
    t_enqueue: float = field(default_factory=time.perf_counter)
    # flight-recorder trace context; None when telemetry is off (every
    # consumer guards, so the None threads through the pipeline for free)
    trace: Optional[object] = None
    # absolute perf_counter deadline; defaults from
    # config.serving_deadline_ms (None = no deadline, checks are free)
    deadline: Optional[float] = None
    # admission-control ordering: under overload the BoundedLanes shed
    # strictly-lower-priority requests first
    priority: int = 0
    # graph version at admission (streaming deployments; None without a
    # StreamingGraph).  The consistency contract is stated against it:
    # the batch serving this request samples a snapshot with
    # version >= graph_version (snapshots only move forward)
    graph_version: Optional[int] = None
    # tenant label as the client sent it (None = untenanted).  QoS
    # admission resolves it through the configured class allowlist and
    # stamps the resolved class on ``tenant_class`` — metrics and fair
    # scheduling only ever see allowlisted class names.
    tenant: Optional[str] = None
    tenant_class: Optional[str] = None

    def __post_init__(self):
        if self.deadline is None:
            self.deadline = deadline_for(self.t_enqueue)
        if self.graph_version is None:
            self.graph_version = flightrec.graph_version()
        if self.trace is None:
            self.trace = flightrec.new_trace()
            if self.trace is not None:
                self.trace.add("enqueue", {"n_ids": int(len(self.ids)),
                                           "client": self.client,
                                           "seq": self.seq})
        if self.trace is not None and self.tenant is not None:
            self.trace.tenant = self.tenant
        if self.trace is not None and _timeline._ON:
            # the admission instant anchors this request's trace_id on
            # the unified timeline; stage slices and the final
            # "request" span (recorder.finish) share it
            _timeline.emit("request.enqueue", cat="serving",
                           attrs={"n_ids": int(len(self.ids)),
                                  "client": self.client},
                           trace=self.trace)

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) \
            >= self.deadline


def _fail_request(req, exc, lane: str, result_queue) -> None:
    """Shared error answer: retained flight record (reason=error) plus
    the typed ``(req, exc)`` tuple on the result queue when one is in
    scope — a failed request is reported, never swallowed."""
    tr = getattr(req, "trace", None)
    if tr is not None:
        tr.add("error", {"type": type(exc).__name__, "message": str(exc)})
        e2e = max(time.perf_counter() - req.t_enqueue, 0.0)
        flightrec.get_recorder().finish(tr, e2e, status="error", lane=lane)
    if result_queue is not None:
        result_queue.put((req, exc))


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class RequestBatcher:
    """Route per-client request streams to the CPU or TPU lane.

    Args:
      stream_queues: input queues, one per client.
      neighbour_num: ``[N]`` expected expansion per node (from
        :func:`quiver_tpu.generate_neighbour_num`).
      threshold: requests with ``sum(neighbour_num[ids]) <= threshold`` go
        to the CPU lane (mode="Auto"), mirroring ``auto_despatch``
        (serving.py:72-95).
      mode: "Auto" | "CPU" | "Device" | "Preparation" (duplicate to both,
        parity serving.py:60-70).
      result_queue: where shed/rejected requests are *answered*.  When
        given, the two lane queues become
        :class:`~quiver_tpu.resilience.BoundedLane`s
        (``config.serving_queue_depth`` capacity, watermark shedding)
        and expired requests are shed at routing; without it the lanes
        stay unbounded and nothing is shed here (there would be no way
        to answer).
      qos: a :class:`~quiver_tpu.resilience.QoSController`, or None to
        resolve from config (``qos_enabled``).  With QoS active, every
        request passes token-bucket admission here (over-quota tenants
        get a typed :class:`~quiver_tpu.resilience.QuotaExceeded`
        answer) and the bounded lanes become
        :class:`~quiver_tpu.resilience.WeightedFairLane`s scheduling
        tenant classes by weight.
    """

    def __init__(self, stream_queues: List["queue.Queue"],
                 neighbour_num: Optional[np.ndarray] = None,
                 threshold: float = 0.0, mode: str = "Auto",
                 result_queue: Optional["queue.Queue"] = None,
                 qos=None):
        assert mode in ("Auto", "CPU", "Device", "Preparation")
        self.stream_queues = stream_queues
        self.neighbour_num = neighbour_num
        self.threshold = threshold
        self.mode = mode
        self.result_queue = result_queue
        self._qos = qos if qos is not None else qos_from_config()
        if result_queue is not None:
            from .config import get_config

            depth = get_config().serving_queue_depth
        else:
            depth = 0
        if depth > 0 and self._qos is not None:
            weights = self._qos.weights()
            default = self._qos.default
            self.cpu_batched_queue = WeightedFairLane(
                "cpu", weights, default_class=default,
                result_queue=result_queue)
            self.device_batched_queue = WeightedFairLane(
                "device", weights, default_class=default,
                result_queue=result_queue)
        elif depth > 0:
            self.cpu_batched_queue = BoundedLane(
                "cpu", result_queue=result_queue)
            self.device_batched_queue = BoundedLane(
                "device", result_queue=result_queue)
        else:
            self.cpu_batched_queue = queue.Queue()
            self.device_batched_queue = queue.Queue()
        self._threads: List[threading.Thread] = []

    def _route(self, req: ServingRequest):
        if shed_if_expired(req, self.result_queue, "batcher"):
            return
        q = self._qos
        if q is not None and not q.admit(req, self.result_queue):
            return
        if q is not None and q.route_floor_to_cpu and self.mode == "Auto" \
                and req.tenant_class == q.floor:
            # degradation ladder L3: the lowest class rides the CPU
            # lane so the device batch stays clear for paying tiers
            self._put(self.cpu_batched_queue, req, "cpu")
            return
        if self.mode == "CPU":
            self._put(self.cpu_batched_queue, req, "cpu")
        elif self.mode == "Device":
            self._put(self.device_batched_queue, req, "device")
        elif self.mode == "Preparation":
            self._put(self.cpu_batched_queue, req, "both")
            self.device_batched_queue.put(req)
        else:
            load = (
                float(self.neighbour_num[req.ids].sum())
                if self.neighbour_num is not None else float("inf")
            )
            if load <= self.threshold:
                self._put(self.cpu_batched_queue, req, "cpu", load)
            else:
                self._put(self.device_batched_queue, req, "device", load)

    @staticmethod
    def _put(q: "queue.Queue", req: ServingRequest, lane: str,
             load: Optional[float] = None):
        if req.trace is not None:
            attrs = {"lane": lane}
            if load is not None and load != float("inf"):
                attrs["load"] = load
            # quiverlint: ignore[QT008] -- queue handoff orders the
            # accesses: the producer stops touching req.trace once it is
            # enqueued, and q.put/get gives the worker a happens-before
            req.trace.add("route", attrs)
        q.put(req)

    def _worker(self, q: "queue.Queue"):
        while True:
            item = q.get()
            if item is _STOP:
                break
            try:
                if not isinstance(item, ServingRequest):
                    item = ServingRequest(ids=np.asarray(item),
                                          client=-1, seq=-1)
                self._route(item)
            except Exception as e:  # noqa: BLE001 — stream must survive
                # a malformed payload (np.asarray raising, a broken ids
                # dtype) used to kill this stream thread silently; now
                # it is rejected and the thread keeps draining
                self._reject(item, e)

    def _reject(self, item, exc) -> None:
        """Answer + account one unroutable payload: tick
        ``serving_rejected_total``, retain a ``rejected`` flight record,
        and answer on the result queue when the payload got far enough
        to be answerable."""
        req = item if isinstance(item, ServingRequest) else None
        tenant = getattr(req, "tenant_class", None)
        if tenant is not None:  # QoS-admitted: label by class (bounded)
            telemetry.counter("serving_rejected_total", tenant=tenant).inc()
        else:
            telemetry.counter("serving_rejected_total").inc()
        tr = req.trace if req is not None else flightrec.new_trace()
        if tr is not None:
            tr.add("reject", {"type": type(exc).__name__,
                              "message": str(exc),
                              "payload": type(item).__name__})
            t0 = req.t_enqueue if req is not None else tr.t_start
            flightrec.get_recorder().finish(
                tr, max(time.perf_counter() - t0, 0.0),
                status="rejected", lane="batcher")
        if req is not None and self.result_queue is not None:
            self.result_queue.put((req, exc))

    def start(self):
        for q in self.stream_queues:
            t = threading.Thread(target=self._worker, args=(q,), daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        """Drain the stream threads; leaked (wedged) threads are logged
        and ticked on ``serving_thread_leak_total`` instead of being
        silently abandoned."""
        for q in self.stream_queues:
            q.put(_STOP)
        leaked = join_and_reap(self._threads, timeout=5.0,
                               component="batcher")
        self.cpu_batched_queue.put(_STOP)
        self.device_batched_queue.put(_STOP)
        return leaked


class HybridSampler:
    """CPU-lane sampler workers (parity: serving.py:101-147).

    Pulls requests from the batcher's CPU queue, samples with the native
    host sampler, pushes ``(request, SampledBatch, sample_time)`` to
    ``sampled_queue``.

    Requests are padded to the serving buckets BEFORE sampling: the
    native sampler's output shapes are a fixed function of the seed
    count, so bucketing here means the downstream device forward sees
    only |buckets| distinct shapes (per-request shapes would compile a
    fresh executable each — the CUDA reference has no such concern,
    serving.py:132).  ``InferenceServer`` slices results back to the true
    request length.
    """

    def __init__(self, cpu_sampler, cpu_batched_queue: "queue.Queue",
                 num_workers: int = 2, buckets: Optional[Sequence] = None,
                 feature=None,
                 result_queue: Optional["queue.Queue"] = None):
        self.sampler = cpu_sampler
        self.inq = cpu_batched_queue
        # deadline sheds and sampler failures are answered here (None:
        # expired items flow through for the server to shed)
        self.result_queue = result_queue
        self.sampled_queue: "queue.Queue" = queue.Queue()
        self.num_workers = num_workers
        # optional lookahead: stage the sampled batch's feature rows on
        # the prefetch pool while the item waits for the CPU-lane server
        # thread — overlaps H2D with queue time, and the prefetch worker
        # attributes its work to this request's trace
        self.feature = feature
        if buckets is None:
            from .config import get_config

            buckets = tuple(get_config().serving_buckets)
        self.buckets = tuple(buckets)
        self._threads: List[threading.Thread] = []

    def _pad(self, ids: np.ndarray) -> np.ndarray:
        b = _next_bucket(len(ids), self.buckets)
        if len(ids) >= b:
            return ids
        return np.concatenate([ids, np.full(b - len(ids), ids[0] if
                                            len(ids) else 0,
                                            dtype=ids.dtype)])

    def _loop(self):
        while True:
            item = self.inq.get()
            if item is _STOP:
                self.inq.put(_STOP)  # let siblings see it too
                break
            if shed_if_expired(item, self.result_queue, "sampler"):
                continue
            t0 = time.perf_counter()
            try:
                with flightrec.activate(item.trace):
                    _CHAOS_SAMPLER()
                    batch = self.sampler.sample(
                        self._pad(np.asarray(item.ids)))
                    dt = time.perf_counter() - t0
                    if flightrec.tracing():
                        flightrec.event("sample", {
                            "seconds": dt,
                            "n_id": int(batch.n_id.shape[0])})
                    if self.feature is not None:
                        self.feature.prefetch(batch.n_id)
            except Exception as e:  # noqa: BLE001 — worker must survive
                telemetry.counter("serving_requests_total",
                                  lane="cpu", status="error").inc()
                _fail_request(item, e, "sampler", self.result_queue)
                continue
            self.sampled_queue.put((item, batch, dt))

    def start(self):
        for _ in range(self.num_workers):
            t = threading.Thread(target=self._loop, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self):
        self.inq.put(_STOP)
        leaked = join_and_reap(self._threads, timeout=5.0,
                               component="sampler")
        self.sampled_queue.put(_STOP)
        return leaked


class InferenceServer:
    """Device stage: sample (TPU lane) → gather → model → result queue.

    Parity: serving.py:150-296.  One device thread drives the TPU with
    bucketed shapes; CPU-lane pre-sampled batches share the same forward.
    ``apply_fn(params, x, blocks)`` is the jitted model forward.
    """

    # lock discipline (enforced by quiverlint QT003): the fused-executable
    # cache is filled lazily from whichever worker thread first sees a
    # bucket size, so every write must hold ``_lock``
    _guarded_by = {"_fused_fns": "_lock"}

    BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024, 2048)

    def __init__(self, tpu_sampler, feature, apply_fn: Callable, params,
                 device_batched_queue: "queue.Queue",
                 cpu_sampled_queue: Optional["queue.Queue"] = None,
                 result_queue: Optional["queue.Queue"] = None,
                 max_coalesce: Optional[int] = None,
                 fused: Optional[bool] = None,
                 cpu_sampler=None, qos=None):
        self.sampler = tpu_sampler
        self.feature = feature
        self.apply_fn = apply_fn
        self.params = params
        self.device_q = device_batched_queue
        self.cpu_q = cpu_sampled_queue
        self.result_queue = result_queue or queue.Queue()
        # continuous batching (QoS only): after the non-blocking drain,
        # hold the coalesced batch open for up to this long while slots
        # remain, admitting late arrivals into the SAME device pass.
        # Executable keying is untouched — the batch still pads to one
        # of the pre-compiled buckets, so steady-state retraces stay 0.
        self._qos = qos if qos is not None else qos_from_config()
        if self._qos is not None:
            from .config import get_config as _gc

            self._admit_window_s = float(_gc().qos_admit_window_ms) / 1e3
        else:
            self._admit_window_s = 0.0
        # failover route for device-lane requests when the device lane
        # fails or its breaker opens: an inline sample on the CPU
        # sampler + the shared presampled forward.  None = no route
        # (failed device requests are answered with the error, the
        # pre-resilience behaviour).
        self.cpu_sampler = cpu_sampler
        # per-lane circuit breakers (config-driven thresholds; tests
        # swap in instances with injected clocks)
        self._breakers = {"device": CircuitBreaker("serving.device"),
                          "cpu": CircuitBreaker("serving.cpu")}
        if max_coalesce is None:
            from .config import get_config

            cfg = get_config()
            max_coalesce = cfg.max_coalesce
            self.BUCKETS = tuple(cfg.serving_buckets)
        self.max_coalesce = max_coalesce
        # fused device lane: sample + gather + forward in ONE jit per
        # bucket — no host hop between stages (the reference pays three
        # kernel launches + a python step between each; TPU pays three
        # dispatches AND a blocking n_id readback unless fused).  Needs
        # the feature fully HBM-resident, like the fused train pipeline.
        if fused is None:
            fused = (getattr(feature, "node_count", 0) > 0
                     and feature.cache_count >= feature.node_count
                     and getattr(tpu_sampler, "mode", "TPU") == "TPU")
        self._fused = fused
        if not fused:
            self._maybe_enable_cold_cache(feature)
        from .recovery.registry import program_cache

        self._fused_fns = program_cache("serving", owner=self)
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._stopped = threading.Event()

    @staticmethod
    def _maybe_enable_cold_cache(feature):
        """Attach the HBM cold-row overlay to budgeted features in the
        unfused lane: recurring serving requests keep re-touching the
        same cold rows, which otherwise cross the host link every
        request (docs/FEATURE_CACHE.md).  Heuristic sizing via
        ``enable_cold_cache()`` defaults; ``cold_cache_size="off"`` (or
        ``0``/``none``) in config vetoes."""
        if (getattr(feature, "node_count", 0) <= 0
                or feature.cache_count >= feature.node_count
                or getattr(feature, "cold_cache", None) is not None
                or not hasattr(feature, "enable_cold_cache")):
            return
        from .config import get_config

        if str(get_config().cold_cache_size).lower() in ("0", "off",
                                                         "none"):
            return
        feature.enable_cold_cache()

    # -- core per-request paths ---------------------------------------
    # quiverlint: bucketed[every result length is drawn from BUCKETS]
    def _pad_ids(self, ids: np.ndarray) -> np.ndarray:
        b = _next_bucket(len(ids), self.BUCKETS)
        if len(ids) >= b:  # at the top bucket exactly (chunking caps len)
            return ids
        return np.concatenate([ids, np.full(b - len(ids), ids[0] if len(ids)
                                            else 0, dtype=ids.dtype)])

    def _run_bucketed(self, ids: np.ndarray,
                      stages: Optional[dict] = None) -> np.ndarray:
        """One padded device pass per <=top-bucket chunk.

        Requests above the top bucket are CHUNKED into top-bucket pieces so
        every device program is one of the |BUCKETS| pre-compiled shapes —
        an unbounded request size never triggers a fresh compile (the
        reference has no analogue: CUDA kernels take any shape; XLA
        executables don't).

        ``stages``: optional dict accumulating per-stage wall seconds
        (``sample`` / ``gather`` / ``infer``).  The stamps are
        consecutive so the stage intervals partition this call's wall
        time exactly; the final ``np.asarray`` host sync is charged to
        ``infer`` (XLA dispatch is async — per-stage attribution of the
        *device* time needs a profiler, not wall clocks).  Warmup passes
        no dict and so never pollutes request metrics.
        """
        top = self.BUCKETS[-1]
        outs = []
        for off in range(0, max(len(ids), 1), top):  # empty ids: one
            # zero-length chunk, padded to the smallest bucket
            chunk = ids[off: off + top]
            padded = self._pad_ids(chunk)
            if self._fused:
                t0 = time.perf_counter()
                # dispatch must stay async: the readback below is the
                # ONE sanctioned sync point per chunk
                with no_sync("serving device loop"):
                    out = self._fused_forward(padded)
                # quiverlint: sync-ok[response boundary: one transfer per chunk]
                outs.append(np.asarray(out)[: len(chunk)])
                if stages is not None:  # one jit: stages are fused too
                    dt = time.perf_counter() - t0
                    stages["infer"] = stages.get("infer", 0.0) + dt
                    if flightrec.tracing():
                        flightrec.event("infer", {"seconds": dt,
                                                  "fused": True})
            else:
                t0 = time.perf_counter()
                batch = self.sampler.sample(padded)
                t1 = time.perf_counter()
                x = self.feature[np.asarray(batch.n_id)]
                t2 = time.perf_counter()
                out = self.apply_fn(self.params, x, batch.layers)
                outs.append(np.asarray(out)[: len(chunk)])  # sync point
                t3 = time.perf_counter()
                if stages is not None:
                    stages["sample"] = stages.get("sample", 0.0) + t1 - t0
                    stages["gather"] = stages.get("gather", 0.0) + t2 - t1
                    stages["infer"] = stages.get("infer", 0.0) + t3 - t2
                    if flightrec.tracing():
                        flightrec.event("sample", {"seconds": t1 - t0})
                        flightrec.event("gather", {"seconds": t2 - t1})
                        flightrec.event("infer", {"seconds": t3 - t2})
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def _fused_forward(self, padded_ids: np.ndarray):
        """One jit per bucket size: sample -> gather -> model, no host
        round-trips between the stages."""
        import jax
        import jax.numpy as jnp

        from .sampler import run_pipeline
        from .utils.rng import make_key

        B = len(padded_ids)
        fn = self._fused_fns.get(B)
        if fn is None:
            s = self.sampler
            indptr, indices = s.csr_topo.to_device(s.device)
            sizes = tuple(s.sizes)
            caps = tuple(s.frontier_caps)
            dedup, gm = s.dedup, s.gather_mode
            srng = s.sample_rng
            cw = s._cum_weights  # weighted samplers stay weighted here
            feature, apply_fn = self.feature, self.apply_fn

            @jax.jit
            def fn(params, seeds, key):
                n_id, _, _, blocks, _ = run_pipeline(
                    dedup, indptr, indices, seeds, key, sizes, caps,
                    gather_mode=gm, cum_weights=cw, sample_rng=srng)
                x = feature.lookup_device(n_id)
                return apply_fn(params, x, blocks)

            # double-checked: the unlocked .get() above is the fast path;
            # two threads racing a cold bucket both build, setdefault
            # keeps exactly one (compile is lazy, losing a build is cheap)
            with self._lock:
                fn = self._fused_fns.setdefault(B, fn)
        return fn(self.params, jnp.asarray(padded_ids, jnp.int32),
                  make_key(np.random.randint(0, 2**31 - 1)))

    def warmup(self, example_node: int = 0):
        """Compile every bucket's executable before traffic arrives.

        The reference pays no warmup (CUDA shape-polymorphism); on TPU a
        cold bucket would stall its first request for the ~seconds-long
        compile, wrecking p99 — so serve only after this returns.
        """
        for b in self.BUCKETS:
            ids = np.full(b, example_node, dtype=np.int64)
            self._run_bucketed(ids)
        if hasattr(self.feature, "warm_executables"):
            # mesh-sharded feature stores pre-build their collective
            # gather ladder too — steady-state serving must trace 0
            self.feature.warm_executables()
        return self

    def _infer_device(self, req: ServingRequest):
        ids = np.asarray(req.ids)
        return self._run_bucketed(ids)[: len(ids)]

    def _infer_presampled(self, req: ServingRequest, batch,
                          stages: Optional[dict] = None):
        t0 = time.perf_counter()
        x = self.feature[np.asarray(batch.n_id)]
        t1 = time.perf_counter()
        out = self.apply_fn(self.params, x, batch.layers)
        out = np.asarray(out)[: len(req.ids)]  # sync point
        t2 = time.perf_counter()
        if stages is not None:
            stages["gather"] = stages.get("gather", 0.0) + t1 - t0
            stages["infer"] = stages.get("infer", 0.0) + t2 - t1
            if flightrec.tracing():
                flightrec.event("gather", {"seconds": t1 - t0})
                flightrec.event("infer", {"seconds": t2 - t1})
        return out

    def _drain_coalesce(self, first: ServingRequest):
        """Pull queued requests (non-blocking) to batch one device pass —
        under load many small requests share a single bucketed forward,
        which is where the TPU's throughput lives.

        With QoS active the drain gains a bounded *admit window*
        (``config.qos_admit_window_ms``): once the queue runs dry with
        slots still free, block briefly for late arrivals instead of
        launching a mostly-empty pass — continuous batching.  The
        window never extends past the first member's deadline headroom,
        and a disabled QoS pays exactly one attribute check."""
        reqs = [first]
        budget = self.BUCKETS[-1] - len(first.ids)
        window = self._admit_window_s
        t_close = time.perf_counter() + window if window > 0 else 0.0
        while len(reqs) < self.max_coalesce and budget > 0:
            try:
                item = self.device_q.get_nowait()
            except queue.Empty:
                if window <= 0:
                    break
                left = t_close - time.perf_counter()
                if first.deadline is not None:
                    left = min(left, first.deadline - time.perf_counter())
                if left <= 0:
                    break
                try:
                    item = self.device_q.get(timeout=left)
                except queue.Empty:
                    break
            if item is _STOP:
                self.device_q.put(_STOP)  # re-post for the loop to see
                break
            if len(item.ids) > budget:
                self.device_q.put(item)
                break
            reqs.append(item)
            budget -= len(item.ids)
        return reqs

    def _infer_coalesced(self, reqs, stages: Optional[dict] = None):
        ids = np.concatenate([np.asarray(r.ids) for r in reqs])
        out = self._run_bucketed(ids, stages)
        off = 0
        outs = []
        for r in reqs:
            outs.append(out[off: off + len(r.ids)])
            off += len(r.ids)
        return outs

    # -- loops ---------------------------------------------------------
    # Unlike the reference's bare `while 1` loops (serving.py:198-230 —
    # one bad request kills the worker process), a failed request is
    # reported on the result queue and the lane keeps serving.
    def _device_loop(self):
        while not self._stopped.is_set():
            item = self.device_q.get()
            if item is _STOP:
                break
            reqs = (
                self._drain_coalesce(item) if self.max_coalesce > 1
                else [item]
            )
            # stage-boundary deadline check: requests that aged out on
            # the queue are shed (answered) before burning device time
            reqs = [r for r in reqs
                    if not shed_if_expired(r, self.result_queue, "device")]
            if not reqs:
                continue
            br = self._breakers["device"]
            if not br.allow():
                self._failover(reqs, "device", None)
                continue
            # dequeue stamp AFTER coalescing: queue_wait covers time on
            # the queue plus the drain, so the per-request intervals
            # (queue_wait + stages) still partition end-to-end latency
            t_deq = time.perf_counter()
            stages: dict = {}
            # a coalesced batch activates EVERY member's trace: they all
            # wait for this device pass, so they all own its events
            # (trace is None for all members when telemetry is off, and
            # activate(None) is the shared no-op)
            act = (flightrec.activate([r.trace for r in reqs])
                   if reqs[0].trace is not None else flightrec.activate(None))
            # ambient deadline for callees without a request in hand
            # (dist feature degraded lookups): the batch's tightest one
            dls = [r.deadline for r in reqs if r.deadline is not None]
            scope = deadline_scope(min(dls) if dls else None,
                                   min(r.t_enqueue for r in reqs))
            try:
                with act, scope:
                    if flightrec.tracing():
                        flightrec.event("dequeue",
                                        {"coalesced": len(reqs)})
                    _CHAOS_DEVICE()
                    outs = self._infer_coalesced(reqs, stages)
                br.record_success()
                t_done = time.perf_counter()
                for r, o in zip(reqs, outs):
                    self._finish(r, o, lane="device", stages=stages,
                                 t_dequeue=t_deq, t_done=t_done)
            except Exception as e:  # noqa: BLE001 — lane must survive
                br.record_failure()
                self._failover(reqs, "device", e)

    def _cpu_loop(self):
        while not self._stopped.is_set():
            item = self.cpu_q.get()
            if item is _STOP:
                break
            req, batch, sample_dt = item
            if shed_if_expired(req, self.result_queue, "cpu"):
                continue
            br = self._breakers["cpu"]
            if not br.allow():
                self._failover([req], "cpu", None)
                continue
            stages = {"sample": float(sample_dt)}
            scope = deadline_scope(req.deadline, req.t_enqueue)
            try:
                with flightrec.activate(req.trace), scope:
                    _CHAOS_CPU()
                    out = self._infer_presampled(req, batch, stages)
                br.record_success()
                t_done = time.perf_counter()
                self._finish(req, out, lane="cpu", stages=stages,
                             t_done=t_done)
            except Exception as e:  # noqa: BLE001 — lane must survive
                br.record_failure()
                self._failover([req], "cpu", e)

    # -- failover -------------------------------------------------------
    def _failover(self, reqs, lane: str, error: Optional[Exception]):
        """Reroute requests off a failed (or breaker-open) lane.  Every
        request is ANSWERED: rerouted and finished, or — when no route
        exists / the reroute itself fails — errored on the result queue.
        ``error`` is the primary-lane failure (None when the breaker
        shorted the attempt)."""
        for r in reqs:
            if shed_if_expired(r, self.result_queue, lane):
                continue
            try:
                done = (self._failover_via_cpu(r) if lane == "device"
                        else self._failover_via_device(r))
            except Exception as e:  # noqa: BLE001 — failover can fail too
                self._answer_error(r, e, "failover")
                continue
            if not done:
                self._answer_error(
                    r, error if error is not None else LaneUnavailable(lane),
                    lane)

    def _failover_via_cpu(self, req) -> bool:
        """Serve one device-lane request inline on the CPU sampler lane.
        False when no ``cpu_sampler`` was wired (no route)."""
        if self.cpu_sampler is None:
            return False
        stages: dict = {}
        with flightrec.activate(req.trace):
            if flightrec.tracing():
                flightrec.event("failover", {"from": "device", "to": "cpu"})
            ids = np.asarray(req.ids)
            t0 = time.perf_counter()
            padded = self._pad_ids(ids) if len(ids) <= self.BUCKETS[-1] \
                else ids
            batch = self.cpu_sampler.sample(padded)
            stages["sample"] = time.perf_counter() - t0
            out = self._infer_presampled(req, batch, stages)
        telemetry.counter("serving_failover_total",
                          direction="device_to_cpu").inc()
        self._finish(req, out, lane="failover", stages=stages,
                     t_done=time.perf_counter())
        return True

    def _failover_via_device(self, req) -> bool:
        """Serve one CPU-lane request via the bucketed device forward.
        False when the device breaker refuses it (no route)."""
        if not self._breakers["device"].allow():
            return False
        stages: dict = {}
        with flightrec.activate(req.trace):
            if flightrec.tracing():
                flightrec.event("failover", {"from": "cpu", "to": "device"})
            ids = np.asarray(req.ids)
            out = self._run_bucketed(ids, stages)[: len(ids)]
        telemetry.counter("serving_failover_total",
                          direction="cpu_to_device").inc()
        self._finish(req, out, lane="failover", stages=stages,
                     t_done=time.perf_counter())
        return True

    def _answer_error(self, req, exc, lane: str):
        telemetry.counter("serving_requests_total", lane=lane,
                          status="error").inc()
        self._finish_error(req, exc, lane=lane)
        self.result_queue.put((req, exc))

    def _finish(self, req, out, lane: str = "device",
                stages: Optional[dict] = None,
                t_dequeue: Optional[float] = None,
                t_done: Optional[float] = None):
        self._record_request(req, lane, stages or {}, t_dequeue, t_done)
        self.result_queue.put((req, out))

    def _finish_error(self, req, exc, lane: str):
        """Error-path retention: a failed request is always kept by the
        flight recorder (reason=error), with the exception on its log."""
        tr = getattr(req, "trace", None)
        if tr is None:
            return
        tr.add("error", {"type": type(exc).__name__, "message": str(exc)})
        e2e = max(time.perf_counter() - req.t_enqueue, 0.0)
        flightrec.get_recorder().finish(tr, e2e, status="error", lane=lane)

    def _record_request(self, req, lane, stages, t_dequeue, t_done):
        """Fold one served request into the registry.  Returns
        ``(e2e_seconds, full_stage_dict)`` so the Debug subclass can
        reuse the exact same numbers for its local accounting.

        ``queue_wait`` is the dequeue stamp minus the enqueue stamp when
        the lane observed one (device lane), else the residual of the
        measured stages against end-to-end (CPU lane, whose ``sample``
        happened inside HybridSampler before this server saw the item).
        Either way ``sum(stages) ≈ e2e``.
        """
        now = t_done if t_done is not None else time.perf_counter()
        e2e = max(now - req.t_enqueue, 0.0)
        full = dict(stages)
        if t_dequeue is not None:
            full["queue_wait"] = max(t_dequeue - req.t_enqueue, 0.0)
        else:
            full["queue_wait"] = max(e2e - sum(full.values()), 0.0)
        telemetry.counter("serving_requests_total", lane=lane,
                          status="ok").inc()
        telemetry.histogram("serving_request_seconds", lane=lane).observe(e2e)
        for stage, dt in full.items():
            telemetry.histogram("serving_stage_seconds", lane=lane,
                                stage=stage).observe(dt)
        tr = getattr(req, "trace", None)
        if tr is not None:
            tr.add("finish", {"lane": lane})
            flightrec.get_recorder().finish(tr, e2e, status="ok", lane=lane,
                                            stages=full)
        return e2e, full

    def expose_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Start the stdlib HTTP metrics endpoint (/metrics,
        /metrics.json, /trace.json) for this process' registry.  Lazy
        import: serving has no hard dependency on the exporter."""
        from .telemetry.export import start_http_server

        self._metrics_server = start_http_server(port=port, host=host)
        return self._metrics_server

    def start_slo_watchdog(self):
        """Start the process-wide SLO watchdog thread (objectives from
        config).  Explicit by design: a background evaluator should not
        appear as a side effect of constructing a server.  Stopped with
        the server."""
        from .telemetry.slo import get_watchdog

        self._slo_watchdog = get_watchdog().start()
        return self._slo_watchdog

    def start(self):
        t = threading.Thread(target=self._device_loop, daemon=True)
        t.start()
        self._threads.append(t)
        if self.cpu_q is not None:
            t2 = threading.Thread(target=self._cpu_loop, daemon=True)
            t2.start()
            self._threads.append(t2)
        return self

    def stop(self):
        self._stopped.set()
        self.device_q.put(_STOP)
        if self.cpu_q is not None:
            self.cpu_q.put(_STOP)
        leaked = join_and_reap(self._threads, timeout=10.0,
                               component="server")
        srv = getattr(self, "_metrics_server", None)
        if srv is not None:
            srv.close()
            self._metrics_server = None
        wd = getattr(self, "_slo_watchdog", None)
        if wd is not None:
            wd.stop()
            self._slo_watchdog = None
        return leaked


def calibrate_threshold(tpu_sampler, cpu_sampler, feature, apply_fn, params,
                        neighbour_num: np.ndarray, node_count: int,
                        trials: int = 8, sizes=(1, 4, 16, 64),
                        seed: int = 0) -> float:
    """Measure both lanes and return the ``neighbour_num``-sum threshold
    below which the CPU lane is faster.

    This automates what the reference's ``Preparation`` mode collects
    manually (serving.py:60-70 duplicates traffic to both lanes so an
    operator can pick a threshold).  Returns a load value usable directly
    as ``RequestBatcher(threshold=...)``.
    """
    import time as _time

    rng = np.random.default_rng(seed)
    points = []  # (load, cpu_dt, tpu_dt)
    for sz in sizes:
        for _ in range(trials):
            ids = rng.integers(0, node_count, sz)
            load = float(neighbour_num[ids].sum())
            t0 = _time.perf_counter()
            b = cpu_sampler.sample(ids)
            x = feature[np.asarray(b.n_id)]
            np.asarray(apply_fn(params, x, b.layers))
            cpu_dt = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            b = tpu_sampler.sample(ids)
            x = feature[np.asarray(b.n_id)]
            np.asarray(apply_fn(params, x, b.layers))
            tpu_dt = _time.perf_counter() - t0
            points.append((load, cpu_dt, tpu_dt))
    return _fit_crossover(points)


def _fit_crossover(points) -> float:
    """Threshold from timing points ``(load, cpu_dt, device_dt)``.

    Fit the crossover instead of keeping the LAST load where CPU won:
    with noisy timings past the crossover a single lucky CPU sample
    would set the threshold far too high and route heavy requests to
    the slow lane.  The threshold is the midpoint at the best split
    (below: CPU lane, at/above: device lane), the max load if CPU
    always wins, 0 if the device lane always wins.
    """
    points = sorted(points)
    if not points:
        return 0.0
    wins = [cpu_dt <= dev_dt for _, cpu_dt, dev_dt in points]
    # optimal split: the index s maximizing (#CPU wins below s) +
    # (#device wins at/after s).  Works at any sample count (a rolling
    # window degenerates to a global vote when n <= window) and a single
    # outlier on either side moves the optimum only if it outweighs the
    # consistent pattern.
    n = len(points)
    dev_wins_suffix = [0] * (n + 1)
    for i in range(n - 1, -1, -1):
        dev_wins_suffix[i] = dev_wins_suffix[i + 1] + (0 if wins[i] else 1)
    best_s, best_score, cpu_prefix = 0, dev_wins_suffix[0], 0
    for s in range(1, n + 1):
        cpu_prefix += 1 if wins[s - 1] else 0
        score = cpu_prefix + dev_wins_suffix[s]
        if score > best_score:
            best_s, best_score = s, score
    if best_s == 0:
        return 0.0
    if best_s == n:
        return points[-1][0]
    return (points[best_s - 1][0] + points[best_s][0]) / 2.0


class InferenceServer_Debug(InferenceServer):
    """Latency-instrumented server (parity: serving.py:298-360).

    ``stats()`` returns avg / p50 / p99 latency and throughput (the
    reference's tp99 harness) plus ``stage_breakdown_ms`` — per-stage
    (queue_wait / sample / gather / infer) mean and total.  Accounting
    lives on a private fixed-bucket :class:`~quiver_tpu.telemetry.Histogram`
    rather than the old unbounded per-request list: memory is O(buckets)
    under sustained traffic, p50/p99 read from bucket interpolation
    (~13% worst-case with the default ~1.26x grid), and the same numbers
    flow into the process registry via the base class.
    """

    # QT003: latency accounting is written from every worker thread via
    # _record_request; it shares the base class's ``_lock``
    _guarded_by = {"_stage_acc": "_lock", "_count": "_lock",
                   "_t_first": "_lock", "_t_last": "_lock"}

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)  # base creates self._lock
        self._hist = telemetry.Histogram("serving_debug_latency")
        self._stage_acc: dict = {}  # stage -> [count, total_s]
        self._t_first = None
        self._t_last = None
        self._count = 0

    def _record_request(self, req, lane, stages, t_dequeue, t_done):
        e2e, full = super()._record_request(req, lane, stages, t_dequeue,
                                            t_done)
        self._hist.observe(e2e)
        with self._lock:
            self._t_first = self._t_first or req.t_enqueue
            self._t_last = req.t_enqueue + e2e
            self._count += 1
            for stage, dt in full.items():
                acc = self._stage_acc.setdefault(stage, [0, 0.0])
                acc[0] += 1
                acc[1] += dt
        return e2e, full

    def flight_records(self) -> list:
        """Retained flight-recorder records (oldest first) — the tail
        of requests worth debugging: slow, errored, or flagged."""
        return flightrec.get_recorder().records()

    def stats(self) -> dict:
        with self._lock:
            n = self._count
            if n == 0:
                return dict(count=0)
            span = max((self._t_last or 0) - (self._t_first or 0), 1e-9)
            breakdown = {
                stage: dict(mean_ms=float(t / c * 1e3),
                            total_ms=float(t * 1e3))
                for stage, (c, t) in sorted(self._stage_acc.items())
            }
        return dict(
            count=int(n),
            avg_latency_ms=float(self._hist.mean * 1e3),
            p50_latency_ms=float(self._hist.percentile(50) * 1e3),
            p99_latency_ms=float(self._hist.percentile(99) * 1e3),
            throughput_rps=float(n / span),
            stage_breakdown_ms=breakdown,
        )
