"""Per-node expanded-neighborhood size — drives serving's CPU/TPU routing.

Reference parity: ``srcs/python/quiver/generate_neighbour_num.py:10-95``
(serial / GPU-mp.spawn / CPU-process variants).  Here the heavy path is the
multithreaded native sampler (``qt_neighbour_num`` in
``cpp/csrc/quiver_cpu.cpp``), with a vectorized-expectation device variant:
instead of sampling once per node, ``mode="expected"`` computes the exact
expected frontier sizes from the degree recurrence on TPU — deterministic
and one matvec per layer, a strictly better routing signal than the
reference's single noisy sample.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from .utils.topology import CSRTopo

__all__ = ["generate_neighbour_num"]


def _expected_counts(indptr, indices, *, n, sizes):
    """Reverse degree recurrence, fully on device.

    g_L = 0; g_l[v] = min(k_l, deg[v]) * (1 + mean_{u in N(v)} g_{l+1}[u]);
    expected total = g_1[v].  The mean over neighbors uses the uniform
    sampling marginals.  ``n`` and ``sizes`` are static so the whole
    recurrence compiles to one XLA program — no per-layer dispatch and a
    single host materialization at the end.
    """
    import jax
    import jax.numpy as jnp

    deg = (indptr[1:] - indptr[:-1]).astype(jnp.float32)
    e = indices.shape[0]
    row_of_edge = (
        jnp.searchsorted(
            indptr,
            jnp.arange(e, dtype=indptr.dtype),
            side="right",
        ) - 1
    )
    g = jnp.zeros((n,), jnp.float32)
    for k in reversed(sizes):
        branch = jnp.minimum(float(k), deg)
        s = jax.ops.segment_sum(g[indices], row_of_edge, num_segments=n)
        g = branch * (1.0 + s / jnp.maximum(deg, 1.0))
    return g


_expected_counts_jit = None


def _get_expected_counts_jit():
    """Build (once) and return the jitted recurrence.  Module-level cache
    so repeated calls with the same (n, sizes) reuse the executable."""
    global _expected_counts_jit
    if _expected_counts_jit is None:
        import jax

        _expected_counts_jit = jax.jit(
            _expected_counts, static_argnames=("n", "sizes"))
    return _expected_counts_jit


def generate_neighbour_num(
    csr_topo: CSRTopo, sizes: Sequence[int], mode: str = "expected",
    n_threads: int = 0, seed: int = 7, path: str = None,
) -> np.ndarray:
    """Return ``[N]`` expected (or sampled) total neighborhood sizes.

    ``mode``: ``"expected"`` (deterministic recurrence, device) or
    ``"sampled"`` (native CPU sampler, parity with the reference).
    Saves to ``path`` (.npy) if given, like the reference's offline script.
    """
    if mode == "sampled":
        from .cpp.native import neighbour_num_native

        out = neighbour_num_native(
            csr_topo.indptr, csr_topo.indices, list(sizes),
            n_threads=n_threads, seed=seed,
        )
    else:
        indptr, indices = csr_topo.to_device()
        n = csr_topo.node_count
        e = csr_topo.edge_count
        indptr = indptr[: n + 1]   # strip lane padding
        indices = indices[:e]
        g = _get_expected_counts_jit()(
            indptr, indices, n=n, sizes=tuple(int(k) for k in sizes))
        # quiverlint: sync-ok[host-return contract: callers get numpy]
        out = np.asarray(g).astype(np.int64)
    if path is not None:
        np.save(path, out)
    return out
