"""Per-node expanded-neighborhood size — drives serving's CPU/TPU routing.

Reference parity: ``srcs/python/quiver/generate_neighbour_num.py:10-95``
(serial / GPU-mp.spawn / CPU-process variants).  Here the heavy path is the
multithreaded native sampler (``qt_neighbour_num`` in
``cpp/csrc/quiver_cpu.cpp``), with a vectorized-expectation device variant:
instead of sampling once per node, ``mode="expected"`` computes the exact
expected frontier sizes from the degree recurrence on TPU — deterministic
and one matvec per layer, a strictly better routing signal than the
reference's single noisy sample.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from .utils.topology import CSRTopo

__all__ = ["generate_neighbour_num"]


def generate_neighbour_num(
    csr_topo: CSRTopo, sizes: Sequence[int], mode: str = "expected",
    n_threads: int = 0, seed: int = 7, path: str = None,
) -> np.ndarray:
    """Return ``[N]`` expected (or sampled) total neighborhood sizes.

    ``mode``: ``"expected"`` (deterministic recurrence, device) or
    ``"sampled"`` (native CPU sampler, parity with the reference).
    Saves to ``path`` (.npy) if given, like the reference's offline script.
    """
    if mode == "sampled":
        from .cpp.native import neighbour_num_native

        out = neighbour_num_native(
            csr_topo.indptr, csr_topo.indices, list(sizes),
            n_threads=n_threads, seed=seed,
        )
    else:
        import jax.numpy as jnp
        import jax

        indptr, indices = csr_topo.to_device()
        n = csr_topo.node_count
        e = csr_topo.edge_count
        indptr = indptr[: n + 1]   # strip lane padding
        indices = indices[:e]
        deg = (indptr[1:] - indptr[:-1]).astype(jnp.float32)
        row_of_edge = (
            jnp.searchsorted(
                indptr,
                jnp.arange(e, dtype=indptr.dtype),
                side="right",
            ) - 1
        )

        # Reverse dynamic program, vectorized over all nodes at once:
        # g_L = 0; g_l[v] = min(k_l, deg[v]) * (1 + mean_{u in N(v)} g_{l+1}[u])
        # expected total = g_1[v].  mean over neighbors uses the uniform
        # sampling marginals.
        import jax.ops

        def mean_over_neighbors(g):
            s = jax.ops.segment_sum(g[indices], row_of_edge, num_segments=n)
            return s / jnp.maximum(deg, 1.0)

        g = jnp.zeros((n,), jnp.float32)
        for k in reversed(list(sizes)):
            branch = jnp.minimum(float(k), deg)
            g = branch * (1.0 + mean_over_neighbors(g))
        out = np.asarray(jax.device_get(g)).astype(np.int64)
    if path is not None:
        np.save(path, out)
    return out
