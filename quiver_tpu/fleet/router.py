"""Partition-aware fleet router: hash, health-gate, dispatch, re-dispatch.

The router is the thin tier in front of the replica fleet.  It holds no
graph state — only three views it can rebuild at any moment:

  * **membership** — the shared directory's fresh ``serving`` records
    (:class:`~quiver_tpu.fleet.membership.MembershipDirectory`);
  * **health** — each replica's ``/healthz`` readiness document, polled
    on a cadence and cached, so a wedged process whose heartbeat file
    is still fresh ages out of routing anyway;
  * **breakers** — one :class:`~quiver_tpu.resilience.breaker.
    CircuitBreaker` per replica, so a replica that eats requests
    (connect timeout, garbage reply) stops receiving them after
    ``failure_threshold`` strikes and is re-probed half-open.

With mesh-native sharded serving (docs/SHARDING.md) the routable unit
may be a *shard group*: N processes that together hold one logical
replica.  A complete, fully healthy group enters the ring as
``group:<gid>`` (breaker key ``fleet.group:<gid>``) and requests land
on its shard-0 coordinator; any missing or unhealthy member removes the
WHOLE group from the ring, so callers get a typed
:class:`NoReplicaAvailable` instead of a partial answer.

Placement is consistent hashing over *partitions*, not raw ids: the
partition of a request is ``ids[0] % config.fleet_partitions`` (the
locality-partition shape GNNSampler argues for — requests for the same
neighbourhood hit the same replica's warm caches), and the ring only
reshuffles ``1/N`` of partitions when a replica joins or leaves.  Hot
tenants (QoS class priority ≥ ``config.fleet_hot_priority``) use
power-of-two-choices between the partition's top two preference-list
replicas, trading a little cache affinity for not letting one replica
melt under a zipfian head key.

The failure contract is the fleet-wide version of "answered, never
dropped": a transport failure or an ``unavailable`` reply re-dispatches
the request to the next replica on the preference list (bounded by
``config.fleet_route_retries``, backoff between attempts); a typed
``shed`` reply is an **answer** and is returned as-is — retrying a shed
would defeat admission control.  When the budget is exhausted the
caller gets a typed :class:`~quiver_tpu.resilience.errors.
NoReplicaAvailable`, and ``fleet_router_unroutable_total`` ticks.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence
from weakref import ref as weakref

from .. import telemetry
from ..resilience import chaos
from ..resilience.breaker import get_breaker
from ..resilience.errors import NoReplicaAvailable
from ..resilience.retry import Backoff
from ..telemetry import flightrec
from ..telemetry import timeline as _timeline
from .membership import (MembershipDirectory, ReplicaInfo, group_complete,
                         shard_groups)

__all__ = ["ConsistentHashRing", "FleetRouter", "fleet_status"]

log = logging.getLogger("quiver_tpu.fleet")

_CHAOS_ROUTE = chaos.point("fleet.route")


def _hash(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big")


class ConsistentHashRing:
    """Consistent hashing with virtual nodes.

    Deterministic across processes (blake2b, no PYTHONHASHSEED
    dependence): every router instance over the same membership set
    computes the same partition → replica preference lists.
    """

    def __init__(self, vnodes: Optional[int] = None):
        from ..config import get_config

        self.vnodes = int(vnodes if vnodes is not None
                          else get_config().fleet_vnodes)
        if self.vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {self.vnodes}")
        self._members: tuple = ()
        self._points: List[int] = []
        self._owners: List[str] = []

    @property
    def members(self) -> tuple:
        return self._members

    def set_members(self, members: Sequence[str]) -> None:
        members = tuple(sorted(set(members)))
        if members == self._members:
            return
        ring = []
        for m in members:
            for v in range(self.vnodes):
                ring.append((_hash(f"{m}#{v}"), m))
        ring.sort()
        self._members = members
        self._points = [p for p, _ in ring]
        self._owners = [m for _, m in ring]

    def preference(self, key, n: Optional[int] = None) -> List[str]:
        """The first ``n`` *distinct* members clockwise of ``key`` —
        the dispatch order for that partition."""
        if not self._members:
            return []
        n = len(self._members) if n is None else min(n, len(self._members))
        i = bisect.bisect(self._points, _hash(str(key))) % len(self._points)
        out: List[str] = []
        for step in range(len(self._points)):
            owner = self._owners[(i + step) % len(self._points)]
            if owner not in out:
                out.append(owner)
                if len(out) >= n:
                    break
        return out


class FleetRouter:
    """Routes serving requests into the fleet; owns no graph state."""

    _guarded_by = {
        "_eligible": "_lock", "_health_ok": "_lock", "_inflight": "_lock",
        "_last_scan": "_lock", "_hops": "_lock", "_hop_ids": "_lock",
        "_groups": "_lock", "_write_path": "_lock",
    }

    def __init__(self, directory: MembershipDirectory,
                 partitions: Optional[int] = None,
                 vnodes: Optional[int] = None,
                 route_retries: Optional[int] = None,
                 request_timeout_s: Optional[float] = None,
                 hot_priority: Optional[int] = None,
                 health_poll_s: float = 0.25,
                 scan_ttl_s: float = 0.1,
                 backoff: Optional[Backoff] = None,
                 federation: Optional[bool] = None,
                 origin: Optional[str] = None):
        from ..config import get_config

        cfg = get_config()
        self.directory = directory
        self.partitions = int(partitions if partitions is not None
                              else cfg.fleet_partitions)
        self.route_retries = int(route_retries if route_retries is not None
                                 else cfg.fleet_route_retries)
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else cfg.fleet_request_timeout_s)
        self.hot_priority = int(hot_priority if hot_priority is not None
                                else cfg.fleet_hot_priority)
        self.health_poll_s = float(health_poll_s)
        self.scan_ttl_s = float(scan_ttl_s)
        self.backoff = backoff if backoff is not None else Backoff(
            base_s=0.005, cap_s=0.1, jitter=0.2)
        self.ring = ConsistentHashRing(vnodes)
        self._lock = threading.Lock()
        self._eligible: Dict[str, ReplicaInfo] = {}
        self._groups: Dict[str, List[ReplicaInfo]] = {}
        self._health_ok: Dict[str, bool] = {}
        self._inflight: Dict[str, int] = {}
        self._last_scan = 0.0
        self._write_path = None       # (leader_id, epoch) last resolved
        self._hp_stop = threading.Event()
        self._hp_thread: Optional[threading.Thread] = None
        # fleet observability plane (docs/OBSERVABILITY.md): the flag is
        # resolved ONCE here, so the off path costs exactly one
        # attribute read per request — no config lookup, no trace, no
        # payload stamp, no new metric keys
        self.federation_enabled = (
            bool(federation) if federation is not None
            else str(cfg.fleet_federation).lower()
            in ("on", "1", "true", "yes"))
        self.origin = str(origin) if origin else f"rtr-{os.getpid():x}"
        self.hop_capacity = max(int(cfg.fleet_trace_ring), 1)
        self._hops: Dict[str, dict] = {}
        self._hop_ids: List[str] = []
        self.federation = None
        if self.federation_enabled:
            from .federation import FleetFederation

            self.federation = FleetFederation(directory, router=self)
        _set_active(self)

    # -- fleet view ----------------------------------------------------
    def refresh(self, force: bool = False) -> None:
        """Re-scan membership (rate-limited by ``scan_ttl_s``) and
        rebuild the routable set: fresh + ``serving`` + health-gated +
        breaker-admitted candidates enter the hash ring."""
        now = time.monotonic()
        with self._lock:
            if not force and (now - self._last_scan) < self.scan_ttl_s:
                return
            self._last_scan = now
        fresh = {r.replica_id: r
                 for r in self.directory.replicas(fresh_only=True)
                 if r.state == "serving"}
        with self._lock:
            health = dict(self._health_ok)
        # shard groups (docs/SHARDING.md) route as ONE unit: a complete,
        # fully healthy group enters the ring as "group:<gid>" with its
        # shard-0 member as the dispatch coordinator; an incomplete or
        # partially unhealthy group takes NO traffic — one dead shard
        # makes the whole logical replica unavailable, never a partial
        # answer.  Whole-graph replicas still route as singletons.
        eligible = {rid: r for rid, r in fresh.items()
                    if r.shard_group is None and health.get(rid, True)}
        groups: Dict[str, List[ReplicaInfo]] = {}
        for gid, members in shard_groups(list(fresh.values())).items():
            telemetry.gauge("fleet_shard_group_members", group=gid).set(
                float(len(members)))
            if group_complete(members) and all(
                    health.get(m.replica_id, True) for m in members):
                groups[gid] = members
                eligible[f"group:{gid}"] = members[0]
        with self._lock:
            self._eligible = eligible
            self._groups = groups
        self.ring.set_members(eligible.keys())
        telemetry.gauge("fleet_router_eligible_total").set(
            float(len(eligible)))

    def _poll_health_once(self) -> None:
        # QT004 keeps http.server out of library modules; the CLIENT
        # side (urllib) is fine and is how the router consumes the
        # ladder each replica's MetricsServer already sells
        import urllib.request

        with self._lock:
            infos = list(self._eligible.values())
            # group units carry only the coordinator in _eligible; poll
            # EVERY member so a wedged non-coordinator shard still takes
            # the whole group off the ring on the next refresh
            for members in self._groups.values():
                infos.extend(members)
        targets = sorted({(r.replica_id, r.host,
                           int(r.detail.get("metrics_port", 0)))
                          for r in infos})
        for rid, host, mport in targets:
            if mport <= 0:
                continue  # no health endpoint: membership state governs
            ok = False
            try:
                with urllib.request.urlopen(
                        f"http://{host}:{mport}/healthz",
                        timeout=self.request_timeout_s) as resp:
                    ok = resp.status == 200 and bool(
                        json.loads(resp.read()).get("ready"))
            except (OSError, ValueError):
                ok = False
            with self._lock:
                self._health_ok[rid] = ok

    def start_health_poller(self) -> "FleetRouter":
        """Background ``/healthz`` poll loop (optional — tests may call
        :meth:`_poll_health_once` deterministically instead)."""

        def _loop():
            while not self._hp_stop.wait(self.health_poll_s):
                try:
                    self._poll_health_once()
                except Exception as e:
                    # the poller must outlive flaky replicas; a failed
                    # sweep leaves the previous health view in place
                    log.warning("fleet health poll failed: %s", e)

        self._hp_stop.clear()
        self._hp_thread = threading.Thread(
            target=_loop, daemon=True, name="quiver-fleet-health")
        self._hp_thread.start()
        return self

    # -- write path (leader resolution) --------------------------------
    def write_path(self) -> Optional[ReplicaInfo]:
        """The current write endpoint: the fleet's fresh leader record,
        epoch-aware.

        Keyed by ``(leader_id, epoch)`` — when a fenced failover moves
        the epoch, the next call observes the change, ticks
        ``fleet_router_write_path_changes_total`` and hands back the
        successor, so writers re-resolve instead of appending at a
        deposed leader's endpoint (whose fence would refuse them
        anyway; this avoids even sending the bytes).  Returns None
        while no fresh leader exists (mid-failover window).  The metric
        is only created once a write path actually moves — a read-only
        fleet never grows a key."""
        leader = self.directory.leader()
        if leader is None:
            with self._lock:
                self._write_path = None
            return None
        key = (leader.replica_id, leader.epoch)
        with self._lock:
            prev = self._write_path
            self._write_path = key
        if prev is not None and prev != key:
            telemetry.counter(
                "fleet_router_write_path_changes_total").inc()
            log.warning("fleet write path moved: %s (epoch %d) -> %s "
                        "(epoch %d)", prev[0], prev[1], key[0], key[1])
        return leader

    # -- placement -----------------------------------------------------
    def partition_of(self, ids) -> int:
        try:
            first = int(ids[0])
        except (IndexError, TypeError, ValueError):
            first = 0
        return first % self.partitions

    def _is_hot(self, tenant: Optional[str]) -> bool:
        if tenant is None:
            return False
        from ..resilience.qos import get_qos

        controller = get_qos()
        if controller is None:
            return False
        klass = controller.resolve(tenant)
        return klass is not None and klass.priority >= self.hot_priority

    def candidates(self, partition: int,
                   tenant: Optional[str] = None) -> List[str]:
        """Dispatch order for a partition.  Hot tenants use power-of-
        two-choices between the top two preferred replicas (least
        in-flight wins) so a zipfian head key cannot melt one replica;
        everyone else gets plain preference order (cache affinity)."""
        prefs = self.ring.preference(partition)
        if len(prefs) >= 2 and self._is_hot(tenant):
            with self._lock:
                a, b = (self._inflight.get(prefs[0], 0),
                        self._inflight.get(prefs[1], 0))
            if b < a:
                prefs[0], prefs[1] = prefs[1], prefs[0]
        return prefs

    # -- dispatch ------------------------------------------------------
    def request(self, ids, tenant: Optional[str] = None,
                seq: Optional[int] = None,
                sleep: Callable[[float], None] = time.sleep) -> dict:
        """Route one serving request; returns the replica's reply dict.

        Transport failures and ``unavailable`` replies re-dispatch to
        the next candidate (bounded); ``ok``/``shed``/``error`` replies
        are answers and return immediately.  Raises
        :class:`NoReplicaAvailable` when the budget is exhausted —
        never returns silence.
        """
        _CHAOS_ROUTE()
        self.refresh()
        partition = self.partition_of(ids)
        prefs = self.candidates(partition, tenant)
        budget = 1 + max(self.route_retries, 0)
        req = {"ids": list(map(int, ids)), "tenant": tenant}
        if seq is not None:
            req["seq"] = seq
        ctx = hop = None
        if self.federation_enabled:
            ctx, hop = self._trace_begin(req, tenant, partition)
        attempts = 0
        tried: set = set()
        try:
            for attempt in range(budget):
                if attempt >= 1:
                    # the fleet may have changed under us (that is the
                    # point of re-dispatch) — rebuild the candidate
                    # list, but never hand the request back to a
                    # replica that already refused it: "unavailable"
                    # keeps the replica eligible (honest refusal, not a
                    # health strike), so without the exclusion the
                    # recomputed preference order re-picks the same
                    # replica instead of the NEXT one
                    self.refresh(force=True)
                    prefs = [p for p
                             in self.candidates(partition, tenant)
                             if p not in tried]
                target = self._pick(prefs)
                if target is None:
                    break
                attempts += 1
                tried.add(target)
                t_attempt = time.perf_counter()
                reply = self._dispatch(target, req)
                if hop is not None:
                    self._hop_attempt(hop, ctx, target, t_attempt, reply)
                if reply is not None:
                    telemetry.counter("fleet_router_requests_total",
                                      replica=target,
                                      status=reply.get("status",
                                                       "ok")).inc()
                    if hop is not None:
                        hop["status"] = reply.get("status", "ok")
                    return reply
                # transport-level failure: the request is still ours to
                # answer — re-dispatch after a short breather
                telemetry.counter("fleet_router_redispatch_total",
                                  replica=target).inc()
                prefs = [p for p in prefs if p != target]
                if attempt + 1 < budget:
                    sleep(self.backoff.delay(attempt))
            telemetry.counter("fleet_router_unroutable_total").inc()
            raise NoReplicaAvailable(partition, attempts)
        finally:
            if hop is not None:
                self._trace_finish(hop, ctx)

    def _pick(self, prefs: List[str]) -> Optional[str]:
        for rid in prefs:
            if get_breaker(f"fleet.{rid}").allow():
                return rid
        return None

    def _dispatch(self, replica_id: str, req: dict) -> Optional[dict]:
        """One attempt against one replica.  Returns the reply dict, or
        None for a transport-level failure / ``unavailable`` (both mean
        "try another replica")."""
        with self._lock:
            info = self._eligible.get(replica_id)
            self._inflight[replica_id] = \
                self._inflight.get(replica_id, 0) + 1
        breaker = get_breaker(f"fleet.{replica_id}")
        try:
            if info is None:
                raise OSError(f"replica {replica_id} left the fleet")
            with socket.create_connection(
                    (info.host, info.port),
                    timeout=self.request_timeout_s) as conn:
                conn.sendall((json.dumps(req) + "\n").encode())
                with conn.makefile("rb") as f:
                    line = f.readline()
            if not line:
                raise OSError(f"replica {replica_id} closed mid-request")
            reply = json.loads(line)
            if reply.get("status") == "unavailable":
                # honest refusal (booting/draining): not a strike worth
                # a full breaker trip, but not an answer either
                breaker.record_failure()
                return None
            breaker.record_success()
            return reply
        except (OSError, ValueError):
            breaker.record_failure()
            with self._lock:
                self._health_ok[replica_id] = False
            return None
        finally:
            with self._lock:
                self._inflight[replica_id] -= 1

    # -- cross-process tracing (only reached with federation on) -------
    def _priority(self, tenant: Optional[str]) -> int:
        if tenant is None:
            return 0
        from ..resilience.qos import get_qos

        controller = get_qos()
        if controller is None:
            return 0
        klass = controller.resolve(tenant)
        return int(klass.priority) if klass is not None else 0

    def _trace_begin(self, req: dict, tenant: Optional[str],
                     partition: int):
        """Stamp the active TraceContext into the wire payload and open
        a hop record.  The trace_id is fleet-qualified in place
        (``<origin>:<local>``) so the router-side record, the replica's
        rehydrated record, and every timeline event join on ONE id and
        per-process ``_next_trace_id`` sequences never collide."""
        ctx = flightrec.current()
        if ctx is None:
            ctx = flightrec.new_trace()
        if ctx is None:  # telemetry disabled: nothing to propagate
            return None, None
        if ":" not in ctx.trace_id:
            ctx.trace_id = f"{self.origin}:{ctx.trace_id}"
        trace = {"trace_id": ctx.trace_id, "origin": self.origin,
                 "tenant": tenant, "priority": self._priority(tenant)}
        deadline = self._deadline_remaining()
        if deadline is not None:
            # ship the REMAINING budget, not the absolute deadline —
            # perf_counter epochs are per-process; the replica
            # re-anchors it on its own clock
            trace["deadline_s"] = deadline
        req["trace"] = trace
        hop = {"trace_id": ctx.trace_id, "origin": self.origin,
               "partition": partition, "tenant": tenant,
               "priority": trace["priority"],
               "wall_start": time.time(),
               "t_start": time.perf_counter(),
               "attempts": [], "status": "unroutable"}
        ctx.add("fleet.route", {"partition": partition,
                                "router": self.origin})
        return ctx, hop

    @staticmethod
    def _deadline_remaining() -> Optional[float]:
        from ..resilience.deadline import ambient_deadline

        deadline = ambient_deadline()
        if deadline is None:
            return None
        return max(deadline - time.perf_counter(), 0.0)

    def _hop_attempt(self, hop: dict, ctx, target: str,
                     t_attempt: float, reply: Optional[dict]) -> None:
        dt = time.perf_counter() - t_attempt
        outcome = ("redispatch" if reply is None
                   else reply.get("status", "ok"))
        hop["attempts"].append({
            "replica": target, "outcome": outcome,
            "t_offset_s": round(t_attempt - hop["t_start"], 6),
            "seconds": round(dt, 6),
        })
        ctx.add("fleet.dispatch", {"replica": target, "outcome": outcome,
                                   "seconds": dt})
        if _timeline._ON:  # one global read when the timeline is off
            _timeline.emit("fleet.dispatch", cat="fleet", dur_s=dt,
                           t0=t_attempt,
                           attrs={"replica": target, "outcome": outcome},
                           trace=ctx)

    def _trace_finish(self, hop: dict, ctx) -> None:
        e2e = time.perf_counter() - hop["t_start"]
        hop["e2e_seconds"] = round(e2e, 6)
        if _timeline._ON:  # one global read when the timeline is off
            _timeline.emit("fleet.route", cat="fleet", dur_s=e2e,
                           t0=hop["t_start"],
                           attrs={"partition": hop["partition"],
                                  "status": hop["status"],
                                  "attempts": len(hop["attempts"])},
                           trace=ctx)
        with self._lock:
            while len(self._hop_ids) >= self.hop_capacity:
                self._hops.pop(self._hop_ids.pop(0), None)
            if hop["trace_id"] not in self._hops:
                self._hop_ids.append(hop["trace_id"])
            self._hops[hop["trace_id"]] = hop

    def hop_record(self, trace_id: str) -> Optional[dict]:
        """The router-side record for one fleet trace_id (what
        ``/debug/fleet/trace/<id>`` joins with the replica's flight
        record), or None when it aged out of the ring."""
        with self._lock:
            hop = self._hops.get(trace_id)
            return dict(hop) if hop is not None else None

    def hop_records(self, limit: int = 50) -> List[dict]:
        """The newest retained hop records, oldest first."""
        with self._lock:
            ids = self._hop_ids[-max(int(limit), 0):]
            return [dict(self._hops[i]) for i in ids if i in self._hops]

    def hop_count(self) -> int:
        with self._lock:
            return len(self._hop_ids)

    # -- introspection -------------------------------------------------
    def status(self) -> dict:
        """JSON view for ``/debug/fleet``."""
        from ..resilience.breaker import breakers_status

        with self._lock:
            eligible = sorted(self._eligible)
            inflight = dict(self._inflight)
            health = dict(self._health_ok)
            groups = {gid: [m.replica_id for m in members]
                      for gid, members in self._groups.items()}
        return {
            "partitions": self.partitions,
            "shard_groups": groups,
            "route_retries": self.route_retries,
            "federation": self.federation_enabled,
            "origin": self.origin,
            "hop_records": self.hop_count(),
            "eligible": eligible,
            "ring_members": list(self.ring.members),
            "inflight": inflight,
            "health_ok": health,
            "breakers": {name: st for name, st in
                         breakers_status().items()
                         if name.startswith("fleet.")},
            "membership": self.directory.status(),
        }

    def start_federation(self) -> "FleetRouter":
        """Start the federation's background sweep (no-op with
        federation off; tests may call
        ``router.federation.scrape_once()`` deterministically
        instead)."""
        if self.federation is not None:
            self.federation.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        self._hp_stop.set()
        if self._hp_thread is not None:
            join_and_reap([self._hp_thread], timeout,
                          component="fleet.route")
            self._hp_thread = None
        if self.federation is not None:
            self.federation.stop(timeout)
            self.federation = None
        _clear_active(self)


# -- /debug/fleet plumbing (weakref, same pattern as recovery.manager) --
_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional[Callable] = None


def _set_active(router: FleetRouter) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = weakref(router)


def _clear_active(router: FleetRouter) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None and _ACTIVE() is router:
            _ACTIVE = None


def fleet_status() -> dict:
    """Status of the most recently constructed router in this process
    (the ``/debug/fleet`` document); ``{"active": False}`` when none."""
    with _ACTIVE_LOCK:
        router = _ACTIVE() if _ACTIVE is not None else None
    if router is None:
        return {"active": False}
    return dict(router.status(), active=True)
