"""Fenced leader election: auto-failover without a coordination service.

The fleet's write path is single-appender by construction — exactly one
leader owns the WAL.  PR 13 made leader *placement* static: the process
started with ``role="leader"`` is the leader until an operator says
otherwise.  This module closes the gap for leader *death*: when the
leader's membership heartbeat expires, followers race to promote the
most-caught-up candidate, and an **epoch fencing token** guarantees the
single-appender invariant survives the race — even against a deposed
leader that is merely suspended, not dead.

The ladder (docs/FLEET.md "Leader failover & fencing"):

  1. **detect** — no fresh leader record and no fresh claim for longer
     than the heartbeat timeout.
  2. **rank** — fresh members sort by (replayed LSN desc, replica id
     asc).  Rank r waits ``r * fleet_election_stagger_s`` before
     claiming, so the most-caught-up follower claims first unless it is
     dead too.
  3. **claim** — publish ``election/claim-<epoch:020d>.json`` through
     ``blockio.atomic_publish(..., exclusive=True)``: tmp + fsync +
     ``os.link``.  The link is a filesystem compare-and-swap — exactly
     one racer owns each epoch, and a reader sees a complete record or
     none.  The new epoch is ``highest claimed + 1``.
  4. **promote** — the winner stops its follower tail, opens the WAL
     (truncating the dead leader's torn debris), folds in the durable
     tail with the same two-pass abort-aware replay boot uses (the
     abort-holdback contract carries through promotion), and starts an
     ingest lane whose appends are fenced.
  5. **fence** — every WAL append / roll / truncate of a fenced writer
     first checks the claim directory; a claim with a higher epoch by
     another replica means *deposed*: the write raises
     :class:`StaleEpochError` and is never durable.  Membership records
     carry the epoch too, so ``FleetMembership.leader()`` resolves
     split-brain windows by epoch, and the router re-resolves the write
     path when the epoch moves.

Liveness is heartbeat-age, exactly like membership: no quorum, no
consensus — the claim file's exclusivity is the only atomic primitive,
and the fencing token is what makes "two processes briefly believe they
lead" harmless (the stale one cannot write).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from .. import telemetry
from ..recovery import blockio
from ..recovery.errors import WALWriteError
from ..resilience import chaos
from .membership import MembershipDirectory, ReplicaInfo

__all__ = ["StaleEpochError", "ClaimRecord", "ElectionDirectory",
           "EpochFence", "FencedWAL", "LeaderElector"]

log = logging.getLogger("quiver_tpu.fleet")

_CHAOS_CLAIM = chaos.point("fleet.election.claim")

_CLAIM_RE = re.compile(r"^claim-(\d{20})\.json$")


class StaleEpochError(WALWriteError):
    """A fenced write from a deposed leader: the claim directory holds
    a higher epoch owned by another replica.  Subclasses
    :class:`WALWriteError` so the ingest worker nacks the op exactly
    like any other durability failure — nothing was appended, nothing
    is acked."""


@dataclass
class ClaimRecord:
    """One epoch-stamped leadership claim."""

    epoch: int
    leader_id: str
    claim_lsn: int = -1          # the claimant's replayed LSN at claim time
    wall: float = 0.0            # wall-clock claim time (cross-process)

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "leader_id": self.leader_id,
                "claim_lsn": self.claim_lsn, "wall": self.wall}

    @classmethod
    def from_dict(cls, d: dict) -> "ClaimRecord":
        return cls(epoch=int(d["epoch"]), leader_id=str(d["leader_id"]),
                   claim_lsn=int(d.get("claim_lsn", -1)),
                   wall=float(d.get("wall", 0.0)))


class ElectionDirectory:
    """``<fleet_dir>/election/claim-<epoch>.json`` claim files.

    Append-only by construction: a claim is published exclusively (the
    ``os.link`` CAS in ``blockio.atomic_publish``) and never modified.
    The current leadership is simply the highest parseable epoch; old
    claims are pruned opportunistically, newest-first readers never
    depend on them."""

    def __init__(self, fleet_root: str):
        self.root = os.path.join(str(fleet_root), "election")
        os.makedirs(self.root, exist_ok=True)

    def _epochs(self) -> List[int]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for n in names:
            m = _CLAIM_RE.match(n)
            if m:
                out.append(int(m.group(1)))
        out.sort()
        return out

    def top(self) -> Optional[ClaimRecord]:
        """The highest-epoch claim, or None.  A claim file unlinked (a
        concurrent prune) or unparseable between listdir and open falls
        through to the next epoch down — a scan never dies on one bad
        file."""
        for epoch in reversed(self._epochs()):
            path = os.path.join(self.root, f"claim-{epoch:020d}.json")
            try:
                with open(path, "rb") as f:
                    return ClaimRecord.from_dict(json.loads(f.read()))
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return None

    def claim(self, record: ClaimRecord) -> bool:
        """Atomically claim ``record.epoch``; True iff this call won the
        epoch.  Exactly one racer can ever win one epoch — the loser
        re-reads :meth:`top` and stands down."""
        _CHAOS_CLAIM()
        path = os.path.join(self.root, f"claim-{record.epoch:020d}.json")
        data = json.dumps(record.to_dict(), sort_keys=True).encode()
        won = blockio.atomic_publish(path, data, exclusive=True)
        telemetry.counter("fleet_election_claims_total",
                          outcome="won" if won else "lost").inc()
        return won

    def prune(self, keep: int = 16) -> int:
        """Drop all but the newest ``keep`` claims; races are fine (the
        loser of an unlink race just counts 0 for that file)."""
        removed = 0
        for epoch in self._epochs()[:-keep] if keep else self._epochs():
            try:
                os.unlink(os.path.join(self.root,
                                       f"claim-{epoch:020d}.json"))
                removed += 1
            except OSError:
                continue
        return removed


class EpochFence:
    """The fencing token check a fenced writer runs before every write.

    Holds the epoch this process claimed; :meth:`check` re-reads the
    claim directory (at most every ``recheck_s`` seconds — 0 means
    every call, what the tests and the chaos harness use) and raises
    :class:`StaleEpochError` once a higher epoch owned by someone else
    exists.  Deposition is sticky: once seen, every later write refuses
    immediately without touching the filesystem."""

    _guarded_by = {"_deposed": "_lock", "_checked_at": "_lock"}

    def __init__(self, election_dir: ElectionDirectory, epoch: int,
                 owner: str, recheck_s: Optional[float] = None):
        from ..config import get_config

        self.election_dir = election_dir
        self.epoch = int(epoch)
        self.owner = str(owner)
        self.recheck_s = float(
            recheck_s if recheck_s is not None
            else get_config().fleet_election_fence_recheck_s)
        self._lock = threading.Lock()
        self._deposed = False
        self._checked_at = -float("inf")

    @property
    def deposed(self) -> bool:
        with self._lock:
            return self._deposed

    def check(self) -> None:
        """Raise :class:`StaleEpochError` when this epoch is fenced off."""
        now = time.monotonic()
        with self._lock:
            deposed = self._deposed
            due = (now - self._checked_at) >= self.recheck_s
            if due:
                self._checked_at = now
        if not deposed and due:
            top = self.election_dir.top()
            if (top is not None and top.epoch > self.epoch
                    and top.leader_id != self.owner):
                with self._lock:
                    self._deposed = True
                deposed = True
        if deposed:
            telemetry.counter("fleet_election_fenced_writes_total",
                              replica=self.owner).inc()
            raise StaleEpochError(
                f"epoch {self.epoch} fenced off (replica {self.owner} "
                "deposed): a higher claim exists")


class FencedWAL:
    """An epoch-fenced view of :class:`~quiver_tpu.recovery.wal.
    WriteAheadLog`: ``append``/``roll``/``truncate_through`` first run
    the fence check, everything else delegates.  A deposed leader's
    write raises before a single byte lands — the cross-process half of
    the single-appender invariant (the WAL's own lock is the
    in-process half)."""

    def __init__(self, wal, fence: EpochFence):
        self._wal = wal
        self.fence = fence

    def append(self, payload: bytes) -> int:
        self.fence.check()
        return self._wal.append(payload)

    def roll(self) -> None:
        self.fence.check()
        self._wal.roll()

    def truncate_through(self, lsn: int) -> int:
        self.fence.check()
        return self._wal.truncate_through(lsn)

    def __getattr__(self, name):
        return getattr(self._wal, name)


class LeaderElector:
    """The per-replica election loop: leader-death detection, ranked
    candidacy, atomic claim, promotion/demotion callbacks.

    Pure control plane — it never touches the WAL itself.  Callbacks:

      * ``applied_lsn_fn()`` — this replica's replayed LSN (candidacy
        currency; leaders report their append frontier).
      * ``role_fn()`` — current role, ``"leader"`` | ``"follower"``.
      * ``promote_fn(claim)`` — this replica just won ``claim``; make
        it the leader (replica.py's promotion path).
      * ``demote_fn(claim)`` — a higher epoch owned by someone else
        exists while ``role_fn()`` says leader; step down.

    Drive it with :meth:`start` (daemon thread at
    ``fleet_election_poll_s``) or deterministically with :meth:`step`.
    """

    _guarded_by = {"epoch": "_lock", "_dead_since": "_lock"}

    def __init__(self, directory: MembershipDirectory, replica_id: str,
                 applied_lsn_fn: Callable[[], int],
                 role_fn: Callable[[], str],
                 promote_fn: Optional[Callable[[ClaimRecord], None]] = None,
                 demote_fn: Optional[Callable[[ClaimRecord], None]] = None,
                 poll_s: Optional[float] = None,
                 stagger_s: Optional[float] = None,
                 timeout_s: Optional[float] = None):
        from ..config import get_config

        cfg = get_config()
        self.directory = directory
        self.election_dir = ElectionDirectory(directory.root)
        self.replica_id = str(replica_id)
        self.applied_lsn_fn = applied_lsn_fn
        self.role_fn = role_fn
        self.promote_fn = promote_fn
        self.demote_fn = demote_fn
        self.poll_s = float(poll_s if poll_s is not None
                            else cfg.fleet_election_poll_s)
        self.stagger_s = float(stagger_s if stagger_s is not None
                               else cfg.fleet_election_stagger_s)
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else cfg.fleet_heartbeat_timeout_s)
        self._lock = threading.Lock()
        self.epoch = -1               # the epoch this replica holds, if any
        self._dead_since: Optional[float] = None
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"quiver-fleet-elector-{self.replica_id}")

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "LeaderElector":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        self._stop_evt.set()
        if self._thread.is_alive():
            join_and_reap([self._thread], timeout,
                          component="fleet.election")

    def is_running(self) -> bool:
        return self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.step()
            except Exception as e:
                # an elector that dies silently turns a failover fleet
                # back into a static one; log and keep polling
                log.warning("elector %s step failed: %s",
                            self.replica_id, e)
            self._stop_evt.wait(self.poll_s)

    # -- the ladder ----------------------------------------------------
    def claim_initial(self) -> ClaimRecord:
        """Boot-time claim for a configured leader: epoch = highest
        claimed + 1, retried past racers (a booting leader outranks any
        dead predecessor's claim by construction)."""
        while True:
            top = self.election_dir.top()
            epoch = (top.epoch if top is not None else 0) + 1
            rec = ClaimRecord(
                epoch=epoch, leader_id=self.replica_id,
                claim_lsn=int(self.applied_lsn_fn()),
                # quiverlint: ignore[QT012] -- claim freshness is
                # compared across processes; wall clock is the only
                # shared clock and the timeout absorbs NTP steps
                wall=time.time())
            if self.election_dir.claim(rec):
                with self._lock:
                    self.epoch = epoch
                telemetry.gauge("fleet_election_epoch").set(float(epoch))
                return rec

    def _rank(self) -> int:
        """This replica's position in the promotion order (0 = claim
        now).  Candidates are fresh members ranked most-caught-up
        first; an unlisted self ranks last (it cannot prove catch-up)."""
        peers = [r for r in self.directory.replicas(fresh_only=True)
                 if r.state not in ("draining",)]
        me = int(self.applied_lsn_fn())

        def key(r: ReplicaInfo):
            applied = (me if r.replica_id == self.replica_id
                       else r.wal_next_lsn - 1)
            return (-applied, r.replica_id)

        order = sorted(peers, key=key)
        for i, r in enumerate(order):
            if r.replica_id == self.replica_id:
                return i
        return len(order)

    def step(self, now: Optional[float] = None) -> Optional[str]:
        """One election pass; returns the action taken (None | "claimed"
        | "lost" | "demoted") — what the tests assert on."""
        now = time.monotonic() if now is None else now
        top = self.election_dir.top()
        if top is not None:
            telemetry.gauge("fleet_election_epoch").set(float(top.epoch))
        role = self.role_fn()
        with self._lock:
            my_epoch = self.epoch
        if role == "leader":
            if (top is not None and top.epoch > my_epoch
                    and top.leader_id != self.replica_id):
                log.warning("replica %s deposed by %s (epoch %d > %d)",
                            self.replica_id, top.leader_id, top.epoch,
                            my_epoch)
                if self.demote_fn is not None:
                    self.demote_fn(top)
                return "demoted"
            return None
        # follower: is there a live leader (fresh record or fresh claim)?
        leader = self.directory.leader()
        claim_fresh = (
            top is not None
            # quiverlint: ignore[QT012] -- claim freshness is cross-
            # process; wall clock is the only shared clock, the timeout
            # absorbs NTP steps
            and (time.time() - top.wall) <= self.timeout_s)
        if leader is not None or claim_fresh:
            with self._lock:
                self._dead_since = None
            return None
        with self._lock:
            if self._dead_since is None:
                self._dead_since = now
                return None
            dead_for = now - self._dead_since
        rank = self._rank()
        if dead_for < rank * self.stagger_s:
            return None
        epoch = (top.epoch if top is not None else 0) + 1
        rec = ClaimRecord(
            epoch=epoch, leader_id=self.replica_id,
            claim_lsn=int(self.applied_lsn_fn()),
            # quiverlint: ignore[QT012] -- cross-process freshness stamp
            wall=time.time())
        if not self.election_dir.claim(rec):
            # a racer beat us to this epoch — its claim is now the fresh
            # one; stand down and re-observe
            with self._lock:
                self._dead_since = None
            return "lost"
        with self._lock:
            self.epoch = epoch
            self._dead_since = None
        telemetry.counter("fleet_election_promotions_total",
                          replica=self.replica_id).inc()
        telemetry.gauge("fleet_election_epoch").set(float(epoch))
        log.warning("replica %s claimed leadership (epoch %d, lsn %d)",
                    self.replica_id, epoch, rec.claim_lsn)
        if self.promote_fn is not None:
            self.promote_fn(rec)
        return "claimed"
