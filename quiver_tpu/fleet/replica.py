"""Fleet replica lifecycle: warm join, serve, drain, rejoin.

One :class:`FleetReplica` is one serving process in the fleet.  Two
roles share the same lifecycle and differ only in how state arrives:

  * **leader** (exactly one) — owns the WAL.  Boots through
    :class:`~quiver_tpu.recovery.manager.RecoveryManager` (checkpoint
    restore + tail replay), attaches an
    :class:`~quiver_tpu.stream.ingest.IngestLane` so every write is
    durable-before-ack, and runs the periodic checkpointer that lets
    followers resync and the log truncate.
  * **follower** (N) — read replica.  Restores the newest *shared*
    checkpoint, then tails the leader's WAL through
    :class:`~quiver_tpu.fleet.shipping.WALFollower`; never opens the
    log for writing.

Both climb the same readiness ladder the single-node tier defined
(``booting → replaying → warming → serving``) and announce every rung
into the shared :class:`~quiver_tpu.fleet.membership.
MembershipDirectory`, so the router's view of "who can take traffic"
is the same contract ``/healthz`` sells.  The join path IS the PR 8
warm-boot path: with ``config.recovery_cache_dir`` set, a joining
replica enables the JAX persistent compilation cache before building
anything, runs its warmup against cached executables, and can ``seal``
its program registry — a rejoin that recompiles is a loud budget
violation, not a silent p99 cliff.

Serving transport is a deliberately small TCP JSON-lines protocol
(stdlib ``socketserver``; the metrics/health HTTP endpoint stays in
``telemetry.export``): one JSON object per line in, one per line out,
multiple requests per connection.  Answers are ``status: ok``, a typed
``shed`` (still an *answer* — the router never re-dispatches it), or
``unavailable`` (booting/draining — the router treats it as a
transport failure and re-dispatches).  Drain is explicit: announce
``draining``, refuse new admissions, finish in-flight requests,
deregister, stop — the inverse of join, and chaos-tested in
``benchmarks/fleet_chaos.py``.
"""

from __future__ import annotations

import json
import logging
import os
import socketserver
import threading
import time
from typing import Callable, Optional

from .. import telemetry
from ..recovery.checkpoint import load_checkpoint, restore_graph
from ..recovery.errors import RecoveryError
from ..resilience import chaos
from ..resilience.errors import (ChaosFault, DeadlineExceeded, LoadShed,
                                 QuotaExceeded)
from ..telemetry import flightrec
from .membership import FLEET_STATES, MembershipDirectory, ReplicaInfo
from .shipping import WALFollower

__all__ = ["FleetReplica"]

log = logging.getLogger("quiver_tpu.fleet")

_CHAOS_JOIN = chaos.point("fleet.join")
# fires inside the serving handler after trace rehydration: an injected
# fault models a replica that accepted the connection but cannot answer
# (honest `unavailable`, the router re-dispatches) — how the fleet-chaos
# harness proves one trace_id lands on two replica timelines
_CHAOS_SERVE = chaos.point("fleet.serve")

# typed sheds cross the wire as answers; everything else is an error
_SHED_TYPES = (LoadShed, DeadlineExceeded, QuotaExceeded)


class _ReplicaTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FleetReplica:
    """One fleet member: boot → announce → serve → drain/stop."""

    _guarded_by = {
        "_state": "_lock", "_stale": "_lock", "_inflight": "_lock",
        "_draining": "_lock", "_boot_seconds": "_lock",
        "manager": "_lock", "graph": "_lock", "follower": "_lock",
        "_server": "_lock", "metrics_server": "_lock", "epoch": "_lock",
        "role": "_lock", "walstream_server": "_lock",
    }

    def __init__(self, replica_id: str, fleet_dir: Optional[str] = None,
                 root: Optional[str] = None,
                 graph_factory: Optional[Callable] = None,
                 role: str = "follower", host: str = "127.0.0.1",
                 port: int = 0,
                 service_fn: Optional[Callable] = None,
                 heartbeat_s: Optional[float] = None,
                 warmup: Optional[Callable] = None, seal: bool = False,
                 catchup_timeout_s: float = 30.0,
                 shard_group: Optional[str] = None,
                 shard_index: Optional[int] = None,
                 shard_count: Optional[int] = None):
        from ..config import get_config

        cfg = get_config()
        if role not in ("leader", "follower"):
            raise ValueError(f"role must be leader|follower, got {role!r}")
        self.replica_id = str(replica_id)
        self.role = role
        fleet_dir = str(fleet_dir if fleet_dir is not None
                        else cfg.fleet_dir)
        if not fleet_dir:
            raise RecoveryError(
                "no fleet directory: pass fleet_dir= or set "
                "QUIVER_TPU_FLEET_DIR / config.fleet_dir")
        root = str(root if root is not None else cfg.recovery_dir)
        if not root:
            raise RecoveryError(
                "no durability root: pass root= or set "
                "QUIVER_TPU_RECOVERY_DIR / config.recovery_dir")
        self.root = root
        self.wal_dir = os.path.join(root, "wal")
        self.ckpt_dir = os.path.join(root, "ckpt")
        self.directory = MembershipDirectory(fleet_dir)
        self.graph_factory = graph_factory
        self.host = host
        self._requested_port = int(port)
        self.service_fn = service_fn
        self.warmup = warmup
        self.seal = bool(seal)
        self.catchup_timeout_s = float(catchup_timeout_s)
        self.heartbeat_s = float(heartbeat_s if heartbeat_s is not None
                                 else cfg.fleet_heartbeat_s)
        # mesh shard-group membership (docs/SHARDING.md): defaults come
        # from the mesh_* config knobs so every member of a sharded
        # launch announces the same group without per-process plumbing;
        # unsharded replicas (no group) announce exactly as before
        self.shard_group = str(shard_group if shard_group is not None
                               else cfg.mesh_group)
        self.shard_index = int(shard_index if shard_index is not None
                               else cfg.mesh_shard_index)
        self.shard_count = int(shard_count if shard_count is not None
                               else cfg.mesh_shards)
        self.graph = None
        self.manager = None           # leader only (RecoveryManager)
        self.lane = None              # leader only (IngestLane)
        self.follower = None          # follower only (TailFollower)
        self.metrics_server = None
        # fleet-autonomy flags resolved ONCE at construction: the off
        # path must stay byte-identical — no election/walstream import,
        # no extra threads, no new metric keys
        self._election_enabled = str(cfg.fleet_election).lower() in (
            "on", "1", "true", "yes")
        self._walstream_enabled = str(cfg.fleet_walstream).lower() in (
            "on", "1", "true", "yes")
        self.elector = None           # LeaderElector when election is on
        self.fence = None             # EpochFence while leading, fenced
        self.epoch = -1               # the fencing epoch currently held
        self.walstream_server = None  # WALStreamServer while leading
        self._lock = threading.Lock()
        self._state = "booting"
        self._stale = True
        self._inflight = 0
        self._draining = False
        self._boot_seconds: Optional[float] = None
        self._server: Optional[_ReplicaTCPServer] = None
        self._server_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None

    # -- readiness ladder ---------------------------------------------
    def _set_state(self, state: str, stale: Optional[bool] = None) -> None:
        assert state in FLEET_STATES
        with self._lock:
            self._state = state
            if stale is not None:
                self._stale = stale
        self._announce()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def health(self) -> dict:
        """Per-replica ``/healthz`` document (instance-scoped, NOT the
        process-global recovery view — N replicas on one host each
        report their own ladder)."""
        with self._lock:
            state, stale = self._state, self._stale
        out = {
            "replica_id": self.replica_id,
            "role": self.role,
            "state": state,
            "ready": state == "serving",
            "stale": stale,
            "managed": True,
        }
        if self.graph is not None:
            out["graph_version"] = int(self.graph.version)
        if self._boot_seconds is not None:
            out["boot_seconds"] = round(self._boot_seconds, 3)
        if self.follower is not None:
            st = self.follower.status()
            out["staleness_lsn"] = st["staleness_lsn"]
            out["staleness_seconds"] = st["staleness_seconds"]
            out["applied_lsn"] = st["applied_lsn"]
        if self.manager is not None and self.manager.wal is not None:
            out["wal_next_lsn"] = self.manager.wal.next_lsn
        return out

    # -- boot ----------------------------------------------------------
    def boot(self) -> "FleetReplica":
        """Join the fleet: warm-boot state, reach ``serving``, open the
        TCP endpoint, start heartbeats."""
        _CHAOS_JOIN()
        t0 = time.perf_counter()
        if self.role == "leader":
            self._boot_leader()
        else:
            self._boot_follower()
        self._start_server()
        boot_seconds = time.perf_counter() - t0
        with self._lock:
            self._boot_seconds = boot_seconds
        telemetry.gauge("fleet_join_seconds",
                        replica=self.replica_id).set(boot_seconds)
        self._set_state("serving", stale=False)
        self._start_heartbeat()
        if self._election_enabled:
            # leaders already claimed in _boot_leader; followers get a
            # fresh elector.  The loop watches for leader death
            # (followers) and deposition (leaders) from here on.
            if self.elector is None:
                self.elector = self._make_elector()
            self.elector.start()
        return self

    def _boot_leader(self) -> None:
        from ..recovery.manager import RecoveryManager
        from ..stream import IngestLane

        self._set_state("booting", stale=True)
        with self._lock:
            self.manager = RecoveryManager(
                self.root, graph_factory=self.graph_factory)
        with self._lock:
            self.graph = self.manager.boot_degraded()
        self._set_state("replaying", stale=True)
        self.manager.finish_boot(warmup=self.warmup, seal=self.seal)
        if self._election_enabled:
            # claim an epoch BEFORE the first fenced append: a booting
            # configured leader outranks any dead predecessor's claim
            self.elector = self._make_elector()
            self._install_fence(self.elector.claim_initial())
        self.lane = IngestLane(self.graph).start()
        self.manager.attach_lane(self.lane)
        if self._walstream_enabled:
            self._start_walstream()
        self._set_state("warming", stale=False)

    def _boot_follower(self) -> None:
        from ..config import get_config

        cfg = get_config()
        self._set_state("booting", stale=True)
        if cfg.recovery_cache_dir:
            # the PR 8 warm-boot path IS the fleet join path: compiled
            # programs come off the shared disk cache, not the compiler
            from ..recovery.registry import get_program_registry

            get_program_registry().enable_persistent_cache(
                cfg.recovery_cache_dir)
        start_lsn = self._restore_from_checkpoint()
        self._set_state("replaying", stale=True)
        self._start_follower(start_lsn)
        self._await_catchup()
        self._set_state("warming", stale=False)
        if self.warmup is not None:
            self.warmup(self.graph)
        if self.seal:
            from ..recovery.registry import get_program_registry

            get_program_registry().seal()

    def _start_follower(self, start_lsn: int) -> None:
        """Start the WAL tail — file tail over the shared directory, or
        the socket tail when ``fleet_walstream`` is on (no shared WAL
        filesystem required; the endpoint is re-resolved from membership
        on every reconnect, so a failover moves the tail by itself)."""
        if self._walstream_enabled:
            from .walstream import WALStreamFollower

            follower = WALStreamFollower(
                self._walstream_endpoint, apply_fn=self._apply_shipped,
                start_lsn=start_lsn,
                resync_fn=self._resync_from_checkpoint,
                name=self.replica_id)
        else:
            follower = WALFollower(
                self.wal_dir, apply_fn=self._apply_shipped,
                start_lsn=start_lsn,
                resync_fn=self._resync_from_checkpoint,
                name=self.replica_id)
        with self._lock:
            self.follower = follower.start()

    def _walstream_endpoint(self):
        """The current leader's stream endpoint per membership, or None
        while there is no fresh leader (the follower just re-polls)."""
        leader = self.directory.leader()
        if leader is None:
            return None
        port = leader.detail.get("walstream_port")
        if not port:
            return None
        return (leader.host, int(port))

    def _restore_from_checkpoint(self) -> int:
        """Restore the newest shared checkpoint into ``self.graph``;
        returns its WAL watermark (-1 for a fresh factory graph)."""
        ckpt = load_checkpoint(self.ckpt_dir)
        if ckpt is not None:
            with self._lock:
                self.graph = restore_graph(ckpt)
            log.info("replica %s restored checkpoint %s (version %d, "
                     "watermark %d)", self.replica_id, ckpt.path,
                     ckpt.graph_version, ckpt.wal_lsn)
            return ckpt.wal_lsn
        if self.graph_factory is None:
            raise RecoveryError(
                f"no checkpoint under {self.ckpt_dir} and no "
                "graph_factory to build a fresh follower graph from")
        with self._lock:
            self.graph = self.graph_factory()
        return -1

    def _resync_from_checkpoint(self) -> int:
        """WALFollower strand handler: rebuild follower state from the
        newest shared checkpoint; returns the next LSN to tail from."""
        watermark = self._restore_from_checkpoint()
        return watermark + 1

    def _apply_shipped(self, lsn: int, op, src, dst, ts) -> None:
        from ..stream.compactor import compact

        graph = self.graph
        if op == "add":
            try:
                graph.add_edges(src, dst, ts if graph.has_ts else None)
            except BufferError:
                compact(graph)
                graph.add_edges(src, dst, ts if graph.has_ts else None)
        elif op == "remove":
            graph.remove_edges(src, dst)

    def _await_catchup(self) -> None:
        """Block until the follower has folded in everything visible
        (staleness 0) — the join equivalent of ``finish_boot`` replay."""
        deadline = time.monotonic() + self.catchup_timeout_s
        while time.monotonic() < deadline:
            st = self.follower.status()
            if st["records"] >= 0 and st["staleness_lsn"] == 0 \
                    and st["last_error"] is None:
                # one caught-up observation after at least one poll ran
                return
            time.sleep(min(self.follower.poll_interval_s, 0.05))
        raise RecoveryError(
            f"replica {self.replica_id} not caught up within "
            f"{self.catchup_timeout_s}s: {self.follower.status()}")

    # -- election / failover ------------------------------------------
    def _applied_lsn(self) -> int:
        """Candidacy currency: the newest LSN this replica has folded in
        (followers: the tail's commit cursor; leaders: the append
        frontier)."""
        follower = self.follower
        if follower is not None:
            return int(follower.applied_lsn)
        manager = self.manager
        if manager is not None and manager.wal is not None:
            return int(manager.wal.next_lsn) - 1
        return -1

    def _make_elector(self):
        from .election import LeaderElector

        return LeaderElector(
            self.directory, self.replica_id,
            applied_lsn_fn=self._applied_lsn,
            role_fn=lambda: self.role,
            promote_fn=self._promote, demote_fn=self._step_down)

    def _install_fence(self, claim) -> None:
        """Wrap the manager's WAL in the epoch fence — every append /
        roll / truncate from here on carries the claimed epoch, and a
        deposed write raises before a byte lands."""
        from .election import EpochFence, FencedWAL

        self.fence = EpochFence(self.elector.election_dir, claim.epoch,
                                self.replica_id)
        # quiverlint: ignore[QT008] -- atomic reference publish: the
        # heartbeat thread only reads `.next_lsn`, which both the raw
        # WAL and the FencedWAL wrapper (delegating __getattr__) answer
        # identically; the checkpointer of this manager starts only
        # after this call (happens-before via Thread.start)
        self.manager.wal = FencedWAL(self.manager.wal, self.fence)
        with self._lock:
            self.epoch = int(claim.epoch)

    def _start_walstream(self) -> None:
        from .walstream import WALStreamServer

        server = WALStreamServer(
            self.wal_dir, host=self.host, name=self.replica_id,
            fence=self.fence)
        with self._lock:
            self.walstream_server = server

    def _promote(self, claim) -> None:
        """Election won (elector thread): adopt the WAL this replica has
        been tailing and become the leader.  The follower's holdback
        semantics carry straight through — its commit cursor is the
        adopt watermark, so a record it was still holding back is folded
        (or aborted) by the manager's two-pass replay, never twice."""
        from ..recovery.manager import RecoveryManager
        from ..stream import IngestLane

        log.warning("replica %s promoting to leader (epoch %d)",
                    self.replica_id, claim.epoch)
        follower = self.follower
        applied = -1
        if follower is not None:
            follower.stop()
            applied = int(follower.applied_lsn)
            with self._lock:
                self.follower = None
        with self._lock:
            self.role = "leader"
        manager = RecoveryManager(self.root,
                                  graph_factory=self.graph_factory)
        manager.adopt(self.graph, applied)
        with self._lock:
            # adopt may have fallen back to a checkpoint boot (late
            # abort across the failover) and built a fresh graph
            self.manager = manager
            self.graph = manager.graph
        self._install_fence(claim)
        self.lane = IngestLane(self.graph).start()
        manager.attach_lane(self.lane)
        manager.start_checkpointer()
        if self._walstream_enabled:
            self._start_walstream()
        self._announce()

    def _step_down(self, claim) -> None:
        """Deposed (elector thread): a higher epoch exists.  Stop every
        write-side component — the fence already refuses appends, this
        makes the stop graceful — and rejoin as a follower of the new
        leader from the exact frontier this process reached."""
        log.warning("replica %s deposed by %s (epoch %d); rejoining as "
                    "follower", self.replica_id, claim.leader_id,
                    claim.epoch)
        telemetry.counter("fleet_election_demotions_total",
                          replica=self.replica_id).inc()
        if self.walstream_server is not None:
            self.walstream_server.stop()
            with self._lock:
                self.walstream_server = None
        if self.lane is not None:
            self.lane.stop()
            self.lane = None
        manager = self.manager
        applied = -1
        if manager is not None:
            if manager.wal is not None:
                applied = int(manager.wal.next_lsn) - 1
            manager.close()
            with self._lock:
                self.manager = None
        self.fence = None
        with self._lock:
            self.role = "follower"
            self.epoch = -1
        self._start_follower(applied)
        self._announce()

    # -- serving endpoint ---------------------------------------------
    def _start_server(self) -> None:
        outer = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    resp = outer._serve_line(line)
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode())

        with self._lock:
            self._server = _ReplicaTCPServer(
                (self.host, self._requested_port), _Handler)
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"quiver-fleet-replica-{self.replica_id}")
        self._server_thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    def _serve_line(self, line: bytes) -> dict:
        t_recv = time.perf_counter()
        try:
            req = json.loads(line)
        except ValueError:
            telemetry.counter("fleet_replica_requests_total",
                              status="unparsable").inc()
            return {"status": "error", "error": "BadRequest",
                    "reason": "unparsable request line"}
        tctx = self._rehydrate(req.get("trace"))
        with self._lock:
            admitted = self._state == "serving" and not self._draining
            if admitted:
                self._inflight += 1
        if not admitted:
            self._finish_trace(tctx, time.perf_counter() - t_recv,
                               "unavailable")
            return {"status": "unavailable", "state": self.state,
                    "replica": self.replica_id}
        t0 = time.perf_counter()
        try:
            with flightrec.activate(tctx):
                if tctx is not None:
                    # the admission gap is the replica-side queue span
                    flightrec.event("replica.queue",
                                    {"seconds": t0 - t_recv})
                _CHAOS_SERVE()
                out = self._deadline_service(req, t0)
                out.setdefault("status", "ok")
        except ChaosFault as e:
            # injected serve fault: accepted the connection, cannot
            # answer — honest refusal, the router re-dispatches
            out = {"status": "unavailable", "state": self.state,
                   "error": type(e).__name__}
        except _SHED_TYPES as e:
            # a typed shed is an ANSWER — the router must not retry it
            out = {"status": "shed", "error": type(e).__name__,
                   "reason": str(e)}
        except Exception as e:
            out = {"status": "error", "error": type(e).__name__,
                   "reason": str(e)}
        finally:
            with self._lock:
                self._inflight -= 1
        e2e = time.perf_counter() - t0
        status = out.get("status", "ok")
        if status in ("ok", "shed", "error"):
            telemetry.counter("fleet_replica_requests_total",
                              status=status).inc()
            telemetry.histogram(
                "fleet_replica_request_seconds").observe(e2e)
        self._finish_trace(tctx, e2e, status)
        out["replica"] = self.replica_id
        out["latency_ms"] = round(e2e * 1e3, 3)
        if tctx is not None:
            out["trace_id"] = tctx.trace_id
        if "seq" in req:
            out["seq"] = req["seq"]
        return out

    def _deadline_service(self, req: dict, t0: float) -> dict:
        """Run the service under the request's shipped deadline budget
        (re-anchored on THIS process's perf_counter — absolute
        deadlines do not survive the hop, remaining seconds do)."""
        trace = req.get("trace")
        deadline_s = (trace.get("deadline_s")
                      if isinstance(trace, dict) else None)
        if deadline_s is None:
            return self._service(req.get("ids", ()), req.get("tenant"))
        from ..resilience.deadline import check_ambient, deadline_scope

        with deadline_scope(t0 + float(deadline_s), t0):
            check_ambient("fleet")  # dead on arrival → typed shed
            return self._service(req.get("ids", ()), req.get("tenant"))

    def _rehydrate(self, trace):
        """Adopt the router-stamped TraceContext, so replica-side stage
        events join the fleet-wide trace_id.  The id arrives already
        origin-qualified (``<origin>:<local>``), which keeps it
        disjoint from this process's own ``<pid>-<seq>`` ids.  Costs
        nothing when no trace rides the payload."""
        if not isinstance(trace, dict):
            return None
        tid = trace.get("trace_id")
        if not tid:
            return None
        tctx = flightrec.new_trace(trace_id=str(tid))
        if tctx is None:  # telemetry disabled in this process
            return None
        tenant = trace.get("tenant")
        if tenant is not None:
            tctx.tenant = str(tenant)
        # fleet-dispatched requests are always retained (the recorder
        # ring is bounded): /debug/fleet/trace/<id> must find the
        # replica-side record, not just the slow/errored tail
        tctx.flag()
        return tctx

    def _finish_trace(self, tctx, e2e: float, status: str) -> None:
        if tctx is None:
            return
        flightrec.get_recorder().finish(tctx, e2e, status=status,
                                        lane="fleet")

    def _service(self, ids, tenant) -> dict:
        if self.service_fn is not None:
            return dict(self.service_fn(ids, tenant))
        # default service: a versioned read touch — enough for routing,
        # membership, and chaos proofs; real deployments pass a
        # service_fn that drives their sampler/feature pipeline
        return {"n": len(ids),
                "version": int(self.graph.version)
                if self.graph is not None else -1}

    # -- metrics / health endpoint ------------------------------------
    def expose_metrics(self, port: int = 0):
        """Per-replica ``/metrics`` + ``/healthz`` on an ephemeral port
        (N replicas on one host never collide)."""
        # local import: telemetry.export pulls in http.server (QT004)
        from ..telemetry.export import MetricsServer

        server = MetricsServer(port=port, health_fn=self.health)
        with self._lock:
            self.metrics_server = server
        return server

    # -- membership / heartbeat ---------------------------------------
    def _info(self) -> ReplicaInfo:
        health = self.health()
        detail = {"metrics_port":
                  self.metrics_server.port if self.metrics_server
                  else 0,
                  # perf_counter↔wall pair stamped back-to-back at
                  # announce time: the federation's clock-offset
                  # estimator aligns per-replica timelines from the
                  # heartbeat stream of these (federation.py)
                  "clock_perf": time.perf_counter(),
                  "clock_wall": time.time()}
        if self.shard_group:
            detail["shard_group"] = self.shard_group
            detail["shard_index"] = self.shard_index
            detail["shard_count"] = self.shard_count
        if self.walstream_server is not None:
            detail["walstream_port"] = self.walstream_server.port
        wal_next = int(health.get("wal_next_lsn", -1))
        if wal_next < 0 and "applied_lsn" in health:
            # followers publish their fold frontier too — it is the
            # candidacy currency the election ranks promotions by
            wal_next = int(health["applied_lsn"]) + 1
        with self._lock:
            epoch = self.epoch
        return ReplicaInfo(
            replica_id=self.replica_id, state=self.state, host=self.host,
            port=self.port, role=self.role, pid=os.getpid(),
            staleness_lsn=int(health.get("staleness_lsn", 0)),
            staleness_seconds=float(health.get("staleness_seconds", 0.0)),
            wal_next_lsn=wal_next, epoch=epoch,
            detail=detail,
        )

    def _announce(self) -> None:
        try:
            self.directory.announce(self._info())
        except OSError as e:
            # a missed heartbeat ages us out of routing; log, don't die
            log.warning("replica %s announce failed: %s",
                        self.replica_id, e)

    def _start_heartbeat(self) -> None:
        self._hb_stop.clear()

        def _beat():
            while not self._hb_stop.wait(self.heartbeat_s):
                self._announce()

        self._hb_thread = threading.Thread(
            target=_beat, daemon=True,
            name=f"quiver-fleet-hb-{self.replica_id}")
        self._hb_thread.start()

    # -- drain / stop --------------------------------------------------
    def drain(self, timeout: float = 30.0) -> None:
        """Graceful exit: stop admitting, finish in-flight, deregister.

        After drain the replica can :meth:`stop` (full shutdown) — or a
        fresh process can rejoin under the same id (warm, through the
        shared caches)."""
        with self._lock:
            self._draining = True
        self._set_state("draining")
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.01)
        # stop heartbeating BEFORE deregistering: a beat landing after
        # the unlink would resurrect the record as a ghost member
        self._hb_stop.set()
        self.directory.deregister(self.replica_id)

    def stop(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        if self.elector is not None:
            self.elector.stop(timeout)
            self.elector = None
        if self.walstream_server is not None:
            self.walstream_server.stop(timeout)
            with self._lock:
                self.walstream_server = None
        self._hb_stop.set()
        threads = []
        if self._hb_thread is not None:
            threads.append(self._hb_thread)
            self._hb_thread = None
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            if self._server_thread is not None:
                threads.append(self._server_thread)
                self._server_thread = None
            with self._lock:
                self._server = None
        if threads:
            join_and_reap(threads, timeout, component="fleet.replica")
        if self.follower is not None:
            self.follower.stop(timeout)
        if self.lane is not None:
            self.lane.stop(timeout)
            self.lane = None
        if self.manager is not None:
            self.manager.close()
            with self._lock:
                self.manager = None
        if self.metrics_server is not None:
            self.metrics_server.close()
            with self._lock:
                self.metrics_server = None
        self.directory.deregister(self.replica_id)
