"""Federation-driven autoscaler: predictive spawn/drain over the fleet.

The reactive loop everyone builds first — "p99 breached, add a replica"
— pays the whole join latency *during* the burst.  This autoscaler is
predictive where the workload allows it: a :class:`DiurnalPredictor`
fits a periodic rate profile (graph-serving traffic is strongly
diurnal) plus a short linear trend, and the control loop provisions for
the rate ``fleet_autoscaler_horizon_s`` seconds *ahead*.  A warm join
(shared checkpoint + persisted feature cache, measured by
``fleet_join_seconds``) lands before the peak instead of after it.

Inputs are read-only federation state — the same merged snapshot
``FleetSLOWatchdog`` scores (fleet request rate, merged p99, max
staleness, eligible-replica floor) — so the scaler needs no new wires
into replicas.  Outputs are two callables supplied by the harness or
operator: ``spawn_fn(count)`` and ``drain_fn(replica_id)``, which go
through the normal membership join/drain choreography; the scaler
never kills processes itself and never drains the leader.

Flap control is structural, not tuned: scale-up needs predicted load
above ``up_ratio`` of current capacity, scale-down below
``down_ratio`` of the *shrunk* capacity (hysteresis band), drains move
one replica at a time, and after any action the loop holds for
``fleet_autoscaler_cooldown_s`` — at most one membership direction
change per cooldown window, by construction.

Everything here is wall-clock driven (diurnal phase only means
anything in wall time) but every entry point takes an explicit ``now``
so tests and the chaos harness replay synthetic days in milliseconds.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Tuple

from .. import telemetry
from ..telemetry.slo import _merged_histogram, _sum_counters

__all__ = ["DiurnalPredictor", "FleetAutoscaler"]

log = logging.getLogger("quiver_tpu.fleet")


class DiurnalPredictor:
    """Periodic rate profile (per-bucket EWMA) + short linear trend.

    ``observe(t, rate)`` folds a measured request rate into the profile
    bucket that ``t`` falls in; ``predict(t)`` returns the larger of
    the profile's memory of that phase and a least-squares trend over
    the recent window — the profile anticipates the *recurring* ramp,
    the trend tracks a burst the profile has never seen.  Single
    caller (the autoscaler loop), so no locking."""

    def __init__(self, period_s: float = 86400.0, buckets: int = 48,
                 alpha: float = 0.3, window: int = 64):
        if period_s <= 0 or buckets <= 0:
            raise ValueError("period_s and buckets must be positive")
        self.period_s = float(period_s)
        self.buckets = int(buckets)
        self.alpha = float(alpha)
        self._profile: List[Optional[float]] = [None] * self.buckets
        self._recent: deque = deque(maxlen=int(window))

    def _bucket(self, t: float) -> int:
        phase = (t % self.period_s) / self.period_s
        return min(int(phase * self.buckets), self.buckets - 1)

    def observe(self, t: float, rate: float) -> None:
        rate = max(float(rate), 0.0)
        b = self._bucket(t)
        prev = self._profile[b]
        self._profile[b] = (rate if prev is None
                            else self.alpha * rate
                            + (1.0 - self.alpha) * prev)
        self._recent.append((float(t), rate))

    def _trend(self, t: float) -> float:
        pts = list(self._recent)
        if len(pts) < 2:
            return pts[-1][1] if pts else 0.0
        t0 = pts[0][0]
        xs = [p[0] - t0 for p in pts]
        ys = [p[1] for p in pts]
        n = len(pts)
        mx, my = sum(xs) / n, sum(ys) / n
        var = sum((x - mx) ** 2 for x in xs)
        if var <= 0.0:
            return ys[-1]
        slope = sum((x - mx) * (y - my)
                    for x, y in zip(xs, ys)) / var
        return my + slope * ((t - t0) - mx)

    def predict(self, t: float) -> float:
        """Predicted request rate at (future) time ``t``."""
        profile = self._profile[self._bucket(t)]
        return max(self._trend(t), profile if profile is not None
                   else 0.0, 0.0)


class FleetAutoscaler:
    """The control loop: federation snapshot in, spawn/drain out.

    QT003: decision state is written by the scaler thread and read by
    ``status()`` from HTTP/test threads; both hold ``_lock``."""

    _guarded_by = {
        "_prev_total": "_lock",
        "_prev_t": "_lock",
        "_last_action_t": "_lock",
        "_last_decision": "_lock",
        "_target": "_lock",
    }

    def __init__(self,
                 snapshot_fn: Callable[[], dict],
                 spawn_fn: Callable[[int], None],
                 drain_fn: Callable[[Optional[str]], None],
                 directory=None,
                 predictor: Optional[DiurnalPredictor] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 rps_per_replica: Optional[float] = None,
                 horizon_s: Optional[float] = None,
                 up_ratio: Optional[float] = None,
                 down_ratio: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 name: str = "autoscaler"):
        from ..config import get_config

        cfg = get_config()
        self.snapshot_fn = snapshot_fn
        self.spawn_fn = spawn_fn
        self.drain_fn = drain_fn
        self.directory = directory
        self.predictor = predictor or DiurnalPredictor()
        self.name = str(name)
        self.min_replicas = int(min_replicas if min_replicas is not None
                                else cfg.fleet_autoscaler_min)
        self.max_replicas = int(max_replicas if max_replicas is not None
                                else cfg.fleet_autoscaler_max)
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else cfg.fleet_autoscaler_cooldown_s)
        self.rps_per_replica = float(
            rps_per_replica if rps_per_replica is not None
            else cfg.fleet_autoscaler_rps_per_replica)
        self.horizon_s = float(horizon_s if horizon_s is not None
                               else cfg.fleet_autoscaler_horizon_s)
        self.up_ratio = float(up_ratio if up_ratio is not None
                              else cfg.fleet_autoscaler_up_ratio)
        self.down_ratio = float(down_ratio if down_ratio is not None
                                else cfg.fleet_autoscaler_down_ratio)
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg.fleet_autoscaler_interval_s)
        self._p99_ceiling_s = cfg.slo_p99_ms / 1e3
        self._staleness_ceiling = cfg.fleet_max_staleness_lsn
        self._heartbeat_timeout_s = cfg.fleet_heartbeat_timeout_s
        self._lock = threading.Lock()
        self._prev_total: Optional[float] = None
        self._prev_t: Optional[float] = None
        self._last_action_t: Optional[float] = None
        self._last_decision: dict = {"action": "hold", "reason": "init"}
        self._target: Optional[int] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- fleet state readers -------------------------------------------
    def _replica_counts(self, snap: dict) -> Tuple[int, List]:
        """(serving replica count, drainable non-leader candidates)."""
        if self.directory is not None:
            members = [r for r in self.directory.replicas(fresh_only=True)
                       if r.state == "serving"]
            drainable = sorted(
                (r for r in members if r.role != "leader"),
                key=lambda r: r.replica_id)
            return len(members), drainable
        v = snap.get("gauges", {}).get("fleet_router_eligible_total")
        return (int(v) if v is not None else 0), []

    @staticmethod
    def _max_staleness(snap: dict) -> int:
        from ..telemetry.registry import parse_metric_key

        worst = 0
        for key, v in snap.get("gauges", {}).items():
            name, _labels = parse_metric_key(key)
            if name == "fleet_replica_staleness_lsn":
                worst = max(worst, int(v))
        return worst

    # -- the decision --------------------------------------------------
    def evaluate_once(self, now: Optional[float] = None,
                      execute: bool = True) -> dict:
        """One control-loop tick: measure, predict, decide, (execute).

        Returns the decision record:
        ``{"action": spawn|drain|hold, "count", "target", "current",
        "predicted_rps", "rate_rps", "reason"}``."""
        # diurnal phase is only meaningful in wall time, and the rate
        # delta must share the predictor's timeline
        now = time.time() if now is None else float(now)  # quiverlint: ignore[QT012] -- diurnal phase needs the wall clock; tests inject `now`
        snap = self.snapshot_fn()
        total = _sum_counters(snap, "fleet_replica_requests_total")
        with self._lock:
            prev_total, prev_t = self._prev_total, self._prev_t
            self._prev_total, self._prev_t = total, now
        rate = 0.0
        if prev_total is not None and prev_t is not None and now > prev_t:
            rate = max(total - prev_total, 0.0) / (now - prev_t)
            self.predictor.observe(now, rate)
        predicted = self.predictor.predict(now + self.horizon_s)

        current, drainable = self._replica_counts(snap)
        hist = _merged_histogram(snap, "fleet_replica_request_seconds")
        p99 = (hist.percentile(99)
               if hist is not None and hist.count else 0.0)
        staleness = self._max_staleness(snap)

        desired = max(int(math.ceil(predicted / self.rps_per_replica))
                      if self.rps_per_replica > 0 else current, 1)
        reason = f"predicted {predicted:.1f} rps"
        breach = False
        if p99 > self._p99_ceiling_s > 0:
            desired, breach = max(desired, current + 1), True
            reason = f"p99 breach ({p99 * 1e3:.0f}ms)"
        if staleness > self._staleness_ceiling > 0:
            desired, breach = max(desired, current + 1), True
            reason = f"staleness breach ({staleness} lsn)"

        capacity = current * self.rps_per_replica
        action, target = "hold", current
        if current <= 0:
            # nothing serving yet: membership choreography (first boot,
            # leader election) owns this phase, not the scaler
            reason = "no serving replicas"
        elif desired > current and (
                breach or predicted > self.up_ratio * capacity):
            action, target = "spawn", min(desired, self.max_replicas)
        elif (desired < current
              # the horizon looks past a burst's end while the burst is
              # still hot — the measured rate floors the shrink decision
              # so capacity never drains out from under live load
              and max(predicted, rate) < self.down_ratio
              * (current - 1) * self.rps_per_replica):
            action, target = "drain", max(current - 1, self.min_replicas)
            reason = (f"predicted {predicted:.1f} rps under "
                      f"{self.down_ratio:.0%} of shrunk capacity")
        target = max(min(target, self.max_replicas), self.min_replicas)
        if target == current:
            action = "hold"

        with self._lock:
            last_action_t = self._last_action_t
        if action != "hold" and last_action_t is not None \
                and (now - last_action_t) < self.cooldown_s:
            action, target = "hold", current
            reason = f"cooldown ({self.cooldown_s:.0f}s)"

        count = abs(target - current)
        decision = {"action": action, "count": count, "target": target,
                    "current": current, "predicted_rps": predicted,
                    "rate_rps": rate, "p99_s": p99,
                    "max_staleness_lsn": staleness, "reason": reason}
        telemetry.counter("fleet_autoscaler_decisions_total",
                          action=action).inc()
        telemetry.gauge("fleet_autoscaler_target_replicas").set(target)
        telemetry.gauge("fleet_autoscaler_predicted_rps").set(predicted)
        with self._lock:
            self._last_decision = dict(decision)
            self._target = target
            if action != "hold":
                self._last_action_t = now

        if execute and action == "spawn":
            self.spawn_fn(count)
        elif execute and action == "drain":
            victim = drainable[-1].replica_id if drainable else None
            self.drain_fn(victim)
        return decision

    def status(self) -> dict:
        with self._lock:
            return dict(self._last_decision)

    # -- loop ----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"quiver-fleet-{self.name}")
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            from ..resilience.shutdown import join_and_reap

            join_and_reap([self._thread], timeout,
                          component="fleet.autoscaler")
            self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception as e:  # noqa: BLE001 -- scaler must outlive a bad tick
                log.warning("autoscaler tick failed: %s", e)
                telemetry.counter("fleet_autoscaler_errors_total").inc()
