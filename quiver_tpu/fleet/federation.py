"""Fleet-wide observability plane: scrape, federate, align, reconstruct.

PR 13 scaled serving out to one leader + N replicas behind a router,
but every observability surface stayed strictly per-process: a request
that crosses router → replica loses its trace identity at the TCP
boundary, and "what is the fleet's p99 right now" has no single
answer.  This module is the read side of the fleet — four pieces:

  * **metrics federation** — a membership-driven scraper polls each
    serving member's ``/metrics``, parses the Prometheus text back
    into per-replica series (:func:`parse_prometheus_text` — the
    *parsing* twin of ``export._escape_label_value``: hostile label
    values round-trip, malformed exposition ticks
    ``fleet_federation_parse_errors_total`` and never kills the
    sweep), and :func:`federate` merges them: counters summed,
    histograms merged bucket-wise (the fixed default bounds make the
    merge exact), gauges reported min/max/avg, every per-replica
    series re-exported under a ``replica`` label.  Served at
    ``GET /metrics/fleet`` + ``/debug/fleet/summary`` on the router's
    MetricsServer.
  * **fleet SLOs** — :class:`FleetSLOWatchdog` runs the PR 4 watchdog
    over the *federated* snapshot: fleet p99 / error ratio over the
    replicas' ``fleet_replica_request*`` series, plus max replica
    staleness and an eligible-replica floor.
  * **merged timelines** — each replica's ``/debug/timeline`` Chrome
    trace is pulled and re-based into one wall-clock timebase using
    per-replica perf_counter↔wall offsets estimated from the
    timestamp pairs replicas embed in their membership heartbeats
    (:func:`estimate_offsets` — median of ``wall - perf``, robust to
    a scheduling stall corrupting one pair).  One process track per
    replica plus the router; exported via ``timeline.export_fleet``
    (a provider hook, so telemetry never imports fleet) and
    ``bench.py --fleet-trace``.
  * **request reconstruction** — :meth:`FleetFederation.reconstruct`
    joins the router's hop record for a trace_id with the owning
    replica's flight record(s) (``GET /debug/fleet/trace/<id>``), so
    one id tells the whole cross-process story, redispatches included.

Everything here is OFF the request path: the router pays one cached
config check when ``config.fleet_federation`` is off (no thread, no
new metric keys), and the scraper is a read-only consumer of endpoints
the fleet already serves.

QT003 lock discipline: scrape state is written by the scraper thread
and read from HTTP handler threads; all access holds ``_lock``.
QT004: urllib (the HTTP *client*) is imported at call time like the
router's health poller; http.server never loads here.
"""

from __future__ import annotations

import json
import logging
import math
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple
from weakref import ref as weakref

from .. import telemetry
from ..telemetry.registry import metric_key, parse_metric_key
from ..telemetry.slo import SLOWatchdog, _merged_histogram, _sum_counters
from .membership import MembershipDirectory

__all__ = [
    "parse_prometheus_text", "federate", "render_fleet_text",
    "estimate_offsets", "FleetFederation", "FleetSLOWatchdog",
    "get_federation", "federation_status",
]

log = logging.getLogger("quiver_tpu.fleet")

# series are keyed (name, ((label, value), ...)) — label values keep
# their raw bytes (commas, quotes, newlines) instead of being folded
# into the registry's flat `name{k=v}` strings, which forbid them
_SeriesKey = Tuple[str, Tuple[Tuple[str, str], ...]]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_UNESCAPE = {"\\": "\\", '"': '"', "n": "\n"}
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_MAX_CLOCK_PAIRS = 32  # heartbeat timestamp pairs retained per replica


# -- Prometheus text parsing -------------------------------------------
def _parse_labels(body: str) -> Optional[Dict[str, str]]:
    """The ``k="v",...`` interior of a label set; None when malformed.
    Inverse of ``export._escape_label_value``: ``\\\\``, ``\\"`` and
    ``\\n`` unescape, anything else after a backslash is corrupt."""
    labels: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            return None
        key = body[i:eq].strip()
        if not _NAME_RE.match(key) or eq + 1 >= n or body[eq + 1] != '"':
            return None
        j = eq + 2
        out: List[str] = []
        closed = False
        while j < n:
            c = body[j]
            if c == "\\":
                rep = _UNESCAPE.get(body[j + 1]) if j + 1 < n else None
                if rep is None:
                    return None
                out.append(rep)
                j += 2
                continue
            if c == '"':
                closed = True
                break
            out.append(c)
            j += 1
        if not closed:
            return None
        labels[key] = "".join(out)
        i = j + 1
        if i < n:
            if body[i] != ",":
                return None
            i += 1
    return labels


def _parse_sample(line: str) -> Optional[Tuple[str, Dict[str, str], float]]:
    """One sample line → ``(name, labels, value)``; None when corrupt."""
    if "{" in line:
        brace = line.index("{")
        name = line[:brace].strip()
        # find the matching close brace quote-aware: label values may
        # contain '}' legitimately
        j, in_q = brace + 1, False
        while j < len(line):
            c = line[j]
            if in_q:
                if c == "\\":
                    j += 2
                    continue
                if c == '"':
                    in_q = False
            elif c == '"':
                in_q = True
            elif c == "}":
                break
            j += 1
        if j >= len(line):
            return None
        labels = _parse_labels(line[brace + 1:j])
        if labels is None:
            return None
        rest = line[j + 1:].split()
    else:
        parts = line.split()
        if len(parts) < 2:
            return None
        name, rest, labels = parts[0], parts[1:], {}
    if not _NAME_RE.match(name) or not rest:
        return None
    try:
        value = float(rest[0])  # optional trailing timestamp is ignored
    except ValueError:
        return None
    return name, labels, value


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _hist_family(name: str, types: Dict[str, str]) \
        -> Tuple[Optional[str], Optional[str]]:
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix):
            base = name[:-len(suffix)]
            if types.get(base) == "histogram":
                return base, suffix[1:]
    return None, None


def _assemble_histogram(parts: dict) -> Optional[dict]:
    """Cumulative ``_bucket{le=...}`` samples → a registry-shaped
    ``{"bounds", "counts", "sum"}`` dict; None when the exposition is
    internally inconsistent (non-monotone cumulative counts, missing
    ``+Inf`` bucket or ``_sum``)."""
    finite = sorted((b, v) for b, v in parts["buckets"]
                    if not math.isinf(b))
    inf = [v for b, v in parts["buckets"] if math.isinf(b)]
    if len(inf) != 1 or parts["sum"] is None:
        return None
    total = inf[0]
    bounds, counts, prev = [], [], 0.0
    for b, cum in finite:
        if cum < prev - 1e-9:
            return None
        bounds.append(b)
        counts.append(int(round(cum - prev)))
        prev = cum
    overflow = total - prev
    if overflow < -1e-9:
        return None
    counts.append(int(round(max(overflow, 0.0))))
    if parts["count"] is not None and abs(parts["count"] - total) > 1e-6:
        return None
    return {"bounds": bounds, "counts": counts,
            "sum": float(parts["sum"]), "min": None, "max": None}


def parse_prometheus_text(text: str) -> Tuple[dict, int]:
    """Prometheus text exposition → ``({"counters", "gauges",
    "histograms"}, n_errors)`` keyed by ``(name, label_tuple)``.

    Every malformed line / inconsistent histogram counts one error and
    is skipped — a hostile or truncated scrape degrades coverage, it
    never raises out of the sweep.  Untyped samples classify by the
    QT006 unit suffix (``_total`` → counter, else gauge).
    """
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    errors = 0
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        parsed = _parse_sample(line)
        if parsed is None:
            errors += 1
            continue
        samples.append(parsed)

    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    hist_parts: Dict[_SeriesKey, dict] = {}
    for name, labels, value in samples:
        family, part = _hist_family(name, types)
        if family is not None:
            base = {k: v for k, v in labels.items() if k != "le"}
            d = hist_parts.setdefault(
                (family, _label_key(base)),
                {"buckets": [], "sum": None, "count": None})
            if part == "bucket":
                le = labels.get("le")
                try:
                    d["buckets"].append((float(le), value))
                except (TypeError, ValueError):
                    errors += 1
            elif part == "sum":
                d["sum"] = value
            else:
                d["count"] = value
            continue
        kind = types.get(name)
        if kind is None:
            kind = "counter" if name.endswith("_total") else "gauge"
        key = (name, _label_key(labels))
        if kind == "counter":
            out["counters"][key] = value
        else:
            out["gauges"][key] = value
    for key, parts in hist_parts.items():
        h = _assemble_histogram(parts)
        if h is None:
            errors += 1
            continue
        out["histograms"][key] = h
    return out, errors


# -- federation (pure) -------------------------------------------------
def _tag_replica(key: _SeriesKey, rid: str) -> _SeriesKey:
    name, labels = key
    merged = dict(labels)
    # a series that is already replica-scoped at the source (shipping's
    # staleness gauges) keeps its own attribution
    merged.setdefault("replica", rid)
    return name, _label_key(merged)


def federate(scrapes: Dict[str, dict]) -> dict:
    """Merge per-replica parsed scrapes into the fleet view: counters
    summed, histograms merged bucket-wise (bounds must match — a
    mismatch drops that family from the aggregate and counts a merge
    error), gauges min/max/avg; every source series re-keyed with a
    ``replica`` label under ``per_replica``."""
    view: dict = {
        "replicas": sorted(scrapes),
        "counters": {}, "gauges": {}, "histograms": {},
        "per_replica": {"counters": {}, "gauges": {}, "histograms": {}},
        "merge_errors": 0,
    }
    gauge_vals: Dict[_SeriesKey, List[float]] = {}
    for rid in sorted(scrapes):
        snap = scrapes[rid]
        for key, v in snap.get("counters", {}).items():
            view["per_replica"]["counters"][_tag_replica(key, rid)] = v
            view["counters"][key] = view["counters"].get(key, 0.0) + v
        for key, v in snap.get("gauges", {}).items():
            view["per_replica"]["gauges"][_tag_replica(key, rid)] = v
            gauge_vals.setdefault(key, []).append(v)
        for key, h in snap.get("histograms", {}).items():
            view["per_replica"]["histograms"][_tag_replica(key, rid)] = h
            agg = view["histograms"].get(key)
            if agg is None:
                view["histograms"][key] = {
                    "bounds": list(h["bounds"]), "counts": list(h["counts"]),
                    "sum": h["sum"], "min": None, "max": None}
            elif agg["bounds"] == list(h["bounds"]):
                agg["counts"] = [a + b for a, b
                                 in zip(agg["counts"], h["counts"])]
                agg["sum"] += h["sum"]
            else:
                view["histograms"].pop(key, None)
                view["merge_errors"] += 1
    view["gauges"] = {
        key: {"min": min(vs), "max": max(vs), "avg": sum(vs) / len(vs)}
        for key, vs in gauge_vals.items()}
    return view


def render_fleet_text(view: dict) -> str:
    """The ``/metrics/fleet`` exposition: aggregate series first, then
    the per-replica series under the same ``# TYPE``.  Gauges aggregate
    as three ``agg="min|max|avg"`` series (summing gauges is a lie)."""
    from ..telemetry.export import _fmt_labels, _fmt_num

    lines: List[str] = []
    typed = set()

    def _type(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    def _hist_lines(name: str, labels: dict, d: dict) -> None:
        cum = 0
        for bound, c in zip(d["bounds"], d["counts"]):
            cum += c
            lines.append(f"{name}_bucket"
                         f"{_fmt_labels(labels, {'le': _fmt_num(bound)})} "
                         f"{cum}")
        cum += d["counts"][-1]
        lines.append(
            f"{name}_bucket{_fmt_labels(labels, {'le': '+Inf'})} {cum}")
        lines.append(f"{name}_sum{_fmt_labels(labels)} "
                     f"{_fmt_num(d['sum'])}")
        lines.append(f"{name}_count{_fmt_labels(labels)} {cum}")

    for (name, labels), v in sorted(view["counters"].items()):
        _type(name, "counter")
        lines.append(f"{name}{_fmt_labels(dict(labels))} {_fmt_num(v)}")
    for (name, labels), v in sorted(view["per_replica"]["counters"].items()):
        _type(name, "counter")
        lines.append(f"{name}{_fmt_labels(dict(labels))} {_fmt_num(v)}")
    for (name, labels), agg in sorted(view["gauges"].items()):
        _type(name, "gauge")
        for k in ("min", "max", "avg"):
            lines.append(f"{name}{_fmt_labels(dict(labels), {'agg': k})} "
                         f"{_fmt_num(agg[k])}")
    for (name, labels), v in sorted(view["per_replica"]["gauges"].items()):
        _type(name, "gauge")
        lines.append(f"{name}{_fmt_labels(dict(labels))} {_fmt_num(v)}")
    for (name, labels), d in sorted(view["histograms"].items()):
        _type(name, "histogram")
        _hist_lines(name, dict(labels), d)
    for (name, labels), d in sorted(
            view["per_replica"]["histograms"].items()):
        _type(name, "histogram")
        _hist_lines(name, dict(labels), d)
    return "\n".join(lines) + "\n"


# -- clock alignment ---------------------------------------------------
def estimate_offsets(samples: Dict[str, Sequence[Tuple[float, float]]]) \
        -> Dict[str, float]:
    """Per-process perf_counter→wall offset from ``(perf, wall)``
    timestamp pairs: the median of ``wall - perf`` per process.  Both
    stamps are taken back-to-back at heartbeat time, so each pair's
    difference is the process's perf epoch plus a sub-millisecond
    sampling error; the median rejects pairs a scheduling stall tore
    apart.  Adding the offset to a perf_counter timestamp lands it on
    that process's wall clock — the shared timebase the merged
    timeline uses."""
    out: Dict[str, float] = {}
    for rid, pairs in samples.items():
        deltas = sorted(w - p for p, w in pairs)
        if not deltas:
            continue
        m = len(deltas) // 2
        out[rid] = (deltas[m] if len(deltas) % 2
                    else (deltas[m - 1] + deltas[m]) / 2.0)
    return out


def _flat_key(name: str, labels: Tuple[Tuple[str, str], ...]) \
        -> Optional[str]:
    # the registry's flat keys forbid `,={}"\n` in label values; a
    # hostile series stays in the tuple-keyed view and is simply not
    # visible to the flat-snapshot consumers (the SLO watchdog)
    try:
        return metric_key(name, dict(labels))
    except (TypeError, ValueError):
        return None


# -- fleet SLOs --------------------------------------------------------
class _FederatedRegistry:
    """Adapter handing the base watchdog a federated ``snapshot()``."""

    __slots__ = ("_fed_ref",)

    def __init__(self, fed: "FleetFederation"):
        self._fed_ref = weakref(fed)

    def snapshot(self) -> dict:
        fed = self._fed_ref()
        return fed.fleet_snapshot() if fed is not None else {}


class FleetSLOWatchdog(SLOWatchdog):
    """The PR 4 watchdog over the federated snapshot: fleet p99 and
    error ratio come from the replicas' ``fleet_replica_request*``
    series, plus two fleet-only objectives — max replica staleness
    (``config.fleet_max_staleness_lsn``) and an eligible-replica floor
    (``config.fleet_min_eligible``)."""

    def __init__(self, federation: "FleetFederation",
                 interval_s: Optional[float] = None):
        from ..config import get_config

        cfg = get_config()
        super().__init__(registry=_FederatedRegistry(federation),
                         interval_s=(interval_s if interval_s is not None
                                     else federation.interval_s))
        self.max_staleness_lsn = float(cfg.fleet_max_staleness_lsn)
        self.min_eligible = float(cfg.fleet_min_eligible)

    def _score(self, window: dict) -> List[dict]:
        # gauges pass through snapshot_delta untouched, so the window
        # carries current staleness / eligibility readings alongside
        # the windowed counter and histogram deltas
        return [self._eval_fleet_p99(window),
                self._eval_fleet_errors(window),
                self._eval_staleness(window),
                self._eval_eligible(window)]

    def _eval_fleet_p99(self, window: dict) -> dict:
        h = _merged_histogram(window, "fleet_replica_request_seconds")
        n = h.count if h is not None else 0
        p99_ms = h.percentile(99) * 1e3 if n else 0.0
        return {
            "objective": "fleet_p99_latency",
            "target": self.p99_ms, "unit": "ms",
            "value": round(p99_ms, 3), "samples": int(n),
            "burn": round(p99_ms / self.p99_ms, 4) if self.p99_ms else 0.0,
            "breaching": bool(n and p99_ms > self.p99_ms),
        }

    def _eval_fleet_errors(self, window: dict) -> dict:
        err = _sum_counters(window, "fleet_replica_requests_total",
                            {"status": "error"})
        total = _sum_counters(window, "fleet_replica_requests_total")
        ratio = err / total if total else 0.0
        return {
            "objective": "fleet_error_ratio",
            "target": self.error_ratio, "unit": "ratio",
            "value": round(ratio, 6), "samples": int(total),
            "burn": (round(ratio / self.error_ratio, 4)
                     if self.error_ratio else 0.0),
            "breaching": bool(total and ratio > self.error_ratio),
        }

    def _eval_staleness(self, window: dict) -> dict:
        worst, worst_rid, n = 0.0, None, 0
        for key, v in window.get("gauges", {}).items():
            name, labels = parse_metric_key(key)
            if name != "fleet_replica_staleness_lsn":
                continue
            n += 1
            if v > worst:
                worst, worst_rid = v, labels.get("replica")
        target = self.max_staleness_lsn
        out = {
            "objective": "fleet_staleness",
            "target": target, "unit": "lsn",
            "value": worst, "samples": n,
            "burn": round(worst / target, 4) if target else 0.0,
            "breaching": bool(n and target and worst > target),
        }
        if worst_rid is not None:
            out["replica"] = worst_rid
        return out

    def _eval_eligible(self, window: dict) -> dict:
        v = window.get("gauges", {}).get("fleet_router_eligible_total")
        known = v is not None
        value = float(v) if known else 0.0
        floor = self.min_eligible
        return {
            "objective": "fleet_eligible",
            "target": floor, "unit": "replicas",
            "value": value, "samples": int(known),
            # floor objective: burn > 1 means fewer routable replicas
            # than provisioned
            "burn": (round(floor / value, 4) if value
                     else (float(known and floor > 0))),
            "breaching": bool(known and value < floor),
        }


# -- the federation ----------------------------------------------------
class FleetFederation:
    """Membership-driven scraper + merged views over the fleet.

    Construct it next to the router (``FleetRouter`` with federation on
    does this itself), then either :meth:`start` the background sweep
    or call :meth:`scrape_once` deterministically (tests, bench).  All
    read views — ``/metrics/fleet``, ``/debug/fleet/summary``,
    ``/debug/fleet/trace/<id>``, ``timeline.export_fleet`` — serve from
    the last completed sweep.
    """

    _guarded_by = {"_scrapes": "_lock", "_meta": "_lock",
                   "_pairs": "_lock", "_view": "_lock"}

    def __init__(self, directory: MembershipDirectory, router=None,
                 interval_s: Optional[float] = None,
                 timeout_s: Optional[float] = None,
                 watchdog: bool = True):
        from ..config import get_config
        from ..telemetry import timeline

        cfg = get_config()
        self.directory = directory
        self._router_ref = weakref(router) if router is not None else None
        self.interval_s = float(interval_s if interval_s is not None
                                else cfg.fleet_scrape_interval_s)
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else cfg.fleet_request_timeout_s)
        self._lock = threading.Lock()
        self._scrapes: Dict[str, dict] = {}
        self._meta: Dict[str, dict] = {}
        self._pairs: Dict[str, List[Tuple[float, float]]] = {}
        self._view: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.watchdog = FleetSLOWatchdog(self) if watchdog else None
        _set_active(self)

        def _provider(ref=weakref(self)):
            fed = ref()
            return fed.fleet_chrome_trace() if fed is not None else None

        timeline.set_fleet_trace_provider(_provider)

    # -- scraping ------------------------------------------------------
    def targets(self) -> List[Tuple[str, str, int]]:
        """Scrapeable members: fresh + serving + a published metrics
        port.  Membership drives the sweep — joins and leaves change
        the target set on the next tick, no registration step."""
        out = []
        for r in self.directory.replicas(fresh_only=True):
            mport = int(r.detail.get("metrics_port", 0) or 0)
            if r.state == "serving" and mport > 0:
                out.append((r.replica_id, r.host, mport))
        return out

    def _harvest_clock_pairs(self) -> None:
        for r in self.directory.replicas(fresh_only=True):
            perf, wall = (r.detail.get("clock_perf"),
                          r.detail.get("clock_wall"))
            if perf is None or wall is None:
                continue
            pair = (float(perf), float(wall))
            with self._lock:
                pairs = self._pairs.setdefault(r.replica_id, [])
                if pairs and pairs[-1] == pair:
                    continue  # heartbeat not re-stamped since last sweep
                pairs.append(pair)
                if len(pairs) > _MAX_CLOCK_PAIRS:
                    del pairs[0]

    def _fetch(self, rid: str, url: str, count_errors: bool = True) \
            -> Optional[bytes]:
        # QT004 keeps http.server out of library modules; the CLIENT
        # side (urllib) is fine — same stance as the router's poller
        import urllib.request

        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
                return r.read()
        except (OSError, ValueError) as e:
            if count_errors:
                telemetry.counter("fleet_federation_scrape_errors_total",
                                  replica=rid).inc()
            log.debug("federation fetch %s failed: %s", url, e)
            return None

    def _fetch_json(self, rid: str, host: str, mport: int, path: str,
                    count_errors: bool = True) -> Optional[dict]:
        body = self._fetch(rid, f"http://{host}:{mport}{path}",
                           count_errors=count_errors)
        if body is None:
            return None
        try:
            return json.loads(body)
        except ValueError:
            if count_errors:
                telemetry.counter("fleet_federation_parse_errors_total").inc()
            return None

    def scrape_once(self) -> int:
        """One federation sweep: harvest heartbeat clock pairs, pull
        every target's ``/metrics``, re-parse, re-federate.  Returns
        the number of members scraped successfully; every failure mode
        ticks its counter and leaves the previous view standing."""
        self._harvest_clock_pairs()
        ok = 0
        for rid, host, mport in self.targets():
            body = self._fetch(rid, f"http://{host}:{mport}/metrics")
            if body is None:
                with self._lock:
                    self._meta[rid] = {"ok": False, "error": "unreachable"}
                continue
            parsed, errors = parse_prometheus_text(
                body.decode("utf-8", "replace"))
            if errors:
                telemetry.counter(
                    "fleet_federation_parse_errors_total").inc(errors)
            telemetry.counter("fleet_federation_scrapes_total",
                              replica=rid).inc()
            ok += 1
            with self._lock:
                self._scrapes[rid] = parsed
                self._meta[rid] = {
                    "ok": True, "parse_errors": errors,
                    "series": (len(parsed["counters"])
                               + len(parsed["gauges"])
                               + len(parsed["histograms"])),
                }
        with self._lock:
            scrapes = dict(self._scrapes)
        view = federate(scrapes)
        if view["merge_errors"]:
            telemetry.counter("fleet_federation_merge_errors_total").inc(
                view["merge_errors"])
        with self._lock:
            self._view = view
        return ok

    # -- merged views --------------------------------------------------
    def fleet_view(self) -> dict:
        with self._lock:
            view = self._view
        if view is None:
            self.scrape_once()
            with self._lock:
                view = self._view
        return view

    def fleet_snapshot(self) -> dict:
        """Registry-shaped flat snapshot of the federation (aggregate
        counters/histograms + per-replica gauges), with the router
        process's own gauges folded in — the :class:`FleetSLOWatchdog`
        input.  Series whose label values the flat keys cannot encode
        are skipped (they remain visible in :meth:`fleet_view`)."""
        view = self.fleet_view()
        snap: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for (name, labels), v in view["counters"].items():
            key = _flat_key(name, labels)
            if key is not None:
                snap["counters"][key] = v
        for (name, labels), h in view["histograms"].items():
            key = _flat_key(name, labels)
            if key is not None:
                snap["histograms"][key] = dict(h)
        for (name, labels), v in view["per_replica"]["gauges"].items():
            key = _flat_key(name, labels)
            if key is not None:
                snap["gauges"][key] = v
        # the eligible-replica gauge lives in the router process, not
        # on any replica: fold local gauges in (replica series win)
        for key, v in telemetry.snapshot().get("gauges", {}).items():
            snap["gauges"].setdefault(key, v)
        return snap

    def prometheus_text(self) -> str:
        """The ``GET /metrics/fleet`` body."""
        return render_fleet_text(self.fleet_view())

    def offsets(self) -> Dict[str, float]:
        """Per-replica perf_counter→wall offsets from harvested
        heartbeat clock pairs."""
        with self._lock:
            pairs = {rid: list(ps) for rid, ps in self._pairs.items()}
        return estimate_offsets(pairs)

    def summary(self) -> dict:
        """The ``GET /debug/fleet/summary`` document."""
        view = self.fleet_view()
        with self._lock:
            meta = {rid: dict(m) for rid, m in self._meta.items()}
            running = (self._thread is not None
                       and self._thread.is_alive())
        router = self._router_ref() if self._router_ref is not None \
            else None
        out = {
            "active": True,
            "interval_s": self.interval_s,
            "running": running,
            "replicas": meta,
            "series": {
                "counters": len(view["counters"]),
                "gauges": len(view["gauges"]),
                "histograms": len(view["histograms"]),
            },
            "merge_errors": view["merge_errors"],
            "offsets_s": {rid: round(off, 6)
                          for rid, off in sorted(self.offsets().items())},
        }
        if router is not None:
            out["router"] = {"origin": router.origin,
                             "hop_records": router.hop_count()}
        if self.watchdog is not None:
            out["slo"] = self.watchdog.status()
        return out

    # -- merged timeline -----------------------------------------------
    def fleet_chrome_trace(self) -> dict:
        """One Perfetto-loadable Chrome trace for the whole fleet: the
        router's own timeline plus every reachable replica's
        ``/debug/timeline``, each re-based from its process-local
        perf_counter epoch onto the wall clock via the heartbeat
        offsets, one process track each."""
        from ..telemetry import timeline

        offsets = self.offsets()
        local_pair = (time.perf_counter(), time.time())
        procs: List[Tuple[str, dict, float]] = [
            ("router", timeline.chrome_trace(),
             local_pair[1] - local_pair[0])]
        skipped: List[str] = []
        for rid, host, mport in self.targets():
            off = offsets.get(rid)
            doc = self._fetch_json(rid, host, mport, "/debug/timeline")
            if off is None or doc is None:
                skipped.append(rid)
                continue
            procs.append((rid, doc, off))
        merged: List[dict] = []
        for idx, (pname, doc, off) in enumerate(procs):
            track = "router" if pname == "router" else f"replica {pname}"
            merged.append({"name": "process_name", "ph": "M", "pid": idx,
                           "tid": 0, "args": {"name": track}})
            for e in doc.get("traceEvents", ()):
                if not isinstance(e, dict):
                    continue
                ev = dict(e)
                ev["pid"] = idx
                if ev.get("ph") == "M":
                    if ev.get("name") == "process_name":
                        continue  # replaced by the per-replica track
                    merged.append(ev)
                    continue
                try:
                    ev["ts"] = float(ev["ts"]) + off * 1e6
                except (KeyError, TypeError, ValueError):
                    continue
                merged.append(ev)
        out = {
            "traceEvents": merged,
            "displayTimeUnit": "ms",
            "otherData": {
                "processes": [p for p, _, _ in procs],
                "offsets_s": {p: round(o, 6) for p, _, o in procs},
            },
        }
        if skipped:
            out["otherData"]["skipped"] = skipped
        return out

    # -- request reconstruction ----------------------------------------
    def reconstruct(self, trace_id: str) -> dict:
        """The ``GET /debug/fleet/trace/<id>`` document: the router's
        hop record joined with the flight record of every replica the
        request was dispatched to.  A replica that died (that is how
        redispatches happen) reports unreachable rather than vanishing
        from the story."""
        from urllib.parse import quote

        router = self._router_ref() if self._router_ref is not None \
            else None
        hop = router.hop_record(trace_id) if router is not None else None
        out: dict = {"trace_id": trace_id, "router": hop, "replicas": {}}
        targets = {rid: (host, mport)
                   for rid, host, mport in self.targets()}
        rids = ([a["replica"] for a in hop.get("attempts", ())]
                if hop else sorted(targets))
        for rid in dict.fromkeys(rids):  # de-dup, order preserved
            loc = targets.get(rid)
            if loc is None:
                out["replicas"][rid] = {"error": "unreachable",
                                        "reason": "not in the fleet"}
                continue
            doc = self._fetch_json(rid, loc[0], loc[1],
                                   "/debug/requests/"
                                   + quote(trace_id, safe=""),
                                   count_errors=False)
            out["replicas"][rid] = (doc if doc is not None
                                    else {"error": "no record"})
        out["found"] = bool(hop) or any(
            "error" not in d for d in out["replicas"].values())
        return out

    # -- lifecycle -----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
                if self.watchdog is not None:
                    self.watchdog.evaluate_once()
            except Exception as e:
                # the sweep must outlive flaky replicas; the previous
                # view stays standing and the next tick retries
                log.warning("federation sweep failed: %s", e)

    def start(self) -> "FleetFederation":
        """Start (idempotently) the background sweep thread."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="quiver-fleet-federation")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        self._stop.set()
        t = self._thread
        if t is not None:
            join_and_reap([t], max(self.interval_s * 2, timeout),
                          component="fleet.federation")
            self._thread = None
        if self.watchdog is not None:
            self.watchdog.stop()
        _clear_active(self)


# -- /metrics/fleet plumbing (weakref, same pattern as fleet.router) ----
_ACTIVE_LOCK = threading.Lock()
_ACTIVE = None


def _set_active(fed: FleetFederation) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = weakref(fed)


def _clear_active(fed: FleetFederation) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None and _ACTIVE() is fed:
            _ACTIVE = None


def get_federation() -> Optional[FleetFederation]:
    """The most recently constructed federation in this process (what
    the MetricsServer's fleet routes serve), or None."""
    with _ACTIVE_LOCK:
        return _ACTIVE() if _ACTIVE is not None else None


def federation_status() -> dict:
    """The ``/debug/fleet/summary`` document; ``{"active": False}``
    when no federation is live."""
    fed = get_federation()
    if fed is None:
        return {"active": False}
    return fed.summary()
