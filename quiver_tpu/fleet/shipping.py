"""WAL shipping: read-only follower tails over the leader's log.

The fleet keeps writes single-writer — one ingest leader appends to the
WAL (``recovery/wal.py``) — and read replicas *tail* the log, folding
acked records into their own graph.  Two transports share one
catch-up/holdback core (:class:`TailFollower`):

  * :class:`WALFollower` (here) reads the leader's segment files
    directly — the shared-filesystem deployment.  It never opens the
    log for writing (a :class:`~quiver_tpu.recovery.wal.WriteAheadLog`
    constructor would truncate the leader's live torn tail), it only
    reads bytes and walks ``blockio.scan_records`` frames, so any
    number of followers can ship from one leader directory.
  * :class:`~quiver_tpu.fleet.walstream.WALStreamFollower` receives
    the same frames over a TCP JSON-lines stream from the leader's
    :class:`~quiver_tpu.fleet.walstream.WALStreamServer` — fleets with
    no shared filesystem.  Same holdback, same staleness contract.

Three live-tailing realities shape the loop:

  * **torn tail = write in progress.**  Replay-at-boot treats a torn
    frame as crash debris; a live follower treats it as the leader's
    append racing the read — it keeps its offset, ticks
    ``fleet_ship_torn_waits_total``, and re-polls.  Waiting is correct
    in both worlds: if the leader actually crashed, its restart
    truncates the debris and the next poll sees a clean (shorter) file.
  * **abort holdback.**  An abort record compensates a durable-but-
    nacked op and lands at the very next LSN (the ingest worker is the
    only appender).  The follower therefore holds back the newest
    visible record until a successor slot appears — proving no abort is
    coming — or a grace window passes (the leader appends the abort
    microseconds after the failed apply, so a grace-expired commit that
    later meets its abort means the leader was suspended mid-pair; that
    is detected as a *late abort* and answered with a checkpoint
    resync, never silently diverging state).
  * **truncation gaps.**  ``truncate_through`` after a leader
    checkpoint may delete segments a lagging follower still needed.
    The follower detects the gap (its next LSN precedes every remaining
    segment) and resyncs from the newest shared checkpoint
    (``fleet_ship_resyncs_total``) instead of stranding.

Staleness is measured, not assumed: ``fleet_replica_staleness_lsn`` is
the distance between the last LSN visible (on disk, or past the stream
frontier) and the last LSN folded into the follower's graph;
``fleet_replica_staleness_seconds`` is how long the follower has been
behind (0 while caught up).  The staleness contract the router and the
chaos harness rely on is in docs/FLEET.md.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Callable, List, Optional, Tuple

from .. import telemetry
from ..recovery import blockio
from ..recovery.errors import WALError
from ..recovery.wal import decode_abort, decode_edge_op
from ..resilience import chaos

__all__ = ["TailFollower", "WALFollower", "list_segments", "scan_frames"]

log = logging.getLogger("quiver_tpu.fleet")

_CHAOS_SHIP = chaos.point("fleet.ship")

# same on-disk naming contract as recovery/wal.py (`wal-<start_lsn>.seg`,
# 20-digit zero-padded) — the follower reads the layout, it never owns it
_SEG_RE = re.compile(r"^wal-(\d{20})\.seg$")


def list_segments(wal_dir: str) -> List[Tuple[int, str]]:
    """``(start_lsn, path)`` per segment under ``wal_dir``, sorted.
    Shared by the file follower and the walstream server — both read
    the leader's layout, neither owns it."""
    out = []
    try:
        names = os.listdir(wal_dir)
    except OSError:
        return []
    for n in names:
        m = _SEG_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(wal_dir, n)))
    out.sort()
    return out


def scan_frames(data: bytes):
    """``(kind, payload, start_offset, end_offset)`` per complete frame,
    plus a trailing ``torn`` flag — end offsets come from the *next*
    frame's start, which is the only way to bound a corrupt frame.
    ``data[start:end]`` is the raw frame (header + payload), which is
    what the walstream server ships so receivers re-verify the disk
    bytes, not a re-framed copy."""
    raw = list(blockio.scan_records(data))
    torn = bool(raw) and raw[-1][0] == "torn"
    usable = raw[:-1] if torn else raw
    frames = []
    for i, (kind, off, payload) in enumerate(usable):
        if i + 1 < len(usable):
            end = usable[i + 1][1]
        elif torn:
            end = raw[-1][1]
        else:
            end = len(data)
        frames.append((kind, payload, off, end))
    return frames, torn


class TailFollower:
    """The transport-independent catch-up/holdback core.

    Subclasses implement :meth:`poll_once` (one tailing pass over their
    transport) and feed every visible slot — in LSN order — through
    :meth:`_observe`; the core resolves abort holdback, commits decoded
    edge ops through ``apply_fn(lsn, op, src, dst, ts)``, counts, and
    publishes the staleness gauges.  ``resync_fn`` is called when the
    follower is stranded (truncation gap or late abort); it must
    re-restore follower state from the newest shared checkpoint and
    return the next LSN to resume from.
    """

    _guarded_by = {
        "_next_lsn": "_lock", "_records": "_lock", "_resyncs": "_lock",
        "_staleness_lsn": "_lock", "_staleness_seconds": "_lock",
        "_caught_up_at": "_lock", "_last_error": "_lock",
    }

    def __init__(self,
                 apply_fn: Callable[[int, str, object, object, object],
                                    None],
                 start_lsn: int = -1,
                 resync_fn: Optional[Callable[[], int]] = None,
                 poll_interval_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 name: str = "follower",
                 thread_prefix: str = "quiver-fleet-ship"):
        from ..config import get_config

        cfg = get_config()
        self.apply_fn = apply_fn
        self.resync_fn = resync_fn
        self.name = str(name)
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else cfg.fleet_ship_poll_ms / 1e3)
        self.grace_s = float(grace_s if grace_s is not None
                             else cfg.fleet_ship_grace_ms / 1e3)
        self._lock = threading.Lock()
        self._next_lsn = int(start_lsn) + 1   # next LSN to commit
        self._records = 0
        self._resyncs = 0
        self._staleness_lsn = 0
        self._staleness_seconds = 0.0
        self._caught_up_at = time.monotonic()
        self._last_error: Optional[str] = None
        # follower-thread-private holdback slot (single thread root —
        # the poll loop; unit tests drive poll_once() from one thread
        # too): (lsn, payload, observed_at)
        self._held: Optional[Tuple[int, bytes, float]] = None
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"{thread_prefix}-{self.name}")

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "TailFollower":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        self._stop_evt.set()
        if self._thread.is_alive():
            join_and_reap([self._thread], timeout, component="fleet.ship")
        self._close_transport()

    def is_running(self) -> bool:
        return self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.poll_once()
            except Exception as e:
                # a follower that dies silently strands its replica in a
                # stale-forever state; record and keep polling
                with self._lock:
                    self._last_error = f"{type(e).__name__}: {e}"
                log.warning("wal follower %s poll failed: %s", self.name, e)
            self._stop_evt.wait(self.poll_interval_s)

    # -- transport hooks -----------------------------------------------
    def poll_once(self) -> int:
        """One tailing pass; returns records committed.  Public so unit
        tests can drive the loop deterministically without the thread."""
        raise NotImplementedError

    def _reset_cursor(self) -> None:
        """Drop transport-side position state after a resync — the next
        poll re-derives it from ``_next_lsn``."""

    def _close_transport(self) -> None:
        """Release transport resources on stop (sockets, handles)."""

    # -- the holdback core ---------------------------------------------
    def _committed_next(self) -> int:
        with self._lock:
            return self._next_lsn

    def _visible_next(self) -> int:
        return self._committed_next() + (1 if self._held is not None else 0)

    def _resync(self, reason: str) -> None:
        telemetry.counter("fleet_ship_resyncs_total",
                          replica=self.name).inc()
        log.warning("wal follower %s resyncing from checkpoint (%s)",
                    self.name, reason)
        if self.resync_fn is None:
            with self._lock:
                self._last_error = f"stranded ({reason}), no resync_fn"
            raise WALError(f"follower {self.name} stranded: {reason}")
        next_lsn = int(self.resync_fn())
        with self._lock:
            self._next_lsn = next_lsn
            self._resyncs += 1
            self._last_error = None
        self._held = None
        self._reset_cursor()

    def _observe(self, lsn: int, payload: Optional[bytes]) -> int:
        """One visible slot: resolve the held predecessor, then hold or
        commit this one.  Returns records committed.  ``payload`` is
        None for a corrupt slot (consumes its LSN, carries no op)."""
        committed = 0
        target = decode_abort(payload) if payload is not None else None
        if self._held is not None:
            held_lsn, held_payload, _t0 = self._held
            self._held = None
            if target is not None and target == held_lsn:
                # the holdback worked: skip the aborted record and
                # consume the abort's own slot in one step — this is
                # NOT a late abort, the target was never applied
                telemetry.counter("fleet_ship_aborted_total",
                                  replica=self.name).inc()
                self._advance(lsn)
                return committed
            committed += self._commit(held_lsn, held_payload)
        if target is not None:
            if target < self._committed_next():
                # abort for a record we already applied: the grace
                # window was beaten — state diverged, rebuild it
                telemetry.counter("fleet_ship_late_aborts_total",
                                  replica=self.name).inc()
                self._advance(lsn)  # consume the abort's own slot
                self._resync(f"late abort for lsn {target}")
                return committed
            # the abort's own slot commits immediately (nothing can
            # cancel an abort)
            self._advance(lsn)
        elif payload is None:
            # corrupt frame: consumes its LSN slot, carries no op
            telemetry.counter("recovery_wal_corrupt_records_total").inc()
            self._advance(lsn)
        else:
            self._held = (lsn, payload, time.monotonic())
        return committed

    def _flush_held(self) -> int:
        """Commit the held tail record once its grace window expires —
        the no-successor-visible path (idle leader)."""
        if self._held is None:
            return 0
        held_lsn, payload, t0 = self._held
        if (time.monotonic() - t0) >= self.grace_s:
            self._held = None
            return self._commit(held_lsn, payload)
        return 0

    def _commit(self, lsn: int, payload: bytes) -> int:
        try:
            op, src, dst, ts = decode_edge_op(payload)
        except WALError as e:
            log.warning("follower %s: undecodable record at lsn %d: %s",
                        self.name, lsn, e)
            self._advance(lsn)
            return 0
        self.apply_fn(lsn, op, src, dst, ts)
        with self._lock:
            self._next_lsn = lsn + 1
            self._records += 1
        telemetry.counter("fleet_ship_records_total",
                          replica=self.name).inc()
        return 1

    def _advance(self, lsn: int) -> None:
        with self._lock:
            self._next_lsn = lsn + 1

    def _extra_lag(self) -> int:
        """Transport-visible lag beyond the held slot (the stream
        follower knows the leader's frontier from keepalives; file
        followers see exactly what is on disk)."""
        return 0

    def _publish_staleness(self) -> None:
        """Distance between what is visible and what is applied.  The
        held-back tail record counts as visible-but-unapplied (honest:
        it IS behind, bounded by the grace window)."""
        lag = (1 if self._held is not None else 0) + self._extra_lag()
        now = time.monotonic()
        with self._lock:
            self._staleness_lsn = lag
            if lag == 0:
                self._caught_up_at = now
                self._staleness_seconds = 0.0
            else:
                self._staleness_seconds = max(now - self._caught_up_at, 0.0)
            s_lsn, s_sec = self._staleness_lsn, self._staleness_seconds
        telemetry.gauge("fleet_replica_staleness_lsn",
                        replica=self.name).set(float(s_lsn))
        telemetry.gauge("fleet_replica_staleness_seconds",
                        replica=self.name).set(s_sec)

    # -- read side -----------------------------------------------------
    @property
    def applied_lsn(self) -> int:
        with self._lock:
            return self._next_lsn - 1

    def status(self) -> dict:
        with self._lock:
            out = {
                "name": self.name,
                "applied_lsn": self._next_lsn - 1,
                "records": self._records,
                "resyncs": self._resyncs,
                "staleness_lsn": self._staleness_lsn,
                "staleness_seconds": round(self._staleness_seconds, 3),
                "last_error": self._last_error,
            }
        out["running"] = self._thread.is_alive()
        return out


class WALFollower(TailFollower):
    """Tail one leader WAL directory, applying committed records.

    The shared-filesystem transport over :class:`TailFollower`: walks
    segment files with a frame-boundary byte cursor, waits on torn
    tails, rotates at sealed segment ends, and resyncs across
    truncation gaps.
    """

    def __init__(self, wal_dir: str,
                 apply_fn: Callable[[int, str, object, object, object],
                                    None],
                 start_lsn: int = -1,
                 resync_fn: Optional[Callable[[], int]] = None,
                 poll_interval_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 name: str = "follower"):
        super().__init__(apply_fn, start_lsn=start_lsn,
                         resync_fn=resync_fn,
                         poll_interval_s=poll_interval_s, grace_s=grace_s,
                         name=name, thread_prefix="quiver-fleet-ship")
        self.wal_dir = str(wal_dir)
        # follower-thread-private tail cursor (single thread root — the
        # poll loop; unit tests drive poll_once() from one thread too):
        self._seg_start: Optional[int] = None  # start LSN of open segment
        self._offset = 0                       # frame-boundary byte offset
        self._torn_waiting = False

    # -- tailing -------------------------------------------------------
    def _segments(self) -> List[Tuple[int, str]]:
        return list_segments(self.wal_dir)

    def _reset_cursor(self) -> None:
        self._seg_start = None
        self._offset = 0

    def _reposition(self, segs: List[Tuple[int, str]]) -> bool:
        """Point the cursor at the segment containing ``_next_lsn``;
        False when the log no longer covers it (truncation gap)."""
        target = self._committed_next()
        candidates = [(s, p) for s, p in segs if s <= target]
        if not candidates:
            return False
        start, path = candidates[-1]
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return False
        frames, _torn = scan_frames(data)
        slot, offset = start, 0
        for _kind, _payload, _off, end in frames:
            if slot >= target:
                break
            slot += 1
            offset = end
        if slot < target:
            # the durable log ends before the LSN a checkpoint claims to
            # cover — never expected (watermarks only cover synced
            # records); refuse to misnumber what follows
            return False
        # quiverlint: ignore[QT008] -- tail cursor has one driver at a
        # time: the poll thread in production, the test harness calling
        # poll_once() when the thread was never started; never both
        self._seg_start = start
        # quiverlint: ignore[QT008] -- single-driver tail cursor (above)
        self._offset = offset
        # quiverlint: ignore[QT008] -- single-driver tail cursor (above)
        self._held = None
        return True

    def poll_once(self) -> int:
        """One tailing pass; returns records committed.  Public so unit
        tests can drive the loop deterministically without the thread."""
        _CHAOS_SHIP()
        segs = self._segments()
        if not segs:
            self._publish_staleness()
            return 0
        if self._seg_start is None or not any(
                s == self._seg_start for s, _p in segs):
            if not self._reposition(segs):
                self._resync("wal no longer covers next lsn")
                segs = self._segments()
                if not self._reposition(segs):
                    self._publish_staleness()
                    return 0
        committed = 0
        while True:
            seg_idx = next((i for i, (s, _p) in enumerate(segs)
                            if s == self._seg_start), None)
            if seg_idx is None:
                break
            start, path = segs[seg_idx]
            try:
                if os.path.getsize(path) < self._offset:
                    # shrunk behind our frame-boundary cursor — only
                    # reachable through outside interference; re-derive
                    # the cursor rather than misframe
                    if not self._reposition(segs):
                        self._resync("segment shrank behind cursor")
                        segs = self._segments()
                        continue
                with open(path, "rb") as f:
                    f.seek(self._offset)
                    chunk = f.read()
            except OSError:
                break
            base = self._offset
            frames, torn = scan_frames(chunk)
            stranded = False
            for kind, payload, _off, end in frames:
                # quiverlint: ignore[QT008] -- single-driver tail cursor
                self._torn_waiting = False
                # the chunk starts at the next unobserved slot and slots
                # are consumed in order, so the frame's LSN is implied
                lsn = self._visible_next()
                committed += self._observe(
                    lsn, payload if kind == "ok" else None)
                if self._seg_start != start:
                    # a late abort resynced mid-scan; restart the walk
                    stranded = True
                    break
                # quiverlint: ignore[QT008] -- single-driver tail cursor
                self._offset = base + end
            if stranded:
                segs = self._segments()
                continue
            if torn:
                if not self._torn_waiting:
                    self._torn_waiting = True
                    telemetry.counter("fleet_ship_torn_waits_total",
                                      replica=self.name).inc()
                break
            # clean EOF: rotate iff a successor segment exists (the
            # leader only rolls before appending to the new file, so a
            # successor means this one is sealed)
            if seg_idx + 1 < len(segs):
                next_start = segs[seg_idx + 1][0]
                if self._visible_next() < next_start:
                    # slots vanished inside a sealed segment — never
                    # expected (restart truncation precedes the roll);
                    # refuse to guess, resync
                    self._resync("sealed segment ends before successor")
                    segs = self._segments()
                    continue
                self._seg_start = next_start
                self._offset = 0
                continue
            break
        committed += self._flush_held()
        self._publish_staleness()
        return committed
