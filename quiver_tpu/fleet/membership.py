"""Shared membership directory for the serving fleet.

One directory on a filesystem every replica can reach (the same class
of storage the checkpoints already live on) holds one
``replica-<id>.json`` per member, published through
``blockio.atomic_publish`` — readers see a complete old record or a
complete new one, never a torn hybrid, and a crashed writer leaves at
worst a stale record that ages out of the freshness window.  No
external coordination service: the WAL stays single-writer, so the
directory only has to answer "who exists, in what state, how fresh" —
liveness is decided by heartbeat age, not by consensus.

A replica announces itself through the fleet readiness ladder
(``booting → replaying → warming → serving``, plus ``draining`` while
it finishes in-flight work before deregistering).  The router treats
only *fresh* ``serving`` records as routable; everything else is
visible for operators (``/debug/fleet``) but receives no traffic.
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import telemetry
from ..recovery import blockio
from ..recovery.manager import RECOVERY_STATES

__all__ = ["FLEET_STATES", "ReplicaInfo", "MembershipDirectory",
           "shard_groups", "group_complete"]

# the recovery ladder plus the explicit-drain state; order is the gauge
# encoding of fleet_replica_state
FLEET_STATES = RECOVERY_STATES + ("draining",)
_STATE_CODE = {s: i for i, s in enumerate(FLEET_STATES)}

_REC_RE = re.compile(r"^replica-([A-Za-z0-9_.-]+)\.json$")


def _record_path(root: str, replica_id: str) -> str:
    if not re.match(r"^[A-Za-z0-9_.-]+$", replica_id):
        raise ValueError(f"replica id {replica_id!r} must be filesystem-"
                         "safe ([A-Za-z0-9_.-])")
    return os.path.join(root, f"replica-{replica_id}.json")


@dataclass
class ReplicaInfo:
    """One parsed membership record."""

    replica_id: str
    state: str = "booting"
    host: str = "127.0.0.1"
    port: int = 0
    role: str = "follower"          # "leader" | "follower"
    pid: int = 0
    heartbeat: float = 0.0          # wall-clock time of the last announce
    staleness_lsn: int = 0
    staleness_seconds: float = 0.0
    wal_next_lsn: int = -1          # leaders: the shipping frontier
    epoch: int = -1                 # leaders: the fencing epoch claimed
    detail: dict = field(default_factory=dict)

    def fresh(self, timeout_s: float, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        return (now - self.heartbeat) <= timeout_s

    # -- shard-group membership (docs/SHARDING.md) ---------------------
    # Shard groups ride the extensible ``detail`` dict, so records from
    # pre-mesh builds parse unchanged and an unsharded fleet never
    # carries the keys at all.
    @property
    def shard_group(self) -> Optional[str]:
        """Group id when this member is one shard of a logical replica
        spanning several processes; None for a whole-graph replica."""
        g = self.detail.get("shard_group")
        return str(g) if g else None

    @property
    def shard_index(self) -> int:
        try:
            return int(self.detail.get("shard_index", 0))
        except (TypeError, ValueError):
            return 0

    @property
    def shard_count(self) -> int:
        try:
            return int(self.detail.get("shard_count", 0))
        except (TypeError, ValueError):
            return 0

    def to_dict(self) -> dict:
        return {
            "replica_id": self.replica_id, "state": self.state,
            "host": self.host, "port": self.port, "role": self.role,
            "pid": self.pid, "heartbeat": self.heartbeat,
            "staleness_lsn": self.staleness_lsn,
            "staleness_seconds": self.staleness_seconds,
            "wal_next_lsn": self.wal_next_lsn, "epoch": self.epoch,
            "detail": self.detail,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ReplicaInfo":
        return cls(
            replica_id=str(d["replica_id"]),
            state=str(d.get("state", "booting")),
            host=str(d.get("host", "127.0.0.1")),
            port=int(d.get("port", 0)),
            role=str(d.get("role", "follower")),
            pid=int(d.get("pid", 0)),
            heartbeat=float(d.get("heartbeat", 0.0)),
            staleness_lsn=int(d.get("staleness_lsn", 0)),
            staleness_seconds=float(d.get("staleness_seconds", 0.0)),
            wal_next_lsn=int(d.get("wal_next_lsn", -1)),
            epoch=int(d.get("epoch", -1)),
            detail=dict(d.get("detail", {})),
        )


def shard_groups(infos: List[ReplicaInfo]) -> Dict[str, List[ReplicaInfo]]:
    """Group shard members by group id, sorted by shard index.  Members
    without a ``shard_group`` (whole-graph replicas) are not included —
    they route as singletons."""
    groups: Dict[str, List[ReplicaInfo]] = {}
    for info in infos:
        gid = info.shard_group
        if gid is not None:
            groups.setdefault(gid, []).append(info)
    for members in groups.values():
        members.sort(key=lambda r: (r.shard_index, r.replica_id))
    return groups


def group_complete(members: List[ReplicaInfo]) -> bool:
    """A shard group is routable only when EVERY declared shard is
    present exactly once: each member's declared ``shard_count`` must
    agree and the shard indices must be exactly ``{0 .. n-1}`` — a
    half-booted or split-brained group never takes traffic."""
    if not members:
        return False
    counts = {m.shard_count for m in members}
    if len(counts) != 1:
        return False
    n = counts.pop()
    if n < 1 or len(members) != n:
        return False
    return sorted(m.shard_index for m in members) == list(range(n))


class MembershipDirectory:
    """File-backed fleet membership: announce / scan / deregister.

    Stateless between calls — every reader re-scans the directory, so
    there is no cached view to invalidate and any process (router,
    replica, operator tooling) can open its own instance over the same
    root.  Announce is an atomic whole-file publish; deregister is an
    unlink; a record whose JSON does not parse (torn by a crashed
    pre-atomic writer, or hand-edited) is skipped, never fatal.
    """

    def __init__(self, root: str,
                 heartbeat_timeout_s: Optional[float] = None):
        from ..config import get_config

        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.heartbeat_timeout_s = float(
            heartbeat_timeout_s if heartbeat_timeout_s is not None
            else get_config().fleet_heartbeat_timeout_s)

    # -- write side ----------------------------------------------------
    def announce(self, info: ReplicaInfo,
                 heartbeat: Optional[float] = None) -> str:
        """Publish (or refresh) one replica record; returns its path."""
        if info.state not in FLEET_STATES:
            raise ValueError(f"unknown fleet state {info.state!r} "
                             f"(expected one of {FLEET_STATES})")
        # stamp the published record, not the caller's object — announce
        # may run from a heartbeat thread while the owner reads its copy
        stamp = time.time() if heartbeat is None else heartbeat
        path = _record_path(self.root, info.replica_id)
        blockio.atomic_publish(
            path, json.dumps(dict(info.to_dict(), heartbeat=stamp),
                             sort_keys=True).encode())
        telemetry.gauge("fleet_replica_state",
                        replica=info.replica_id).set(
            float(_STATE_CODE[info.state]))
        return path

    def deregister(self, replica_id: str) -> bool:
        """Remove a replica's record (drain completion / shutdown);
        True when a record existed."""
        try:
            os.unlink(_record_path(self.root, replica_id))
        except FileNotFoundError:
            return False
        return True

    # -- read side -----------------------------------------------------
    def replicas(self, fresh_only: bool = False) -> List[ReplicaInfo]:
        """Every parseable record, sorted by id.  ``fresh_only`` drops
        records whose heartbeat is older than the freshness window —
        the router's definition of "exists"."""
        out: List[ReplicaInfo] = []
        now = time.time()
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in sorted(names):
            if not _REC_RE.match(name):
                continue
            try:
                with open(os.path.join(self.root, name), "rb") as f:
                    info = ReplicaInfo.from_dict(json.loads(f.read()))
            except FileNotFoundError:
                # unlinked between listdir and open (deregister racing
                # a scan): the member is simply gone, same treatment as
                # any other unreadable record
                telemetry.counter(
                    "fleet_membership_parse_errors_total").inc()
                continue
            except (OSError, ValueError, KeyError, TypeError):
                # torn/garbage record: a membership scan must never die
                # on one bad file
                telemetry.counter(
                    "fleet_membership_parse_errors_total").inc()
                continue
            if fresh_only and not info.fresh(self.heartbeat_timeout_s, now):
                continue
            out.append(info)
        counts: Dict[str, int] = {s: 0 for s in FLEET_STATES}
        for info in out:
            if info.state in counts and info.fresh(
                    self.heartbeat_timeout_s, now):
                counts[info.state] += 1
        for state, n in counts.items():
            telemetry.gauge("fleet_replicas_total", state=state).set(
                float(n))
        return out

    def get(self, replica_id: str) -> Optional[ReplicaInfo]:
        for info in self.replicas():
            if info.replica_id == replica_id:
                return info
        return None

    def leader(self) -> Optional[ReplicaInfo]:
        """The fresh leader record, if any — the one with the highest
        fencing epoch.

        During a failover there is a window where a deposed leader's
        still-fresh record coexists with the successor's: the epoch is
        the authority (the fence guarantees the higher epoch owns the
        WAL), with heartbeat recency only as a tiebreak for epoch-less
        legacy records.  Observing more than one fresh leader ticks
        ``fleet_leader_conflicts_total`` — a conflict the fence makes
        harmless but operators still want to see."""
        leaders = [r for r in self.replicas(fresh_only=True)
                   if r.role == "leader"]
        if not leaders:
            return None
        if len(leaders) > 1:
            telemetry.counter("fleet_leader_conflicts_total").inc()
        return max(leaders, key=lambda r: (r.epoch, r.heartbeat))

    def status(self) -> dict:
        """JSON view for ``/debug/fleet``."""
        now = time.time()
        return {
            "root": self.root,
            "heartbeat_timeout_s": self.heartbeat_timeout_s,
            "replicas": [
                dict(r.to_dict(),
                     fresh=r.fresh(self.heartbeat_timeout_s, now),
                     # quiverlint: ignore[QT012] -- heartbeat ages are
                     # cross-process, so wall clock is the only shared
                     # clock; freshness windows absorb small NTP steps
                     heartbeat_age_s=round(max(now - r.heartbeat, 0.0), 3))
                for r in self.replicas()
            ],
        }
