"""Elastic replicated serving fleet (docs/FLEET.md).

Turns the single-node subsystems into a deployable system: a thin
partition-aware router in front of N replicas that warm-boot from
shared checkpoints + the JAX persistent compilation cache, tail the
single-writer WAL for reads, and survive ``kill -9`` mid-burst without
losing an in-flight request (``benchmarks/fleet_chaos.py``).

  * :mod:`~quiver_tpu.fleet.membership` — shared file-based replica
    directory (atomic-rename records, heartbeat freshness);
  * :mod:`~quiver_tpu.fleet.shipping` — read-only WAL follower with a
    measured, bounded staleness watermark;
  * :mod:`~quiver_tpu.fleet.replica` — replica lifecycle: warm join,
    heartbeats, TCP serving endpoint, drain/rejoin;
  * :mod:`~quiver_tpu.fleet.router` — consistent-hash routing, health
    gating, per-replica breakers, bounded re-dispatch;
  * :mod:`~quiver_tpu.fleet.federation` — the fleet observability
    plane: metrics federation, fleet SLOs, clock-aligned merged
    timelines, cross-process trace reconstruction
    (docs/OBSERVABILITY.md);
  * :mod:`~quiver_tpu.fleet.election` — fenced leader auto-failover:
    epoch-stamped exclusive claims, a fencing token on every write,
    ranked follower promotion (``fleet_election=on``);
  * :mod:`~quiver_tpu.fleet.walstream` — socket WAL shipping for
    followers with no shared WAL filesystem (``fleet_walstream=on``);
  * :mod:`~quiver_tpu.fleet.autoscaler` — federation-driven predictive
    spawn/drain control loop (``fleet_autoscaler=on``).
"""

from .autoscaler import DiurnalPredictor, FleetAutoscaler
from .election import (ClaimRecord, ElectionDirectory, EpochFence,
                       FencedWAL, LeaderElector, StaleEpochError)
from .federation import (FleetFederation, FleetSLOWatchdog,
                         estimate_offsets, federate, federation_status,
                         get_federation, parse_prometheus_text)
from .membership import FLEET_STATES, MembershipDirectory, ReplicaInfo
from .replica import FleetReplica
from .router import ConsistentHashRing, FleetRouter, fleet_status
from .shipping import TailFollower, WALFollower
from .walstream import WALStreamFollower, WALStreamServer

__all__ = [
    "FLEET_STATES", "MembershipDirectory", "ReplicaInfo", "FleetReplica",
    "ConsistentHashRing", "FleetRouter", "fleet_status", "WALFollower",
    "TailFollower", "FleetFederation", "FleetSLOWatchdog",
    "estimate_offsets", "federate", "federation_status", "get_federation",
    "parse_prometheus_text", "ClaimRecord", "ElectionDirectory",
    "EpochFence", "FencedWAL", "LeaderElector", "StaleEpochError",
    "WALStreamServer", "WALStreamFollower", "DiurnalPredictor",
    "FleetAutoscaler",
]
