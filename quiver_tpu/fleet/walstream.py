"""Socket WAL shipping: cross-host follower tails, no shared filesystem.

``shipping.WALFollower`` assumes the follower can read the leader's
segment files.  This module removes that assumption: the leader runs a
:class:`WALStreamServer` — a TCP JSON-lines endpoint in the same
transport shape as the replica serving endpoint — and followers run a
:class:`WALStreamFollower`, the same
:class:`~quiver_tpu.fleet.shipping.TailFollower` catch-up/holdback core
over a stream cursor instead of a byte cursor.

Wire protocol (one JSON object per line):

  * hello (client → server): ``{"from_lsn": N, "follower": id}`` — the
    resume cursor.  Reconnect-after-disconnect is just a new hello with
    the next uncommitted LSN; the server re-serves from there.
  * frame (server → client): ``{"lsn": N, "frame": "<base64>"}`` — the
    **raw disk bytes** of one ``blockio`` record (header + payload).
    The receiver runs ``blockio.scan_records`` over them, so the CRC
    that is re-verified is the one the leader's disk holds — a frame
    corrupted in server memory or on the wire is caught, ticked
    (``fleet_walstream_crc_errors_total``) and re-fetched by resume,
    never applied.  A checksum-corrupt slot on the leader's own disk
    ships as ``{"lsn": N, "kind": "corrupt"}`` (consumes its LSN,
    carries no op — identical to the file follower's treatment).
  * eot (server → client): ``{"eot": true, "next_lsn": N}`` — the
    leader's durable frontier; sent after every cycle as keepalive and
    staleness signal.  A torn tail on the leader's disk is *waited
    out* exactly like ``WALFollower`` does: the server stops before
    the torn frame and re-polls — it never ships unframeable bytes.
  * gap (server → client): ``{"error": "gap", ...}`` — the log no
    longer covers ``from_lsn`` (checkpoint truncation ran ahead of
    this follower); the follower resyncs from the newest shared
    checkpoint and reconnects, same contract as the file tail.

Chaos points ``fleet.walstream.send`` / ``fleet.walstream.recv`` fire
per shipped/received record, so a seeded plan can cut the stream at an
exact record index and the harness can prove resume-from-LSN loses
nothing.
"""

from __future__ import annotations

import base64
import json
import logging
import socket
import socketserver
import threading
import time
from typing import Callable, Optional, Tuple

from .. import telemetry
from ..recovery import blockio
from ..resilience import chaos
from ..resilience.errors import ChaosFault
from .shipping import TailFollower, list_segments, scan_frames

__all__ = ["WALStreamServer", "WALStreamFollower"]

log = logging.getLogger("quiver_tpu.fleet")

_CHAOS_SEND = chaos.point("fleet.walstream.send")
_CHAOS_RECV = chaos.point("fleet.walstream.recv")


class _StreamReset(Exception):
    """Receiver-side transport anomaly (CRC mismatch, LSN gap, protocol
    garbage): drop the connection and resume from the committed LSN."""


class _RawTail:
    """Per-connection raw-frame cursor over the leader's segment files.

    The same walk as ``WALFollower.poll_once`` — reposition by LSN,
    stop at torn tails, rotate only past sealed segments — but yielding
    raw frame bytes instead of decoding them, and shipping corrupt
    slots as explicit markers.  Thread-private to one handler."""

    def __init__(self, wal_dir: str, next_lsn: int):
        self.wal_dir = str(wal_dir)
        self.next_lsn = int(next_lsn)
        self._seg_start: Optional[int] = None
        self._offset = 0

    def _reposition(self, segs) -> bool:
        candidates = [(s, p) for s, p in segs if s <= self.next_lsn]
        if not candidates:
            return False
        start, path = candidates[-1]
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return False
        frames, _torn = scan_frames(data)
        slot, offset = start, 0
        for _kind, _payload, _off, end in frames:
            if slot >= self.next_lsn:
                break
            slot += 1
            offset = end
        if slot < self.next_lsn:
            return False
        self._seg_start = start
        self._offset = offset
        return True

    def poll(self):
        """``("frames", [(lsn, kind, raw_bytes)])`` with whatever is
        newly visible (possibly empty), or ``("gap", oldest_lsn)`` when
        the log no longer covers the cursor."""
        segs = list_segments(self.wal_dir)
        if not segs:
            # an empty directory is a leader that has not appended yet
            # when the cursor is at the origin; anything else is a gap
            return (("frames", []) if self.next_lsn == 0
                    else ("gap", 0))
        if self._seg_start is None or not any(
                s == self._seg_start for s, _p in segs):
            if not self._reposition(segs):
                return ("gap", segs[0][0])
        out = []
        while True:
            seg_idx = next((i for i, (s, _p) in enumerate(segs)
                            if s == self._seg_start), None)
            if seg_idx is None:
                break
            _start, path = segs[seg_idx]
            try:
                with open(path, "rb") as f:
                    f.seek(self._offset)
                    chunk = f.read()
            except OSError:
                break
            frames, torn = scan_frames(chunk)
            for kind, _payload, off, end in frames:
                out.append((self.next_lsn, kind, bytes(chunk[off:end])))
                self.next_lsn += 1
            if frames:
                self._offset += frames[-1][3]
            if torn:
                break
            if seg_idx + 1 < len(segs):
                # sealed: rotate iff a successor exists
                self._seg_start = segs[seg_idx + 1][0]
                self._offset = 0
                continue
            break
        return ("frames", out)


class _StreamTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class WALStreamServer:
    """Leader-side WAL stream endpoint: serve framed records from an
    LSN cursor to any number of followers.

    Read-only over the segment files (the WAL object keeps sole write
    ownership); an optional :class:`~quiver_tpu.fleet.election.
    EpochFence` makes a deposed leader's stream go quiet — followers
    get a ``deposed`` error and re-resolve the write path through
    membership instead of tailing a fenced-off log."""

    def __init__(self, wal_dir: str, host: str = "127.0.0.1",
                 port: Optional[int] = None,
                 poll_interval_s: Optional[float] = None,
                 name: str = "leader", fence=None):
        from ..config import get_config

        cfg = get_config()
        self.wal_dir = str(wal_dir)
        self.host = host
        self.name = str(name)
        self.fence = fence
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else cfg.fleet_ship_poll_ms / 1e3)
        self._stop_evt = threading.Event()
        outer = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                outer._serve_conn(self)

        self._server = _StreamTCPServer(
            (host, int(port if port is not None
                       else cfg.fleet_walstream_port)), _Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"quiver-fleet-walstream-{self.name}")
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def stop(self, timeout: float = 5.0) -> None:
        from ..resilience.shutdown import join_and_reap

        self._stop_evt.set()
        self._server.shutdown()
        self._server.server_close()
        if self._thread.is_alive():
            join_and_reap([self._thread], timeout,
                          component="fleet.walstream")

    # -- one connection ------------------------------------------------
    def _serve_conn(self, handler) -> None:
        line = handler.rfile.readline()
        if not line:
            return
        try:
            hello = json.loads(line)
            from_lsn = int(hello.get("from_lsn", 0))
        except (ValueError, TypeError):
            self._send(handler, {"error": "bad_hello"})
            return
        telemetry.counter("fleet_walstream_connections_total",
                          replica=self.name).inc()
        if from_lsn > 0:
            telemetry.counter("fleet_walstream_resumes_total",
                              replica=self.name).inc()
        tail = _RawTail(self.wal_dir, from_lsn)
        try:
            while not self._stop_evt.is_set():
                if self.fence is not None and self.fence.deposed:
                    # a deposed leader must not keep feeding followers a
                    # log it no longer owns — send them back to
                    # membership to find the new write path
                    self._send(handler, {"error": "deposed"})
                    return
                state = tail.poll()
                if state[0] == "gap":
                    self._send(handler, {"error": "gap",
                                         "oldest_lsn": state[1]})
                    return
                for lsn, kind, raw in state[1]:
                    _CHAOS_SEND()
                    if kind == "ok":
                        msg = {"lsn": lsn,
                               "frame":
                               base64.b64encode(raw).decode("ascii")}
                    else:
                        msg = {"lsn": lsn, "kind": "corrupt"}
                    self._send(handler, msg)
                    telemetry.counter("fleet_walstream_sent_total",
                                      replica=self.name).inc()
                self._send(handler, {"eot": True,
                                     "next_lsn": tail.next_lsn})
                self._stop_evt.wait(self.poll_interval_s)
        except ChaosFault:
            # injected send fault: the connection dies mid-stream — the
            # follower's resume-from-LSN is what the harness proves
            return
        except OSError:
            return  # follower went away; its reconnect is a new hello

    @staticmethod
    def _send(handler, msg: dict) -> None:
        handler.wfile.write((json.dumps(msg) + "\n").encode())


class WALStreamFollower(TailFollower):
    """The socket-tail follower: :class:`TailFollower` holdback over a
    resumable stream cursor.

    ``endpoint_fn()`` returns the current ``(host, port)`` of the
    leader's stream endpoint (or None while there is no leader) — it is
    re-resolved on every (re)connect, so a fenced failover moves the
    tail to the new leader's endpoint without restarting the replica.
    """

    def __init__(self,
                 endpoint_fn: Callable[[], Optional[Tuple[str, int]]],
                 apply_fn: Callable[[int, str, object, object, object],
                                    None],
                 start_lsn: int = -1,
                 resync_fn: Optional[Callable[[], int]] = None,
                 poll_interval_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 connect_timeout_s: Optional[float] = None,
                 name: str = "follower"):
        from ..config import get_config

        super().__init__(apply_fn, start_lsn=start_lsn,
                         resync_fn=resync_fn,
                         poll_interval_s=poll_interval_s, grace_s=grace_s,
                         name=name, thread_prefix="quiver-fleet-walstream")
        self.endpoint_fn = endpoint_fn
        self.connect_timeout_s = float(
            connect_timeout_s if connect_timeout_s is not None
            else get_config().fleet_request_timeout_s)
        # follower-thread-private stream cursor (same single-driver
        # contract as the file follower's byte cursor)
        self._sock: Optional[socket.socket] = None
        self._buf = bytearray()
        self._server_next: Optional[int] = None
        self._connected_once = False

    # -- transport -----------------------------------------------------
    def _disconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf.clear()

    def _reset_cursor(self) -> None:
        self._disconnect()
        self._server_next = None

    def _close_transport(self) -> None:
        self._disconnect()

    def _connect(self) -> bool:
        ep = self.endpoint_fn()
        if not ep:
            return False
        try:
            sock = socket.create_connection(
                (ep[0], int(ep[1])), timeout=self.connect_timeout_s)
            sock.sendall((json.dumps(
                {"from_lsn": self._committed_next(),
                 "follower": self.name}) + "\n").encode())
        except OSError:
            return False
        self._sock = sock
        self._buf.clear()
        if self._connected_once:
            telemetry.counter("fleet_walstream_reconnects_total",
                              replica=self.name).inc()
        self._connected_once = True
        return True

    def _read_lines(self):
        """Complete lines until the poll deadline / eot — own buffering
        (a timeout mid-``readline`` on a makefile reader would leave
        its buffer state undefined; this never loses buffered bytes)."""
        deadline = time.monotonic() + max(self.poll_interval_s, 0.01)
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = bytes(self._buf[:nl])
                del self._buf[:nl + 1]
                yield line
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            self._sock.settimeout(remaining)
            try:
                data = self._sock.recv(65536)
            except TimeoutError:
                return
            except socket.timeout:  # pre-3.10 alias, kept for safety
                return
            if not data:
                raise _StreamReset("stream closed by leader")
            self._buf += data

    def _verify(self, frame: bytes) -> bytes:
        """Receiver-side CRC re-verification of the shipped disk bytes,
        through the same ``blockio`` framing replay trusts."""
        scanned = list(blockio.scan_records(frame))
        if len(scanned) == 1 and scanned[0][0] == "ok":
            return scanned[0][2]
        telemetry.counter("fleet_walstream_crc_errors_total",
                          replica=self.name).inc()
        raise _StreamReset("frame failed CRC re-verification")

    # -- tailing -------------------------------------------------------
    def poll_once(self) -> int:
        if self._sock is None and not self._connect():
            self._publish_staleness()
            return 0
        committed = 0
        try:
            for line in self._read_lines():
                try:
                    msg = json.loads(line)
                except ValueError:
                    raise _StreamReset("unparsable stream line")
                if "error" in msg:
                    if msg["error"] == "gap":
                        # truncation ran ahead of us: same contract as
                        # the file tail — checkpoint resync
                        self._resync("stream gap (leader truncated)")
                        break
                    raise _StreamReset(f"stream error: {msg['error']}")
                if msg.get("eot"):
                    self._server_next = int(msg.get("next_lsn", -1))
                    break
                _CHAOS_RECV()
                lsn = int(msg["lsn"])
                vn = self._visible_next()
                if lsn < vn:
                    continue  # duplicate slot after a resume
                if lsn > vn:
                    raise _StreamReset(
                        f"stream skipped lsn {vn} (got {lsn})")
                payload = (None if msg.get("kind") == "corrupt"
                           else self._verify(
                               base64.b64decode(msg["frame"])))
                committed += self._observe(lsn, payload)
        except (_StreamReset, ChaosFault, OSError, KeyError,
                TypeError) as e:
            log.warning("walstream follower %s dropped connection: %s",
                        self.name, e)
            self._disconnect()
        committed += self._flush_held()
        self._publish_staleness()
        return committed

    def _extra_lag(self) -> int:
        if self._sock is None or self._server_next is None:
            return 0
        return max(self._server_next - self._visible_next(), 0)
