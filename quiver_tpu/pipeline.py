"""Fully-fused training pipeline: sample + gather + forward/backward in
ONE compiled program.

The reference's hot loop crosses the host every batch: python drives
sampler kernels, then a feature gather, then the torch step
(``examples/pyg/ogbn_products_sage_quiver.py:138-147``).  On TPU the whole
chain is expressible as a single jit — seeds in, (state, loss) out — so
steady-state training has zero host round-trips and XLA overlaps sampling
gathers with the previous layer's compute.  Requires the feature hot tier
to cover the graph (HBM-resident or ici-sharded); budgeted hot/cold setups
fall back to the two-stage loop (``SeedLoader``).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from .feature import Feature
from .sampler import GraphSageSampler, run_pipeline
from .parallel.train import TrainState

__all__ = ["make_fused_train_step", "make_fused_eval_fn"]


def _check(feature: Feature):
    assert feature.cache_count >= feature.node_count, (
        "fused pipeline needs the feature fully HBM-resident "
        f"(cache {feature.cache_count} < nodes {feature.node_count}); "
        "use SeedLoader for budgeted hot/cold configs"
    )


def make_fused_train_step(sampler: GraphSageSampler, feature: Feature,
                          apply_fn: Callable,
                          tx: optax.GradientTransformation,
                          loss_fn: Optional[Callable] = None):
    """Build ``(state, seeds, labels, label_mask, key) -> (state, loss)``
    with sampling and feature gather inside the jit."""
    _check(feature)
    indptr, indices = sampler.csr_topo.to_device(sampler.device)
    sizes = tuple(sampler.sizes)
    gm, srng = sampler.gather_mode, sampler.sample_rng
    dedup = sampler.dedup
    caps = tuple(sampler.frontier_caps)

    if loss_fn is None:
        def loss_fn(logits, labels, mask):
            ls = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            )
            m = mask.astype(ls.dtype)
            return (ls * m).sum() / jnp.maximum(m.sum(), 1.0)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, seeds, labels, label_mask, key):
        ks, kd = jax.random.split(key)
        n_id, n_mask, num, blocks, _ = run_pipeline(
            dedup, indptr, indices, seeds, ks, sizes, caps, gather_mode=gm,
            sample_rng=srng
        )
        x = feature.lookup_device(n_id)

        def compute(params):
            logits = apply_fn(params, x, blocks, train=True,
                              rngs={"dropout": kd})
            return loss_fn(logits, labels, label_mask)

        loss, grads = jax.value_and_grad(compute)(state.params)
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.tx), loss

    return step


def make_scan_epoch(sampler: GraphSageSampler, feature: Feature,
                    apply_fn: Callable, tx: optax.GradientTransformation,
                    loss_fn: Optional[Callable] = None):
    """Whole-epoch ``lax.scan`` variant of the fused step.

    ``(state, seeds [S, B], labels [S, B], key) -> (state, losses [S])`` —
    S steps execute as ONE device program: no per-step dispatch at all.
    Compile cost is paid once per (S, B) shape; use for steady production
    epochs, the plain fused step for interactive work.
    """
    _check(feature)
    step = make_fused_train_step(sampler, feature, apply_fn, tx, loss_fn)
    # reuse the already-jitted step inside scan: re-expressing it as a
    # traced body lets XLA pipeline across steps
    indptr, indices = sampler.csr_topo.to_device(sampler.device)

    @jax.jit
    def epoch(state: TrainState, seeds, labels, key):
        S, B = seeds.shape
        ones = jnp.ones((B,), bool)

        def body(state, xs):
            s, l, k = xs
            state, loss = step(state, s, l, ones, k)
            return state, loss

        keys = jax.random.split(key, S)
        state, losses = jax.lax.scan(body, state, (seeds, labels, keys))
        return state, losses

    return epoch


def make_fused_eval_fn(sampler: GraphSageSampler, feature: Feature,
                       apply_fn: Callable):
    """``(params, seeds, key) -> logits`` with sampling inside the jit."""
    _check(feature)
    indptr, indices = sampler.csr_topo.to_device(sampler.device)
    sizes = tuple(sampler.sizes)
    gm, srng = sampler.gather_mode, sampler.sample_rng

    dedup = sampler.dedup
    caps = tuple(sampler.frontier_caps)

    @jax.jit
    def eval_fn(params, seeds, key):
        n_id, n_mask, num, blocks, _ = run_pipeline(
            dedup, indptr, indices, seeds, key, sizes, caps, gather_mode=gm,
            sample_rng=srng
        )
        x = feature.lookup_device(n_id)
        return apply_fn(params, x, blocks, train=False, rngs=None)

    return eval_fn
