"""Single-chip big-graph tier — the TPU answer to UVA mode.

Reference: ``quiver<T,CUDA>`` mode ``ZERO_COPY`` keeps the CSR in pinned
host memory and lets sampling kernels read it over PCIe
(``srcs/cpp/include/quiver/quiver.cu.hpp:16-26, 155-464``), so one GPU can
sample a graph larger than its HBM.  TPU kernels cannot dereference host
memory mid-kernel, so a literal port is impossible; the tpu-first
equivalent mirrors the feature store's hot/cold split:

  * **hot rows** — the byte-budgeted, degree-ordered top rows' edge lists
    live in HBM as a compacted sub-CSR; their sampling runs on device at
    HBM bandwidth (the common case: power-law graphs put most sampled
    edges in few rows).
  * **cold rows** — remaining edge lists stay in host RAM (or mmap) and
    sample through the multithreaded native CPU sampler
    (``cpp/csrc/quiver_cpu.cpp``) — RAM plays pinned memory, the CPU
    plays the PCIe engine.

Each hop dispatches the device program first (async) and samples the cold
subset while it runs, so the host tier hides behind the device tier
exactly like the reference's zero-copy reads hide behind the kernel.

Activated by ``GraphSageSampler(..., mode="UVA", uva_budget="1G")``.
With no budget (or a budget covering all edges) every row is hot and the
mode degenerates to plain TPU sampling of an HBM graph.
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from .utils.topology import CSRTopo, parse_size

__all__ = ["UVAGraph"]


class UVAGraph:
    """Hot/cold split of a CSR's edge lists (see module docstring)."""

    def __init__(self, topo: CSRTopo, budget: Union[int, str, None],
                 n_threads: int = 0):
        import jax.numpy as jnp

        deg = topo.degree.astype(np.int64)
        n = topo.node_count
        budget_b = None if budget is None else parse_size(budget)
        if budget_b is None or budget_b >= topo.edge_count * 4:
            hot_mask = np.ones(n, dtype=bool)
        else:
            order = np.argsort(-deg, kind="stable")
            cum = np.cumsum(deg[order]) * 4  # indices are int32
            hot_mask = np.zeros(n, dtype=bool)
            hot_mask[order[cum <= budget_b]] = True
        self.is_hot = hot_mask
        self.hot_edges = int(deg[hot_mask].sum())
        self.cold_edges = int(topo.edge_count - self.hot_edges)

        # compacted hot sub-CSR over ALL node ids: cold rows have degree 0
        hot_deg = np.where(hot_mask, deg, 0)
        indptr_hot = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(hot_deg, out=indptr_hot[1:])
        if self.hot_edges >= 2**31:  # same guard as CSRTopo.to_device
            raise ValueError(
                f"hot tier has {self.hot_edges:,} edges — int32 positions "
                "overflow; lower uva_budget (or shard over a mesh)"
            )
        edge_is_hot = np.repeat(hot_mask, deg)
        indices_hot = topo.indices[edge_is_hot].astype(np.int32)
        # pad to a non-empty multiple of 128 (lanes/pallas gather modes;
        # empty tables break jnp.take even when fully masked)
        pad = (-len(indices_hot)) % 128 or (128 if not len(indices_hot)
                                            else 0)
        if pad:
            indices_hot = np.concatenate(
                [indices_hot, np.zeros(pad, np.int32)]
            )
        # indptr needs the same 128 padding: the lanes gather truncates
        # the table to a 128 multiple and CLIPS indices — an unpadded
        # [n+1] indptr silently returns a wrong row's pointers for the
        # last (n+1) % 128 node ids
        indptr_pad = indptr_hot.astype(np.int32)
        ppad = (-len(indptr_pad)) % 128
        if ppad:
            # repeat the final offset: padded "rows" read as degree 0
            indptr_pad = np.concatenate(
                [indptr_pad, np.full(ppad, indptr_pad[-1], np.int32)]
            )
        self.indptr_dev = jnp.asarray(indptr_pad)
        self.indices_dev = jnp.asarray(indices_hot)

        from .cpp.native import CPUSampler

        # the host tier keeps the FULL CSR (cold rows are read from it);
        # with an mmap-backed topo this never materializes in RAM
        self.cpu = CPUSampler(topo.indptr, topo.indices,
                              n_threads=n_threads)

    def stats(self) -> dict:
        return dict(hot_edges=self.hot_edges, cold_edges=self.cold_edges,
                    hot_rows=int(self.is_hot.sum()),
                    hbm_bytes=int(self.hot_edges * 4))


def sample_uva(uva: UVAGraph, sizes, input_nodes, key, gather_mode="xla",
               sample_rng="auto", overlap=True, timings=None):
    """Host-driven multi-hop loop over the hot/cold split.

    Per hop: device samples the hot rows (dispatched async), the native
    CPU sampler covers the cold rows meanwhile, blocks merge host-side
    with the same positional no-dedup relabeling as the TPU pipeline.
    Returns the ``(n_id, n_id_mask, num_nodes, blocks)`` tuple the caller
    wraps into a :class:`SampledBatch`.

    ``overlap=False`` forces the device sync BEFORE the host tier runs —
    the serialized baseline the overlap claim is measured against
    (bench's ``sampling_uva`` section reports the A/B as
    ``overlap_factor``).  ``timings``: optional dict accumulating
    ``host_s`` (cold-tier wall inside this call) for tier attribution.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    from . import telemetry
    from .ops.sample import sample_neighbors

    frontier = np.asarray(input_nodes, dtype=np.int32)
    fmask = np.ones(len(frontier), dtype=bool)
    blocks = []
    keys = jax.random.split(key, len(sizes))
    # One host readback covers every hop's cold-tier seed (it used to be a
    # per-hop sync inside the loop): the host tier's RNG derives from the
    # same jax keys, so a pinned key still replays BOTH tiers.
    # quiverlint: ignore[QT001] — single pre-loop sync replaces L per-hop syncs
    key_data = np.asarray(jax.random.key_data(keys))
    for l, k in enumerate(sizes):
        hot = uva.is_hot[frontier] & fmask
        # device first (returns immediately — XLA async dispatch) ...
        out = sample_neighbors(uva.indptr_dev, uva.indices_dev,
                               jnp.asarray(frontier), k, keys[l],
                               seed_mask=jnp.asarray(hot),
                               gather_mode=gather_mode,
                               sample_rng=sample_rng)
        if not overlap:  # serialized A/B baseline: wait for device first
            # quiverlint: ignore[QT001] — overlap=False A/B baseline
            # serializes device-then-host on purpose (measures the win)
            out.nbrs.block_until_ready()
        # ... host tier runs while the device works
        cold_idx = np.nonzero(fmask & ~hot)[0]
        if len(cold_idx):
            hop_seed = int(key_data[l, -1])
            t0 = _time.perf_counter()
            cn, cm, _ = uva.cpu.sample_neighbors(frontier[cold_idx], k,
                                                 seed=hop_seed)
            host_dt = _time.perf_counter() - t0
            if timings is not None:
                timings["host_s"] = timings.get("host_s", 0.0) + host_dt
            telemetry.histogram("uva_host_tier_seconds").observe(host_dt)
        # per-hop hot/cold seed attribution: how much of the frontier the
        # HBM sub-CSR actually covered (the UVA design bet)
        telemetry.counter("uva_seeds_total", tier="hot").inc(
            float(hot.sum()))
        telemetry.counter("uva_seeds_total", tier="cold").inc(
            float(len(cold_idx)))
        # hot/cold merge happens on host: this is the UVA design's one
        # deliberate sync per hop, overlapped with the host tier above
        # quiverlint: ignore[QT001]
        nbrs = np.asarray(out.nbrs).copy()   # sync point
        mask = np.asarray(out.mask).copy()   # quiverlint: ignore[QT001]
        if len(cold_idx):
            nbrs[cold_idx] = cn
            mask[cold_idx] = cm
        t = len(frontier)
        pos = (t + np.arange(t, dtype=np.int32)[:, None] * k
               + np.arange(k, dtype=np.int32)[None, :])
        blocks.append((np.where(mask, pos, 0), mask, int(fmask.sum())))
        frontier = np.concatenate(
            [frontier, np.where(mask, nbrs, 0).reshape(-1)]
        ).astype(np.int32)
        fmask = np.concatenate([fmask, mask.reshape(-1)])
    return frontier, fmask, int(fmask.sum()), blocks[::-1]
