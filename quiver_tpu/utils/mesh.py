"""Device-mesh topology helpers.

Reference parity: ``p2pCliqueTopo`` / ``init_p2p``
(``srcs/python/quiver/utils.py:7-106``, ``quiver_feature.cu:378-428``).

The reference probes pairwise ``cudaDeviceCanAccessPeer`` and colors the
access matrix into NVLink cliques.  On TPU the equivalent structure is free:
every chip in a slice is connected over ICI, and host boundaries (DCN) are
visible via ``device.process_index``.  So the "clique" of a device is the
set of devices on its ICI fabric — for feature sharding we treat each
process's local devices as the fast clique and cross-process as the DCN
tier, which is exactly how the reference splits NVLink vs NCCL tiers.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["MeshTopo", "make_mesh", "init_p2p"]


class MeshTopo:
    """ICI/DCN topology view over the available jax devices."""

    def __init__(self, devices: Optional[Sequence] = None):
        import jax

        self.devices = list(devices) if devices is not None else jax.devices()
        cliques: Dict[int, List] = {}
        for d in self.devices:
            cliques.setdefault(d.process_index, []).append(d)
        self._cliques = {i: ds for i, (_, ds) in
                         enumerate(sorted(cliques.items()))}

    @property
    def info(self) -> str:
        lines = []
        for cid, ds in self._cliques.items():
            lines.append(
                f"Clique {cid} (ICI): {[str(d) for d in ds]}"
            )
        return "\n".join(lines)

    def get_clique_id(self, device) -> int:
        for cid, ds in self._cliques.items():
            if device in ds:
                return cid
        raise KeyError(device)

    def p2p_clique(self) -> Dict[int, List]:
        return dict(self._cliques)

    @property
    def p2p_clique_device_list(self):
        return [ds for _, ds in sorted(self._cliques.items())]


def make_mesh(axis_names: Sequence[str] = ("data",),
              shape: Optional[Sequence[int]] = None,
              devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` over the given (or all) devices.

    ``shape`` defaults to all devices on the first axis.  Multi-axis shapes
    are filled major-to-minor, matching ``mesh_utils`` conventions.
    """
    import jax
    from jax.sharding import Mesh

    devs = np.array(devices if devices is not None else jax.devices())
    if shape is None:
        shape = [len(devs)] + [1] * (len(axis_names) - 1)
    return Mesh(devs.reshape(tuple(shape)), tuple(axis_names))


def init_p2p(device_list=None):
    """No-op on TPU (ICI is always on); kept for API parity."""
    return MeshTopo()
