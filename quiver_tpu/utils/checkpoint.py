"""Checkpoint / resume utilities.

The reference delegates checkpointing to user code (``torch.save`` of the
model; partition artifacts as ``.pt`` files — SURVEY.md §5).  We provide a
library-level equivalent so training scripts stay 3-line swaps: save/restore
of the :class:`quiver_tpu.parallel.TrainState` (params + optimizer state)
plus arbitrary numpy metadata, using orbax when available and a plain
npz/pickle fallback otherwise.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint", "latest_checkpoint"]


def _to_host(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def save_checkpoint(path: str, state, step: int,
                    extra: Optional[Dict[str, Any]] = None) -> str:
    """Write ``{path}/ckpt_{step}.pkl`` (host numpy pytree)."""
    os.makedirs(path, exist_ok=True)
    payload = {
        "step": int(step),
        "params": _to_host(state.params),
        "opt_state": _to_host(state.opt_state),
        "extra": extra or {},
    }
    f = os.path.join(path, f"ckpt_{step}.pkl")
    tmp = f + ".tmp"
    with open(tmp, "wb") as fh:
        pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, f)  # atomic publish
    return f


def latest_checkpoint(path: str) -> Optional[str]:
    if not os.path.isdir(path):
        return None
    cands = [f for f in os.listdir(path)
             if f.startswith("ckpt_") and f.endswith(".pkl")]
    if not cands:
        return None
    step = max(int(f[5:-4]) for f in cands)
    return os.path.join(path, f"ckpt_{step}.pkl")


def load_checkpoint(path_or_file: str, state=None):
    """Load a checkpoint; with ``state`` given, returns a new TrainState
    with restored params/opt_state (tx reused), else the raw payload."""
    f = path_or_file
    if os.path.isdir(f):
        f = latest_checkpoint(f)
        if f is None:
            raise FileNotFoundError(f"no checkpoints under {path_or_file}")
    with open(f, "rb") as fh:
        payload = pickle.load(fh)
    if state is None:
        return payload
    import jax

    from ..parallel.train import TrainState

    params = jax.tree_util.tree_map(
        lambda ref, new: np.asarray(new), state.params, payload["params"]
    )
    opt_state = jax.tree_util.tree_map(
        lambda ref, new: np.asarray(new), state.opt_state,
        payload["opt_state"]
    )
    return TrainState(params, opt_state, state.tx), payload["step"]
