"""Checkpoint / resume utilities.

The reference delegates checkpointing to user code (``torch.save`` of the
model; partition artifacts as ``.pt`` files — SURVEY.md §5).  We provide a
library-level equivalent so training scripts stay 3-line swaps: save/restore
of the :class:`quiver_tpu.parallel.TrainState` (params + optimizer state)
plus arbitrary numpy metadata.

Two backends:
  * **orbax** (default when importable — it is in the standard image):
    ``{path}/ckpt_{step}/`` in orbax's tensorstore format.  Handles sharded
    ``jax.Array`` params natively, which matters for the papers100M-scale
    multi-host configs where a pickled host copy would not even fit.
  * **pickle** fallback: ``{path}/ckpt_{step}.pkl`` host-numpy pytree.

Both publish atomically (write to a temp name, then rename).
"""

from __future__ import annotations

import os
import pickle
import shutil
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint", "latest_checkpoint"]


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:  # pragma: no cover — orbax is in the image
        return None


def _to_host(tree):
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def save_checkpoint(path: str, state, step: int,
                    extra: Optional[Dict[str, Any]] = None,
                    backend: str = "auto") -> str:
    """Write step ``step``; returns the checkpoint path.

    ``backend``: "auto" (orbax if available), "orbax", or "pickle".
    """
    assert backend in ("auto", "orbax", "pickle"), backend
    os.makedirs(path, exist_ok=True)
    ocp = _orbax() if backend in ("auto", "orbax") else None
    if backend == "orbax" and ocp is None:
        raise RuntimeError("orbax requested but not importable")
    if ocp is not None:
        f = os.path.join(os.path.abspath(path), f"ckpt_{step}")
        tmp = f + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(tmp, {
            "step": np.int64(step),
            "params": state.params,
            "opt_state": state.opt_state,
            # tensorstore holds only numeric arrays; arbitrary metadata
            # rides as a pickled byte array
            "extra_pkl": np.frombuffer(
                pickle.dumps(extra or {}), dtype=np.uint8
            ).copy(),
        })
        if os.path.exists(f):
            shutil.rmtree(f)
        os.replace(tmp, f)  # atomic publish
        return f
    payload = {
        "step": int(step),
        "params": _to_host(state.params),
        "opt_state": _to_host(state.opt_state),
        "extra": extra or {},
    }
    f = os.path.join(path, f"ckpt_{step}.pkl")
    tmp = f + ".tmp"
    with open(tmp, "wb") as fh:
        pickle.dump(payload, fh, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, f)  # atomic publish
    return f


def latest_checkpoint(path: str) -> Optional[str]:
    """Newest checkpoint under ``path`` (either backend's layout)."""
    if not os.path.isdir(path):
        return None
    best_step, best = -1, None
    for f in os.listdir(path):
        if not f.startswith("ckpt_") or f.endswith(".tmp"):
            continue
        stem = f[5:-4] if f.endswith(".pkl") else f[5:]
        try:
            step = int(stem)
        except ValueError:
            continue
        if step > best_step:
            best_step, best = step, os.path.join(path, f)
    return best


def load_checkpoint(path_or_file: str, state=None):
    """Load a checkpoint; with ``state`` given, returns
    ``(TrainState, step)`` with restored params/opt_state (tx reused),
    else the raw payload dict."""
    f = path_or_file
    if os.path.isdir(f):
        # a checkpoint ROOT contains ckpt_<step> children; an orbax LEAF
        # carries orbax's metadata marker.  Resolve by content — the
        # root's own name is irrelevant (it may itself start with ckpt_).
        resolved = latest_checkpoint(f)
        if resolved is not None:
            f = resolved
        elif not os.path.exists(os.path.join(f, "_CHECKPOINT_METADATA")):
            raise FileNotFoundError(f"no checkpoints under {path_or_file}")
    if os.path.isdir(f):  # orbax layout
        ocp = _orbax()
        if ocp is None:
            raise RuntimeError(f"{f} is an orbax checkpoint but orbax is "
                               "not importable")
        if state is not None:
            # restore with the live structure so dtypes/shardings follow
            # the running state (multi-host: shards land on their devices)
            template = {
                "step": np.int64(0),
                "params": state.params,
                "opt_state": state.opt_state,
                "extra_pkl": np.zeros(0, np.uint8),
            }
            payload = ocp.PyTreeCheckpointer().restore(f, item=template)
        else:
            payload = ocp.PyTreeCheckpointer().restore(f)
        if "extra_pkl" in payload:
            raw = np.asarray(payload.pop("extra_pkl"), dtype=np.uint8)
            payload["extra"] = (
                pickle.loads(raw.tobytes()) if raw.size else {}
            )
    else:
        with open(f, "rb") as fh:
            payload = pickle.load(fh)
    if state is None:
        return payload
    from ..parallel.train import TrainState

    return (TrainState(payload["params"], payload["opt_state"], state.tx),
            int(payload["step"]))
