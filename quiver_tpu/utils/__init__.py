from .topology import CSRTopo, coo_to_csr, parse_size, reindex_feature, reindex_by_config
from .mesh import MeshTopo, make_mesh, init_p2p
