"""Tracing / timing / debug utilities.

Reference parity: ``TRACE_SCOPE`` compile-time macros (``trace.hpp:6-13``,
enabled by ``QUIVER_ENABLE_TRACE``), the RAII ``timer`` (``timer.hpp:7-30``)
and ``show_tensor_info`` (``srcs/cpp/src/quiver/cpu/tensor.cpp:96``).

TPU-native version: spans are env-gated (``QUIVER_TPU_TRACE=1``) python
context managers that aggregate wall time per scope name (device work is
async — spans around jitted calls measure dispatch unless you pass
``block=True``), plus an optional bridge into ``jax.profiler`` traces for
XLA-level timelines.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import defaultdict
from typing import Dict

__all__ = ["trace_scope", "Timer", "trace_summary", "reset_trace",
           "show_tensor_info", "profile_trace"]

_ENABLED = os.environ.get("QUIVER_TPU_TRACE", "0") not in ("0", "", "false")
_stats = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_lock = threading.Lock()


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool):
    global _ENABLED
    _ENABLED = on


@contextlib.contextmanager
def trace_scope(name: str, block=None):
    """Aggregate wall-time span (parity: ``TRACE_SCOPE(name)``).

    ``block``: optional array/pytree to ``jax.block_until_ready`` on exit so
    the span covers device execution, not just dispatch.
    """
    if not _ENABLED:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if block is not None:
            import jax

            jax.block_until_ready(block)
        dt = time.perf_counter() - t0
        with _lock:
            s = _stats[name]
            s[0] += 1
            s[1] += dt


class Timer:
    """RAII-style wall-clock printer (parity: ``timer.hpp``)."""

    def __init__(self, name: str, printer=print):
        self.name = name
        self.printer = printer

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.printer(
            f"[timer] {self.name}: {time.perf_counter() - self.t0:.4f}s"
        )


def trace_summary() -> Dict[str, Dict[str, float]]:
    """Per-scope {count, total_s, mean_ms}."""
    with _lock:
        return {
            k: dict(count=v[0], total_s=v[1],
                    mean_ms=v[1] / max(v[0], 1) * 1e3)
            for k, v in _stats.items()
        }


def reset_trace():
    with _lock:
        _stats.clear()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """XLA-level profiler span (tensorboard-viewable)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def show_tensor_info(t, name: str = "tensor", printer=print):
    """Shape/dtype/device printer (parity: N15 ``show_tensor_info``)."""
    import numpy as np

    try:
        devs = getattr(t, "devices", None)
        dev = list(devs()) if callable(devs) else None
    except Exception:
        dev = None
    printer(
        f"{name}: shape={tuple(t.shape)} dtype={t.dtype}"
        + (f" devices={dev}" if dev else "")
    )
    return t
