"""Tracing / timing / debug utilities — back-compat shim.

Reference parity: ``TRACE_SCOPE`` compile-time macros (``trace.hpp:6-13``,
enabled by ``QUIVER_ENABLE_TRACE``), the RAII ``timer`` (``timer.hpp:7-30``)
and ``show_tensor_info`` (``srcs/cpp/src/quiver/cpu/tensor.cpp:96``).

The span machinery itself now lives in :mod:`quiver_tpu.telemetry.spans`;
this module keeps the historical API (``trace_scope`` / ``Timer`` /
``trace_summary`` / ``reset_trace``, env-gated by ``QUIVER_TPU_TRACE=1``)
and delegates to the process-wide :class:`~quiver_tpu.telemetry.SpanTracer`
so old call sites and the new instrumentation aggregate into ONE place.
Device work is async — spans around jitted calls measure dispatch unless
you pass ``block=`` an array (or list of arrays) to block on.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Dict

from .. import telemetry as _telemetry

__all__ = ["trace_scope", "Timer", "trace_summary", "reset_trace",
           "show_tensor_info", "profile_trace"]

_ENABLED = os.environ.get("QUIVER_TPU_TRACE", "0") not in ("0", "", "false")


def _tracer():
    # the REAL tracer, not the noop: this module has its own gate
    # (QUIVER_TPU_TRACE) predating the QUIVER_TELEMETRY switch, and its
    # tested contract is "set_enabled(True) => spans aggregate".
    return _telemetry._tracer


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool):
    global _ENABLED
    _ENABLED = on
    # QUIVER_TPU_TRACE historically meant "record spans"; in the new
    # subsystem that maps to Chrome-trace event retention as well.
    _tracer().set_tracing(bool(on))


def trace_scope(name: str, block=None):
    """Aggregate wall-time span (parity: ``TRACE_SCOPE(name)``).

    ``block``: optional array (or list/tuple of arrays) to
    ``block_until_ready`` on exit so the span covers device execution,
    not just dispatch.
    """
    if not _ENABLED:
        return contextlib.nullcontext()
    return _tracer().span(name, block=block)


class Timer:
    """RAII-style wall-clock printer (parity: ``timer.hpp``)."""

    def __init__(self, name: str, printer=print):
        self.name = name
        self.printer = printer

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.printer(
            f"[timer] {self.name}: {time.perf_counter() - self.t0:.4f}s"
        )


def trace_summary() -> Dict[str, Dict[str, float]]:
    """Per-scope {count, total_s, mean_ms}."""
    return _tracer().summary()


def reset_trace():
    _tracer().reset()


_PROFILE_WARNED = False


def _warn_profile_once(msg: str):
    global _PROFILE_WARNED
    if not _PROFILE_WARNED:
        _PROFILE_WARNED = True
        import sys

        print(f"[quiver_tpu] {msg}", file=sys.stderr)


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """XLA-level profiler span (tensorboard-viewable).

    Best effort: when the profiler cannot start (no ``jax.profiler``,
    another trace already live, unwritable ``log_dir``) the span
    degrades to a no-op with ONE stderr warning per process —
    a perf-investigation flag must never take the workload down.
    ``stop_trace`` is only called for a trace this span started.
    """
    started = False
    jax = None
    try:
        import jax

        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:
        _warn_profile_once(
            f"XLA profiler unavailable ({e!r}); profile_trace is a no-op")
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                _warn_profile_once(f"XLA profiler stop failed ({e!r})")


def show_tensor_info(t, name: str = "tensor", printer=print):
    """Shape/dtype/device printer (parity: N15 ``show_tensor_info``)."""
    try:
        devs = getattr(t, "devices", None)
        dev = list(devs()) if callable(devs) else None
    except Exception:
        dev = None
    printer(
        f"{name}: shape={tuple(t.shape)} dtype={t.dtype}"
        + (f" devices={dev}" if dev else "")
    )
    return t
