"""Synthetic graph generators for benchmarks/examples/tests.

The reference benches on OGB datasets; in no-egress environments we
generate graphs with matching scale and degree skew (lognormal ≈ the
power-law-ish degree profile of products/reddit).  Centralizes the logic
duplicated across bench/example scripts.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .topology import CSRTopo

__all__ = ["synthetic_csr", "synthetic_products", "synthetic_reddit",
           "community_graph"]


def synthetic_csr(n_nodes: int, n_edges: int, seed: int = 0,
                  sigma: float = 1.0) -> Tuple[np.ndarray, np.ndarray]:
    """Degree-skewed random CSR; returns (indptr, indices)."""
    rng = np.random.default_rng(seed)
    raw = rng.lognormal(mean=3.0, sigma=sigma, size=n_nodes)
    deg = np.maximum(raw / raw.sum() * n_edges, 1).astype(np.int64)
    indptr = np.zeros(n_nodes + 1, dtype=np.int64)
    np.cumsum(deg, out=indptr[1:])
    e = int(indptr[-1])
    indices = rng.integers(0, n_nodes, size=e, dtype=np.int32)
    return indptr, indices


def synthetic_products(seed: int = 0) -> CSRTopo:
    """ogbn-products scale: 2.45M nodes, ~123M edges."""
    indptr, indices = synthetic_csr(2_449_029, 123_718_280, seed)
    return CSRTopo(indptr=indptr, indices=indices)


def synthetic_reddit(seed: int = 0) -> CSRTopo:
    """Reddit scale: 233K nodes, ~11.6M edges."""
    indptr, indices = synthetic_csr(232_965, 11_606_919, seed)
    return CSRTopo(indptr=indptr, indices=indices)


def community_graph(n_nodes: int, n_classes: int, intra_deg: int = 6,
                    inter_deg: int = 2, noise: float = 0.3,
                    feat_extra: int = 0, seed: int = 0):
    """SBM-ish learnable graph: features = class one-hot + noise.

    Returns (CSRTopo, features [N, n_classes+feat_extra], labels [N]).
    Used wherever a loss must demonstrably decrease.
    """
    rng = np.random.default_rng(seed)
    comm = rng.integers(0, n_classes, n_nodes)
    order = np.argsort(comm, kind="stable")
    # class -> contiguous slice for O(1) intra sampling
    bounds = np.searchsorted(comm[order], np.arange(n_classes + 1))
    k = intra_deg + inter_deg
    src = np.repeat(np.arange(n_nodes), k)
    dst = np.empty(n_nodes * k, dtype=np.int64)
    # vectorized intra draws: uniform position inside each node's own
    # class slice (fully vectorized so products-scale graphs build in
    # seconds, not minutes)
    lo = bounds[comm]
    hi = np.maximum(bounds[comm + 1], lo + 1)
    u = rng.random((n_nodes, intra_deg))
    intra = order[(lo[:, None] + u * (hi - lo)[:, None]).astype(np.int64)]
    inter = rng.integers(0, n_nodes, (n_nodes, inter_deg))
    dst.reshape(n_nodes, k)[:, :intra_deg] = intra
    dst.reshape(n_nodes, k)[:, intra_deg:] = inter
    topo = CSRTopo(edge_index=np.stack([src, dst]), node_count=n_nodes)
    feat = np.eye(n_classes, dtype=np.float32)[comm]
    feat += rng.normal(0, noise, feat.shape).astype(np.float32)
    if feat_extra:
        feat = np.concatenate(
            [feat, rng.normal(0, noise, (n_nodes, feat_extra))
             .astype(np.float32)], axis=1,
        )
    return topo, feat, comm.astype(np.int32)
