"""Graph topology containers for the TPU-native quiver rebuild.

Reference parity: ``srcs/python/quiver/utils.py:119-225`` (``CSRTopo``),
``utils.py:229-247`` (``reindex_by_config`` / ``reindex_feature``),
``utils.py:259-280`` (``parse_size``).

Design notes (TPU-first):
  * The canonical storage is a pair of **numpy** arrays (``indptr``,
    ``indices``) on host; device placement is explicit via
    :meth:`CSRTopo.to_device`, which returns jax Arrays in HBM.  There is no
    UVA / pinned-memory mode: the TPU analogue of "graph bigger than device
    memory" is sharding the edge array across a mesh (see
    ``quiver_tpu.dist``) or keeping the topology on host and sampling there
    (CPU mode, ``quiver_tpu.cpp``).
  * ``indices`` is int32 (node ids), ``indptr`` is int64 on host. For the
    on-device path we require ``edge_count < 2**31`` per *shard* so indptr
    fits int32 (XLA default); larger graphs must be sharded, which is also
    what the bandwidth math wants.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "CSRTopo",
    "coo_to_csr",
    "parse_size",
    "reindex_feature",
    "reindex_by_config",
    "UNITS",
]


def coo_to_csr(
    src: np.ndarray, dst: np.ndarray, node_count: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COO edge list -> CSR (indptr, indices, eid), rows are ``src``.

    Replaces the reference's scipy ``csr_matrix`` detour
    (``utils.py:109-116``) and the GPU zip/sort path
    (``quiver_sample.cu:463-497``) with a single stable counting sort.
    Returns ``eid`` (the permutation of input edge positions) so edge
    features can follow the reorder.
    """
    src = np.asarray(src)
    dst = np.asarray(dst)
    if node_count is None:
        node_count = int(max(src.max(), dst.max())) + 1 if src.size else 0
    counts = np.bincount(src, minlength=node_count).astype(np.int64)
    indptr = np.zeros(node_count + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    # Stable sort by src keeps each row's neighbors in input order.
    eid = np.argsort(src, kind="stable").astype(np.int64)
    indices = dst[eid].astype(np.int32)
    return indptr, indices, eid


class CSRTopo:
    """Graph topology in CSR format (host-resident numpy).

    ``CSRTopo(edge_index=...)`` or ``CSRTopo(indptr=..., indices=...)``,
    mirroring the reference API (``utils.py:119-152``).  ``edge_index`` is a
    ``[2, E]`` array-like of (src, dst).
    """

    def __init__(self, edge_index=None, indptr=None, indices=None, eid=None,
                 node_count: Optional[int] = None):
        if edge_index is not None:
            edge_index = np.asarray(edge_index)
            self.indptr_, self.indices_, self.eid_ = coo_to_csr(
                edge_index[0], edge_index[1], node_count
            )
            if eid is not None:
                self.eid_ = np.asarray(eid)[self.eid_]
        elif indptr is not None and indices is not None:
            self.indptr_ = np.asarray(indptr, dtype=np.int64)
            self.indices_ = np.asarray(indices, dtype=np.int32)
            self.eid_ = None if eid is None else np.asarray(eid)
        else:
            raise ValueError("need edge_index or (indptr, indices)")
        self.feature_order_: Optional[np.ndarray] = None
        # device placements keyed by device (None = default device) — a
        # dict, not a single slot, so to_device(devA) after to_device(devB)
        # returns arrays on the device actually asked for
        self._device_arrays: dict = {}
        self._version = 0

    @property
    def indptr(self) -> np.ndarray:
        return self.indptr_

    @property
    def indices(self) -> np.ndarray:
        return self.indices_

    @property
    def eid(self):
        return self.eid_

    @property
    def feature_order(self):
        return self.feature_order_

    @feature_order.setter
    def feature_order(self, feature_order):
        self.feature_order_ = (
            None if feature_order is None else np.asarray(feature_order)
        )

    @property
    def degree(self) -> np.ndarray:
        return self.indptr[1:] - self.indptr[:-1]

    @property
    def node_count(self) -> int:
        return int(self.indptr_.shape[0] - 1)

    @property
    def edge_count(self) -> int:
        return int(self.indices_.shape[0])

    def to_device(self, device=None):
        """Place (indptr, indices) in device HBM as int32 jax Arrays.

        Both arrays are zero-padded to a multiple of 128 so the fast
        lane-select gather (``ops.fastgather``) can view them as
        ``[rows, 128]`` with a free in-jit reshape.  Padding is harmless to
        the XLA-take path (real entries come first; callers never index
        past ``node_count``/``edge_count``).  Requires
        ``edge_count < 2**31``; larger graphs shard over the mesh.  Cached
        per device; :meth:`invalidate` drops every cached placement.
        """
        import jax
        import jax.numpy as jnp

        cache_key = device
        cached = self._device_arrays.get(cache_key)
        if cached is None:
            if self.edge_count >= 2**31:
                raise ValueError(
                    "edge_count >= 2^31: shard the graph (quiver_tpu.dist) "
                    "instead of single-device placement"
                )

            def pad128(a):
                # multiple of 128, and never empty (edge-less graphs must
                # still produce a gatherable device array)
                target = max(((len(a) + 127) // 128) * 128, 128)
                if target != len(a):
                    a = np.concatenate(
                        [a, np.zeros(target - len(a), a.dtype)]
                    )
                return a

            indptr = jnp.asarray(pad128(self.indptr_.astype(np.int32)))
            indices = jnp.asarray(pad128(self.indices_.astype(np.int32)))
            if device is not None:
                indptr = jax.device_put(indptr, device)
                indices = jax.device_put(indices, device)
            cached = (indptr, indices)
            self._device_arrays[cache_key] = cached
        return cached

    @property
    def version(self) -> int:
        """Bumped by :meth:`invalidate`; lets holders of device arrays
        detect that their copy predates a topology swap."""
        return self._version

    def invalidate(self):
        """Drop all cached device placements and bump :attr:`version`.

        Must be called after mutating ``indptr_``/``indices_`` in place (the
        stream compactor swaps whole arrays instead, but either way a stale
        ``to_device`` result would silently serve the old topology).
        """
        self._device_arrays = {}
        self._version += 1

    def share_memory_(self):  # torch-API compat: numpy arrays already share
        return self

    def __repr__(self):
        return (
            f"CSRTopo(nodes={self.node_count}, edges={self.edge_count})"
        )


def reindex_by_config(adj_csr: CSRTopo, graph_feature, gpu_portion: float,
                      seed: int = 0):
    """Degree-descending reorder with a shuffled hot prefix.

    Parity with ``utils.py:229-242``: sorts nodes by degree (descending),
    randomly permutes the top ``gpu_portion`` slice (so cache-resident rows
    are load-balanced when later range-sharded), and returns the permuted
    feature plus ``new_order`` mapping old id -> new row.
    """
    node_count = adj_csr.node_count
    hot = int(node_count * gpu_portion)
    degree = adj_csr.degree
    prev_order = np.argsort(-degree, kind="stable")
    rng = np.random.default_rng(seed)
    prev_order[:hot] = prev_order[rng.permutation(hot)]
    new_order = np.empty(node_count, dtype=np.int64)
    new_order[prev_order] = np.arange(node_count, dtype=np.int64)
    graph_feature = np.asarray(graph_feature)[prev_order]
    return graph_feature, new_order


def reindex_feature(graph: CSRTopo, feature, ratio: float, seed: int = 0):
    assert isinstance(graph, CSRTopo), "Input graph should be CSRTopo object"
    return reindex_by_config(graph, feature, ratio, seed=seed)


UNITS = {
    "KB": 2**10, "MB": 2**20, "GB": 2**30,
    "K": 2**10, "M": 2**20, "G": 2**30,
}


def parse_size(sz) -> int:
    """'200M' / '1.5GB' / int / float -> bytes (``utils.py:259-280``)."""
    if isinstance(sz, int):
        return sz
    if isinstance(sz, float):
        return int(sz)
    if isinstance(sz, str):
        s = sz.upper().strip()
        for suf in sorted(UNITS, key=len, reverse=True):
            if s.endswith(suf):
                return int(float(s[: -len(suf)]) * UNITS[suf])
        if s.isdigit():
            return int(s)
    raise ValueError(f"invalid size: {sz!r}")
