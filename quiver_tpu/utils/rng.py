"""PRNG key helper — TPU-measured RNG implementation selection.

Round-2 on-chip measurements (docs/TPU_MEASUREMENTS.md) overturned the
round-1 hypothesis that threefry's unrolled HLO caused the sampler
compile hang — that was a tunnel outage artifact.  On a real v5e the
3-hop pipeline steady-state is threefry 237 ms/batch vs rbg 1866 ms/batch
(uniform-heavy path, gather_mode="xla"): XLA's RngBitGenerator lowering
is the SLOW one at sampling's draw volumes.  Default is therefore
threefry2x32 everywhere — reproducible streams, fast steady-state; the
hot sampler additionally bypasses per-draw key RNG entirely via
``sample_rng="hash"`` (counter-hash uniforms, ``ops/sample.py``), so keys
only feed cheap split/fold_in.

The reference's analogue is per-thread curand Philox
(``cuda_random.cu.hpp:12-20``) — likewise a counter hash.
"""

from __future__ import annotations

__all__ = ["make_key", "default_impl"]


def default_impl() -> str:
    """Default PRNG impl; ``QUIVER_TPU_PRNG`` overrides."""
    import os

    return os.environ.get("QUIVER_TPU_PRNG") or "threefry2x32"


def make_key(seed: int = 0, impl: str | None = None):
    """A ``jax.random`` key using the backend-appropriate implementation.

    Pass ``impl="threefry2x32"`` to force reproducible keys on TPU, or set
    ``QUIVER_TPU_PRNG=threefry2x32|rbg`` to override globally.
    """
    import jax

    return jax.random.key(seed, impl=impl or default_impl())
