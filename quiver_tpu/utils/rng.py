"""PRNG key helper — TPU-friendly RNG implementation selection.

JAX's default threefry2x32 PRNG lowers to a large unrolled HLO per draw;
on TPU that costs both compile time (measured: the dominant term in the
sampler pipeline's first-call latency over the axon tunnel) and runtime
(software hashing on the VPU).  The TPU hardware path is XLA's
``RngBitGenerator`` (``impl="rbg"``), which compiles to a single op.

The reference faces the same trade on GPU and picks the hardware-ish
answer too: per-thread curand Philox states (``cuda_random.cu.hpp:12-20``),
not a counter-based pure RNG.  ``make_key`` mirrors that: hardware RNG on
accelerators, reproducible threefry on CPU (tests).

Sampling uses RNG only to pick neighbor subsets — cryptographic stream
quality is irrelevant; rbg's weaker cross-shard independence guarantees
are fine.
"""

from __future__ import annotations

__all__ = ["make_key", "default_impl"]


def default_impl() -> str:
    """Backend-appropriate PRNG impl; ``QUIVER_TPU_PRNG`` overrides."""
    import os

    import jax

    env = os.environ.get("QUIVER_TPU_PRNG")
    if env:
        return env
    return "rbg" if jax.default_backend() not in ("cpu",) else "threefry2x32"


def make_key(seed: int = 0, impl: str | None = None):
    """A ``jax.random`` key using the backend-appropriate implementation.

    Pass ``impl="threefry2x32"`` to force reproducible keys on TPU, or set
    ``QUIVER_TPU_PRNG=threefry2x32|rbg`` to override globally.
    """
    import jax

    return jax.random.key(seed, impl=impl or default_impl())
