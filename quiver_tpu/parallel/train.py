"""Data-parallel training utilities.

Reference parity: the reference leaves the training loop to user PyG code
with ``DistributedDataParallel`` (e.g. ``examples/multi_gpu/pyg/
ogb-products/dist_sampling_ogb_products_quiver.py:82-160``).  We provide the
TPU-idiomatic equivalent so examples stay 3-line swaps: a jitted train step
whose batch is sharded over the mesh's data axis and whose gradients are
averaged by XLA (``NamedSharding`` on inputs does what DDP's NCCL allreduce
did — no wrapper class needed).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["TrainState", "make_train_step", "shard_batch", "replicate"]


class TrainState:
    """Minimal train state (params + opt state), pytree-registered."""

    def __init__(self, params, opt_state, tx):
        self.params = params
        self.opt_state = opt_state
        self.tx = tx

    def tree_flatten(self):
        return (self.params, self.opt_state), self.tx

    @classmethod
    def tree_unflatten(cls, tx, children):
        return cls(children[0], children[1], tx)

    @classmethod
    def create(cls, params, tx):
        return cls(params, tx.init(params), tx)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


def make_train_step(apply_fn: Callable, tx: optax.GradientTransformation,
                    loss_fn: Optional[Callable] = None,
                    mesh: Optional[Mesh] = None, data_axis: str = "data"):
    """Build a jitted ``(state, x, blocks, labels, label_mask, key) -> (state,
    loss)`` step.

    With ``mesh`` given, inputs are expected sharded over ``data_axis``
    (leading dim); params replicated.  XLA inserts the gradient psum —
    the DDP equivalent.
    """
    if loss_fn is None:
        def loss_fn(logits, labels, mask):
            ls = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            )
            m = mask.astype(ls.dtype)
            return (ls * m).sum() / jnp.maximum(m.sum(), 1.0)

    def apply_and_loss(params, x, blocks, labels, label_mask, key):
        logits = apply_fn(params, x, blocks, train=True,
                          rngs={"dropout": key})
        return loss_fn(logits, labels, label_mask)

    def step(state: TrainState, x, blocks, labels, label_mask, key):
        loss, grads = jax.value_and_grad(apply_and_loss)(
            state.params, x, blocks, labels, label_mask, key
        )
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.tx), loss

    if mesh is None:
        # donate the state: params/opt_state buffers update in place on
        # device instead of being copied every step
        return jax.jit(step, donate_argnums=(0,))

    # Data-parallel variant: the batch pytree is STACKED on a leading
    # replica axis of size mesh.shape[data_axis] (each replica sampled its
    # own seeds, so frontiers are per-replica — the GNN analogue of DDP's
    # per-rank batch).  vmap over the replica axis + sharded inputs makes
    # XLA place one replica per device and psum the gradients.
    ndev = int(mesh.shape[data_axis])

    def dp_step(state: TrainState, x, blocks, labels, label_mask, key):
        keys = jax.random.split(key, ndev)

        def compute(params):
            losses = jax.vmap(
                lambda xx, bb, ll, mm, kk: apply_and_loss(
                    params, xx, bb, ll, mm, kk
                )
            )(x, blocks, labels, label_mask, keys)
            return losses.mean()

        loss, grads = jax.value_and_grad(compute)(state.params)
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.tx), loss

    repl = NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(data_axis))
    return jax.jit(
        dp_step,
        donate_argnums=(0,),
        in_shardings=(repl, data, data, data, data, repl),
        out_shardings=(repl, repl),
    )


def shard_batch(mesh: Mesh, tree, data_axis: str = "data"):
    """Put a host batch onto the mesh, sharded on the leading dim."""
    sh = NamedSharding(mesh, P(data_axis))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)


def replicate(mesh: Mesh, tree):
    sh = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)
