"""Async sampling pipeline — prefetch sample+gather ahead of the train step.

Reference parity: ``AsyncCudaNeighborSampler``
(``srcs/python/quiver/async_cuda_sampler.py:24-58``) and the stream-pool
async launches (``stream_pool.hpp``, ``algorithm.cu.hpp``).  On TPU the
device work is already async (XLA dispatch returns immediately); what needs
overlapping is the *host* side — seed generation, feature cold-tail gather,
numpy staging.  ``Prefetcher`` runs those on a worker thread with a bounded
queue, so the accelerator never waits on the host.
"""

from __future__ import annotations

import contextvars
import queue
import threading
from typing import Callable, Iterable, Iterator

from ..resilience.shutdown import join_and_reap

__all__ = ["Prefetcher", "AsyncNeighborSampler", "AsyncCudaNeighborSampler"]

_END = object()


class Prefetcher:
    """Wrap a batch-producing callable over an index iterable.

    ``make_batch(item)`` runs on the worker thread (sample + gather +
    device_put); consumers iterate finished batches.  :meth:`stop`
    terminates an in-flight iteration from any thread — the worker's
    bounded put and the consumer's get are both shutdown-aware, so a
    wedged consumer (stopped draining, never closed the generator)
    cannot deadlock the worker against the full queue.
    """

    def __init__(self, items: Iterable, make_batch: Callable, depth: int = 2):
        self.items = list(items)
        self.make_batch = make_batch
        self.depth = depth
        self._stop: "threading.Event" = threading.Event()
        self._thread = None

    def __len__(self):
        return len(self.items)

    def stop(self) -> None:
        """Request shutdown of the current iteration (idempotent, safe
        from any thread).  The worker exits its put loop within one
        timeout tick; a consumer blocked in get() exits on its next."""
        self._stop.set()

    def __iter__(self) -> Iterator:
        q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        exc = []
        stop = self._stop = threading.Event()
        # snapshot the consumer's context (flight-recorder trace, etc.)
        # so worker-side batch building attributes to whoever started
        # the iteration — threads do not inherit contextvars.  Sequential
        # cvctx.run calls are safe: one worker, one context.
        cvctx = contextvars.copy_context()

        def _put_interruptible(item) -> bool:
            # shutdown-aware bounded put: a consumer that abandons
            # iteration early (break / exception) or an external stop()
            # ends the wait; a plain q.put would block this worker
            # forever on the full bounded queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for it in self.items:
                    if stop.is_set():
                        return
                    if not _put_interruptible(cvctx.run(self.make_batch, it)):
                        return
            except BaseException as e:
                # surfaced on the consumer side: __iter__ re-raises
                # exc[0] after the join below
                exc.append(e)
            finally:
                _put_interruptible(_END)

        t = threading.Thread(target=worker, daemon=True)
        self._thread = t
        t.start()
        try:
            while True:
                try:
                    out = q.get(timeout=0.2)
                except queue.Empty:
                    # stopped AND worker gone: no _END is coming (its
                    # put was interrupted) — exit instead of waiting
                    if stop.is_set() and not t.is_alive():
                        break
                    continue
                if out is _END:
                    break
                yield out
        finally:
            stop.set()
            join_and_reap([t], timeout=5.0, component="prefetcher")
        if exc:
            raise exc[0]


class AsyncNeighborSampler:
    """One-hop async sampler (API parity with P16).

    ``sample_async(seeds)`` dispatches a jitted one-hop sample and returns
    immediately (jax arrays are futures); ``.result()``-style blocking is a
    ``block_until_ready`` away.
    """

    def __init__(self, csr_topo, k: int, device=None):
        from ..sampler import GraphSageSampler

        self._s = GraphSageSampler(csr_topo, [k], device=device)
        self.k = k

    def sample_async(self, seeds, key=None):
        return self._s.sample_layer(seeds, self.k, key=key)

    def sample(self, seeds, key=None):
        out = self.sample_async(seeds, key=key)
        import jax

        jax.block_until_ready(out)
        return out


# reference-name alias (P16, ``async_cuda_sampler.py``): same role, no CUDA
AsyncCudaNeighborSampler = AsyncNeighborSampler
