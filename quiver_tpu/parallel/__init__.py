from .train import TrainState, make_train_step, shard_batch, replicate
from .prefetch import Prefetcher, AsyncNeighborSampler
