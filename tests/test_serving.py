"""Serving pipeline tests: batcher routing, hybrid sampling, end-to-end
inference with latency stats (parity: reference serving.py behavior)."""

import queue
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu import (
    CSRTopo, Feature, GraphSageSampler, RequestBatcher, HybridSampler,
    InferenceServer, InferenceServer_Debug, generate_neighbour_num,
)
from quiver_tpu.serving import ServingRequest
from quiver_tpu.models import GraphSAGE


def test_batcher_routing(small_graph):
    nn_num = generate_neighbour_num(small_graph, [4, 3], mode="expected")
    q = queue.Queue()
    rb = RequestBatcher([q], neighbour_num=nn_num,
                        threshold=float(np.median(nn_num) * 4),
                        mode="Auto").start()
    deg = small_graph.degree
    light = np.argsort(deg)[:2]          # low-degree -> CPU lane
    heavy = np.argsort(deg)[-16:]        # high-degree batch -> TPU lane
    q.put(ServingRequest(ids=light, client=0, seq=0))
    q.put(ServingRequest(ids=heavy, client=0, seq=1))
    time.sleep(0.3)
    rb.stop()
    cpu_items, dev_items = [], []
    while not rb.cpu_batched_queue.empty():
        it = rb.cpu_batched_queue.get()
        if isinstance(it, ServingRequest):
            cpu_items.append(it)
    while not rb.device_batched_queue.empty():
        it = rb.device_batched_queue.get()
        if isinstance(it, ServingRequest):
            dev_items.append(it)
    assert len(cpu_items) == 1 and cpu_items[0].seq == 0
    assert len(dev_items) == 1 and dev_items[0].seq == 1


def test_end_to_end_serving(small_graph, rng):
    n = small_graph.node_count
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sizes = [4, 3]
    tpu_sampler = GraphSageSampler(small_graph, sizes)
    cpu_sampler = GraphSageSampler(small_graph, sizes, mode="CPU")
    model = GraphSAGE(hidden=16, out_dim=3, num_layers=2, dropout=0.0)
    seeds0 = np.arange(8, dtype=np.int64)
    b0 = tpu_sampler.sample(seeds0)
    x0 = feature[np.asarray(b0.n_id)]
    params = model.init(jax.random.PRNGKey(0), x0, b0.layers)
    apply_fn = jax.jit(
        lambda p, x, blocks: model.apply(p, x, blocks)
    )

    nn_num = generate_neighbour_num(small_graph, sizes, mode="expected")
    stream = queue.Queue()
    rb = RequestBatcher([stream], neighbour_num=nn_num,
                        threshold=float(np.percentile(nn_num, 50) * 8),
                        mode="Auto").start()
    hs = HybridSampler(cpu_sampler, rb.cpu_batched_queue,
                       num_workers=2).start()
    server = InferenceServer_Debug(
        tpu_sampler, feature, apply_fn, params,
        rb.device_batched_queue, hs.sampled_queue,
    ).start()

    n_req = 12
    for i in range(n_req):
        ids = rng.integers(0, n, rng.integers(1, 16))
        stream.put(ServingRequest(ids=ids, client=0, seq=i))

    results = []
    for _ in range(n_req):
        results.append(server.result_queue.get(timeout=60))
    rb.stop()
    hs.stop()
    server.stop()

    assert len(results) == n_req
    for req, out in results:
        assert out.shape == (len(req.ids), 3)
        assert np.isfinite(out).all()
    stats = server.stats()
    assert stats["count"] == n_req
    assert stats["p99_latency_ms"] >= stats["p50_latency_ms"]
    assert stats["throughput_rps"] > 0


def test_preparation_mode_duplicates(small_graph):
    q = queue.Queue()
    rb = RequestBatcher([q], mode="Preparation").start()
    q.put(ServingRequest(ids=np.array([1, 2]), client=0, seq=0))
    time.sleep(0.2)
    rb.stop()
    assert isinstance(rb.cpu_batched_queue.get_nowait(), ServingRequest)
    assert isinstance(rb.device_batched_queue.get_nowait(), ServingRequest)


def test_server_lane_survives_errors(small_graph, rng):
    """A poisoned request yields an error result; later requests still
    serve (the reference's loops would have died — serving.py:198)."""
    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [3])
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0), feature[np.asarray(b0.n_id)],
                        b0.layers)

    calls = {"n": 0}

    def apply_fn(p, x, blocks):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("boom")
        return model.apply(p, x, blocks)

    dq = queue.Queue()
    server = InferenceServer(sampler, feature, apply_fn, params, dq,
                             max_coalesce=1).start()
    dq.put(ServingRequest(ids=np.array([1, 2, 3]), client=0, seq=0))
    dq.put(ServingRequest(ids=np.array([4, 5]), client=0, seq=1))
    r0 = server.result_queue.get(timeout=60)
    r1 = server.result_queue.get(timeout=60)
    server.stop()
    outs = {r0[0].seq: r0[1], r1[0].seq: r1[1]}
    assert isinstance(outs[0], RuntimeError)
    assert outs[1].shape == (2, 2)


def test_device_lane_coalesces(small_graph, rng):
    """Multiple queued requests share one forward pass; outputs split
    correctly per request."""
    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [3])
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    forwards = {"n": 0}

    def apply_fn(p, x, blocks):
        forwards["n"] += 1
        return model.apply(p, x, blocks)

    dq = queue.Queue()
    # enqueue BEFORE starting so the loop sees a full queue to coalesce
    sizes = [3, 5, 2, 4]
    for i, s in enumerate(sizes):
        dq.put(ServingRequest(ids=rng.integers(0, n, s), client=0, seq=i))
    server = InferenceServer(sampler, feature, apply_fn, params, dq,
                             max_coalesce=8).start()
    got = {}
    for _ in sizes:
        req, out = server.result_queue.get(timeout=60)
        got[req.seq] = out
    server.stop()
    assert forwards["n"] < len(sizes)  # coalescing happened
    for i, s in enumerate(sizes):
        assert got[i].shape == (s, 2)


def test_calibrate_threshold(small_graph, rng):
    from quiver_tpu.serving import calibrate_threshold
    from quiver_tpu import generate_neighbour_num

    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    tpu_s = GraphSageSampler(small_graph, [3])
    cpu_s = GraphSageSampler(small_graph, [3], mode="CPU")
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = tpu_s.sample(np.arange(4, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    apply_fn = jax.jit(lambda p, x, blocks: model.apply(p, x, blocks))
    nn_num = generate_neighbour_num(small_graph, [3], mode="expected")
    thr = calibrate_threshold(tpu_s, cpu_s, feature, apply_fn, params,
                              nn_num, n, trials=2, sizes=(1, 8))
    assert thr >= 0.0


def test_oversized_request_served(small_graph, rng):
    """Requests above the top bucket run unpadded instead of crashing."""
    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [3])
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    apply_fn = lambda p, x, blocks: model.apply(p, x, blocks)
    dq = queue.Queue()
    server = InferenceServer(sampler, feature, apply_fn, params, dq,
                             max_coalesce=1).start()
    big = rng.integers(0, n, InferenceServer.BUCKETS[-1] + 100)
    dq.put(ServingRequest(ids=big, client=0, seq=0))
    req, out = server.result_queue.get(timeout=120)
    server.stop()
    assert not isinstance(out, Exception), out
    assert out.shape == (len(big), 2)


def test_warmup_then_zero_recompiles(small_graph, rng):
    """After warmup(), a mixed-size request storm — including sizes above
    the top bucket — triggers ZERO new traces (VERDICT next #4)."""
    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [3])
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)

    traces = []

    @jax.jit
    def apply_fn(p, x, blocks):
        traces.append(x.shape)  # python body runs only when (re)tracing
        return model.apply(p, x, blocks)

    dq = queue.Queue()
    srv_sampler = GraphSageSampler(small_graph, [3])
    server = InferenceServer(srv_sampler, feature, apply_fn, params, dq,
                             max_coalesce=1, fused=False)
    server.BUCKETS = (4, 8, 16)
    sampler_builds = []
    orig_build = srv_sampler._build_jit
    srv_sampler._build_jit = lambda B: (sampler_builds.append(B),
                                        orig_build(B))[1]
    server.warmup()
    assert sorted(sampler_builds) == [4, 8, 16]
    n_traces = len(traces)
    assert n_traces == 3

    server.start()
    sizes = [1, 2, 3, 4, 5, 7, 8, 9, 15, 16, 17, 20, 35, 40, 100]
    for i, sz in enumerate(sizes):
        dq.put(ServingRequest(ids=rng.integers(0, n, sz), client=0, seq=i))
    outs = {}
    for _ in sizes:
        req, out = server.result_queue.get(timeout=120)
        assert not isinstance(out, Exception), out
        outs[req.seq] = out
    server.stop()
    for i, sz in enumerate(sizes):
        assert outs[i].shape == (sz, 2)
    # the storm hit only pre-warmed executables
    assert len(traces) == n_traces, f"recompiled: {traces[n_traces:]}"
    assert sorted(set(sampler_builds)) == [4, 8, 16]


def test_fused_device_lane(small_graph, rng):
    """Fully-cached feature auto-enables the fused one-jit lane; results
    match the unfused path's shape/correctness and one executable exists
    per bucket after warmup."""
    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [3])
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    apply_fn = lambda p, x, blocks: model.apply(p, x, blocks)
    dq = queue.Queue()
    server = InferenceServer(GraphSageSampler(small_graph, [3]), feature,
                             apply_fn, params, dq, max_coalesce=1)
    assert server._fused  # auto-on: feature fully HBM-resident
    server.BUCKETS = (4, 8)
    server.warmup()
    assert sorted(server._fused_fns) == [4, 8]
    server.start()
    for i, sz in enumerate([2, 5, 7, 20]):
        dq.put(ServingRequest(ids=rng.integers(0, n, sz), client=0, seq=i))
    outs = {}
    for _ in range(4):
        req, out = server.result_queue.get(timeout=60)
        assert not isinstance(out, Exception), out
        outs[req.seq] = out
    server.stop()
    for i, sz in enumerate([2, 5, 7, 20]):
        assert outs[i].shape == (sz, 2)
    assert sorted(server._fused_fns) == [4, 8]  # storm added none


def test_hybrid_sampler_buckets_cpu_lane(small_graph):
    """CPU-lane batches arrive bucket-shaped: the device forward sees
    only |buckets| distinct shapes regardless of request sizes."""
    cpu_sampler = GraphSageSampler(small_graph, [3], mode="CPU")
    inq = queue.Queue()
    hs = HybridSampler(cpu_sampler, inq, num_workers=1,
                       buckets=(4, 8)).start()
    for i, sz in enumerate([1, 3, 5, 8, 11]):
        inq.put(ServingRequest(ids=np.arange(sz), client=0, seq=i))
    shapes = {}
    for _ in range(5):
        req, batch, dt = hs.sampled_queue.get(timeout=30)
        shapes[req.seq] = batch.n_id.shape[0]
    hs.stop()
    # sizes 1,3 -> bucket 4; 5,8 -> bucket 8; 11 -> above top: as-is
    frontier = lambda b: b + b * 3
    assert shapes[0] == shapes[1] == frontier(4)
    assert shapes[2] == shapes[3] == frontier(8)
    assert shapes[4] == frontier(11)


def test_fit_crossover_robust_to_noise():
    """The threshold fit must not be dragged up by one lucky CPU sample
    past the crossover (round-3 picked the LAST load where CPU won)."""
    from quiver_tpu.serving import _fit_crossover

    # clean crossover at load ~100: cpu wins below, device above
    pts = [(l, 1.0, 2.0) for l in (10, 20, 40, 80)] + \
          [(l, 3.0, 1.0) for l in (120, 200, 400, 800, 1600)]
    thr = _fit_crossover(pts)
    assert 80 <= thr <= 120, thr

    # one lucky CPU sample deep past the crossover must NOT set the
    # threshold to 1600
    noisy = pts + [(1600.0001, 0.5, 1.0)]
    thr = _fit_crossover(noisy)
    assert thr <= 200, thr

    # degenerate cases
    assert _fit_crossover([]) == 0.0
    assert _fit_crossover([(5, 2.0, 1.0)]) == 0.0          # device always
    assert _fit_crossover([(5, 1.0, 2.0), (9, 1.0, 2.0)]) == 9  # cpu always


def test_fit_crossover_small_sample():
    """With fewer points than any window width, a clean CPU prefix must
    still yield a positive threshold (not a global-majority 0.0)."""
    from quiver_tpu.serving import _fit_crossover

    thr = _fit_crossover(
        [(10, 1, 2), (20, 1, 2), (120, 3, 1), (200, 3, 1), (400, 3, 1)])
    assert 20 <= thr <= 120, thr
    thr = _fit_crossover([(10, 1, 2), (120, 3, 1)])
    assert 10 <= thr <= 120, thr
