"""Lock-witness sanitizer tests (quiverlint v2's dynamic half).

The install/uninstall fixture drives the witness directly so these run
in the normal suite too; under ``make sanitize`` (QUIVER_SANITIZE=1)
install() is a no-op on the already-installed witness and teardown
leaves it in place for the rest of the session.

The inversion test is deliberately deterministic: thread 1 takes A→B
and fully exits before thread 2 takes B→A, so no interleaving luck is
involved — the order graph, not an actual deadlock, raises the flag.
"""

import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from quiver_tpu.analysis import witness

pytestmark = pytest.mark.sanitize

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture
def w():
    was_installed = witness.installed()
    witness.install()
    witness.drain()
    yield witness
    witness.drain()
    if not was_installed:  # don't tear down the session-wide sanitizer
        witness.uninstall()


def kinds(vs):
    return sorted(v.kind for v in vs)


def test_wraps_lock_construction(w):
    assert isinstance(threading.Lock(), witness._WitnessLock)
    assert isinstance(threading.RLock(), witness._WitnessLock)


def test_deterministic_two_thread_inversion(w):
    class Box:
        def __init__(self):
            self.alock = threading.Lock()
            self.block = threading.Lock()

    b = Box()

    def fwd():
        with b.alock:
            with b.block:
                pass

    def bwd():
        with b.block:
            with b.alock:
                pass

    t1 = threading.Thread(target=fwd)
    t1.start()
    t1.join()  # A->B fully witnessed before the reverse order runs
    t2 = threading.Thread(target=bwd)
    t2.start()
    t2.join()
    vs = w.drain()
    assert "lock-order" in kinds(vs), vs
    msg = next(v for v in vs if v.kind == "lock-order").message
    assert "Box.alock" in msg and "Box.block" in msg


def test_consistent_order_stays_quiet(w):
    class Box:
        def __init__(self):
            self.alock = threading.Lock()
            self.block = threading.Lock()

    b = Box()
    for _ in range(3):
        with b.alock:
            with b.block:
                pass
    assert w.drain() == []


def test_seeded_static_order_flags_single_reversal(w):
    w.seed_order([("SeedA._first", "SeedB._second")])

    class SeedA:
        def __init__(self):
            self._first = threading.Lock()

    class SeedB:
        def __init__(self):
            self._second = threading.Lock()

    a, b = SeedA(), SeedB()
    with b._second:      # the reverse order, exactly once
        with a._first:
            pass
    vs = w.drain()
    assert kinds(vs) == ["lock-order"]
    assert "canonical order" in vs[0].message


def test_plain_lock_reentry_recorded_not_hung(w):
    lock = threading.Lock()
    lock.acquire()
    assert lock.acquire(timeout=0.01) is False  # delegates, returns
    lock.release()
    assert "self-deadlock" in kinds(w.drain())


def test_rlock_reentry_is_fine(w):
    lock = threading.RLock()
    with lock:
        with lock:
            pass
    assert w.drain() == []


def test_guarded_write_enforcement(w):
    class G:
        _guarded_by = {"val": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self.val = 0  # construction frame: exempt

    g = G()
    with g._lock:
        g.val = 1  # held: fine
    assert w.drain() == []
    g.val = 2  # unguarded rebind
    vs = w.drain()
    assert kinds(vs) == ["unguarded-write"]
    assert "G.val" in vs[0].message


def test_condition_over_witnessed_lock(w):
    cv = threading.Condition(threading.Lock())
    got = []

    def waiter():
        with cv:
            got.append(cv.wait(timeout=2.0))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join()
    assert got == [True]
    assert w.drain() == []


def test_feature_publication_is_witness_clean(w):
    """Regression for the Feature table-swap fix: constructing a Feature
    and re-publishing its order must honor the _guarded_by contract
    under the sanitizer (locks here were made AFTER install, so the
    wrapped __setattr__ checks are live)."""
    np = pytest.importorskip("numpy")
    from quiver_tpu.feature import Feature

    feat = Feature(rank=0, device_list=[0])
    feat.from_cpu_tensor(np.arange(20, dtype=np.float32).reshape(5, 4))
    # re-publication takes the same atomic-swap path on a live object
    feat.from_cpu_tensor(np.ones((6, 3), dtype=np.float32))
    vs = w.drain()
    assert vs == [], vs


def test_witness_off_is_zero_overhead():
    """Without QUIVER_SANITIZE, importing quiver_tpu must neither load
    the witness nor touch the Lock factories."""
    env = {k: v for k, v in os.environ.items() if k != "QUIVER_SANITIZE"}
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import threading, _thread, sys\n"
        "orig_l, orig_r = threading.Lock, threading.RLock\n"
        "import quiver_tpu\n"
        "assert 'quiver_tpu.analysis.witness' not in sys.modules\n"
        "assert threading.Lock is orig_l is _thread.allocate_lock\n"
        "assert threading.RLock is orig_r\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=str(REPO), env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_env_gate_installs_and_wraps():
    env = dict(os.environ, QUIVER_SANITIZE="1", JAX_PLATFORMS="cpu")
    code = (
        "import threading\n"
        "import quiver_tpu\n"
        "from quiver_tpu.analysis import witness\n"
        "assert witness.installed()\n"
        "assert isinstance(threading.Lock(), witness._WitnessLock)\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=str(REPO), env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_executor_first_import_under_witness():
    # concurrent.futures.thread creates a module-level Lock at first
    # import and registers its _at_fork_reinit with os.register_at_fork;
    # a wrapper missing that attribute poisons the half-initialized
    # stdlib module for the rest of the process.  Fresh interpreter so
    # the first import really happens under the patched factory.
    env = dict(os.environ, QUIVER_SANITIZE="1", JAX_PLATFORMS="cpu")
    code = (
        "import quiver_tpu\n"
        "from concurrent.futures import ThreadPoolExecutor\n"
        "with ThreadPoolExecutor(1) as p:\n"
        "    assert p.submit(lambda: 41 + 1).result(10) == 42\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=str(REPO), env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
