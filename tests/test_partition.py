"""Partitioner tests (parity: reference partition.py semantics)."""

import numpy as np
import pytest

from quiver_tpu import (
    partition_without_replication,
    quiver_partition_feature,
    load_quiver_feature_partition,
)
from quiver_tpu.partition import (
    select_nodes,
    partition_feature_without_replication,
)


@pytest.fixture
def probs(rng):
    n = 200
    # two partitions with disjoint-ish hot sets
    p0 = np.zeros(n)
    p1 = np.zeros(n)
    p0[:80] = rng.uniform(0.5, 1.0, 80)
    p1[60:140] = rng.uniform(0.5, 1.0, 80)
    return [p0, p1]


def test_partition_complete_and_disjoint(probs):
    parts = partition_without_replication(probs)
    allv = np.concatenate(parts)
    assert len(allv) == len(set(allv.tolist())) == len(probs[0])
    # balanced within a chunk's worth
    assert abs(len(parts[0]) - len(parts[1])) <= len(probs[0]) // 16


def test_partition_affinity(probs):
    """Nodes accessed only by partition 0 should mostly land there."""
    parts = partition_without_replication(probs)
    only0 = set(range(0, 60))
    placed0 = only0 & set(parts[0].tolist())
    # balance constraint legitimately displaces a few exclusive nodes
    assert len(placed0) > len(only0) * 0.8


def test_select_nodes(probs):
    accessed, unaccessed = select_nodes(probs)
    assert set(accessed.tolist()) == set(range(140))
    assert set(unaccessed.tolist()) == set(range(140, 200))


def test_feature_partition_roundtrip(tmp_path, probs, rng):
    n = len(probs[0])
    feature = rng.normal(size=(n, 8)).astype(np.float32)
    parts, orders, book = quiver_partition_feature(
        feature, probs, str(tmp_path)
    )
    for p in range(2):
        ids, cache_order, feat_p, book_l = load_quiver_feature_partition(
            p, str(tmp_path)
        )
        np.testing.assert_allclose(feat_p, feature[ids])
        assert (book_l[ids] == p).all()
        # cache order is probability-descending within the partition
        pr = probs[p][cache_order]
        assert (np.diff(pr) <= 1e-12).all()
    # every node has a home
    assert (book >= 0).all()
