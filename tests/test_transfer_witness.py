"""Device-transfer witness tests (quiverlint v3's dynamic half).

The install/uninstall fixture drives the witness directly so these run
in the normal suite too; under ``make sanitize`` (QUIVER_SANITIZE=1)
install() is a no-op on the already-installed witness and teardown
leaves it in place for the rest of the session.

The in-region test is deliberately deterministic: the coercion happens
on this thread, inside the ``with`` block, every run — no timing or
device luck involved.  Zero-overhead-off and env-gate contracts run in
fresh subprocesses so the import-time behavior is the real thing.
"""

import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quiver_tpu.analysis import staging, transfer_witness
from quiver_tpu.analysis.staging import regions

pytestmark = pytest.mark.sanitize

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture
def tw():
    was_installed = transfer_witness.installed()
    transfer_witness.install()
    transfer_witness.drain()
    yield transfer_witness
    transfer_witness.drain()
    if not was_installed:  # don't tear down the session-wide sanitizer
        transfer_witness.uninstall()


@pytest.fixture
def live_telemetry():
    from quiver_tpu import telemetry

    was = telemetry.enabled()
    telemetry.set_enabled(True)
    telemetry.reset()
    yield telemetry
    telemetry.reset()
    telemetry.set_enabled(was)


def test_transfers_observed_and_attributed(tw):
    x = jnp.arange(4)
    _ = float(x.sum())
    _ = np.asarray(x)
    sites = [t.site for t in tw.transfers()]
    assert "float()" in sites and "np.asarray" in sites
    me = Path(__file__).name
    assert any(t.where.startswith(me) for t in tw.transfers())
    assert tw.violations() == []  # outside any region: observed, legal


def test_device_get_records_exactly_one_transfer(tw):
    # device_get materializes via np.asarray internally — the re-entry
    # guard must collapse that to ONE attributed transfer, not two
    _ = jax.device_get(jnp.arange(3))
    assert [t.site for t in tw.transfers()] == ["jax.device_get"]


def test_in_region_sync_is_deterministic_violation(tw):
    with staging.no_sync("unit region"):
        _ = np.asarray(jnp.arange(3))
    vs = tw.drain()
    assert [v.kind for v in vs] == ["in-region-sync"]
    assert "unit region" in vs[0].message
    assert "np.asarray" in vs[0].message


def test_host_data_in_region_stays_quiet(tw):
    with staging.no_sync("unit region"):
        _ = np.asarray([1, 2, 3])  # host data: no transfer at all
        _ = float(3.5)
    assert tw.drain() == []


def test_install_arms_region_gate(tw):
    assert regions.on()
    with staging.no_sync("lbl"):
        assert staging.active() == "lbl"
        with staging.no_sync("inner"):
            assert staging.active() == "inner"
        assert staging.active() == "lbl"
    assert staging.active() is None


def test_region_gate_is_single_global_read():
    # the off-path cost of on() is pinned to one module-global load —
    # the same gating contract the timeline's hot-path guard carries
    assert regions.on.__code__.co_names == ("_ON",)


def test_attribution_lands_on_live_trace(tw, live_telemetry):
    from quiver_tpu.telemetry import flightrec

    tr = flightrec.new_trace()
    assert tr is not None
    with flightrec.activate(tr):
        _ = np.asarray(jnp.arange(3))
    evs = [e for e in tr.events if e[1] == "host_transfer"]
    assert evs, tr.events
    assert evs[0][3]["site"] == "np.asarray"
    assert evs[0][3]["where"].startswith(Path(__file__).name)


def test_counter_ticks_per_site(tw, live_telemetry):
    _ = float(jnp.arange(2).sum())
    snap = live_telemetry.snapshot()
    keys = [k for k in snap.get("counters", {})
            if "sanitize_host_transfers_total" in k and "float()" in k]
    assert keys, snap.get("counters", {}).keys()


def test_uninstall_restores_coercion_points():
    if transfer_witness.installed():
        pytest.skip("sanitize session: witness stays installed")
    orig_asarray, orig_array = np.asarray, np.array
    orig_device_get = jax.device_get
    transfer_witness.install()
    try:
        assert np.asarray is not orig_asarray
        assert jax.device_get is not orig_device_get
    finally:
        transfer_witness.uninstall()
    assert np.asarray is orig_asarray and np.array is orig_array
    assert jax.device_get is orig_device_get
    assert not regions.on()
    assert transfer_witness.transfers() == []


def test_region_gate_off_is_shared_noop():
    if transfer_witness.installed():
        pytest.skip("sanitize session: gate armed")
    assert regions.no_sync("a") is regions.no_sync("b")
    assert staging.active() is None


def test_witness_off_is_zero_overhead():
    """Without QUIVER_SANITIZE, importing quiver_tpu must neither load
    the transfer witness nor touch numpy/jax coercion points, and the
    region gate must stay the shared no-op."""
    env = {k: v for k, v in os.environ.items() if k != "QUIVER_SANITIZE"}
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import sys\n"
        "import numpy as np\n"
        "import jax\n"
        "orig_asarray, orig_array = np.asarray, np.array\n"
        "orig_device_get = jax.device_get\n"
        "import quiver_tpu\n"
        "assert 'quiver_tpu.analysis.transfer_witness' not in sys.modules\n"
        "assert np.asarray is orig_asarray and np.array is orig_array\n"
        "assert jax.device_get is orig_device_get\n"
        "from quiver_tpu.analysis.staging import regions\n"
        "assert regions.on() is False\n"
        "assert regions.no_sync('a') is regions.no_sync('b')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=str(REPO), env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_env_gate_installs_and_records():
    env = dict(os.environ, QUIVER_SANITIZE="1", JAX_PLATFORMS="cpu")
    code = (
        "import quiver_tpu\n"
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "from quiver_tpu.analysis import staging\n"
        "from quiver_tpu.analysis import transfer_witness as tw\n"
        "assert tw.installed()\n"
        "with staging.no_sync('gate region'):\n"
        "    np.asarray(jnp.arange(3))\n"
        "vs = tw.drain()\n"
        "assert [v.kind for v in vs] == ['in-region-sync'], vs\n"
        "assert 'gate region' in vs[0].message\n"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120,
                          cwd=str(REPO), env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
