"""Flight recorder + SLO watchdog tests.

Covers the tentpole acceptance criteria: a slow request (injected sleep
in the CPU lane) is retained with a complete, ordered event log whose
stage intervals partition the end-to-end latency — including at least
one event attributed from the feature-prefetch worker thread — while a
fast request is discarded; /debug/requests and /debug/slo round-trip
JSON over HTTP; steady-state serving replay with tracing active builds
zero new jit executables.
"""

import json
import queue
import threading
import time

import numpy as np
import jax
import pytest

import quiver_tpu.config as config_mod
from quiver_tpu import (
    Feature, GraphSageSampler, HybridSampler, InferenceServer,
    InferenceServer_Debug, RequestBatcher, SeedLoader, telemetry,
)
from quiver_tpu.analysis.retrace_guard import count_jit_builds
from quiver_tpu.models import GraphSAGE
from quiver_tpu.serving import ServingRequest
from quiver_tpu.telemetry import flightrec
from quiver_tpu.telemetry.flightrec import (
    FlightRecorder, TraceContext, partition_check,
)
from quiver_tpu.telemetry.slo import SLOWatchdog, get_watchdog

pytestmark = pytest.mark.telemetry

_CFG_FIELDS = ("flightrec_capacity", "flightrec_slow_ms", "slo_p99_ms",
               "slo_error_ratio", "slo_coldcache_hit_floor",
               "slo_interval_s")


@pytest.fixture(autouse=True)
def _clean_flightrec():
    """Fresh recorder/watchdog/registry per test; config restored after.

    ``telemetry.reset()`` drops the flightrec + slo singletons, so a
    test that tweaks config just resets and touches them again."""
    cfg = config_mod.get_config()
    saved = {k: getattr(cfg, k) for k in _CFG_FIELDS}
    telemetry.set_enabled(True)
    telemetry.reset()
    yield
    config_mod.update(**saved)
    telemetry.set_enabled(True)
    telemetry.reset()


# ===================================================== unit: TraceContext
def test_trace_event_log_is_monotonic_and_thread_stamped():
    tr = flightrec.new_trace()
    tr.add("enqueue", {"n_ids": 3})
    with flightrec.activate(tr):
        assert flightrec.tracing()
        flightrec.event("sample", {"seconds": 0.01})

    done = threading.Event()

    def worker():
        with flightrec.activate(tr):
            flightrec.event("gather")
        done.set()

    threading.Thread(target=worker, name="stager-0").start()
    assert done.wait(5)
    tr.add("finish")
    rec = tr.to_record(0.5, lane="cpu", stages={"sample": 0.5})
    names = [e["name"] for e in rec["events"]]
    assert names == ["enqueue", "sample", "gather", "finish"]
    ts = [e["t"] for e in rec["events"]]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)
    threads = {e["name"]: e["thread"] for e in rec["events"]}
    assert threads["gather"] == "stager-0"
    assert rec["events"][0]["attrs"] == {"n_ids": 3}


def test_event_cap_counts_drops():
    tr = TraceContext()
    for i in range(flightrec._MAX_EVENTS_PER_TRACE + 5):
        tr.add("e")
    rec = tr.to_record(0.0)
    assert len(rec["events"]) == flightrec._MAX_EVENTS_PER_TRACE
    assert rec["events_dropped"] == 5


def test_coalesced_activation_fans_out_to_all_members():
    trs = [TraceContext() for _ in range(3)]
    with flightrec.activate(trs):
        flightrec.event("dequeue", {"coalesced": 3})
    for tr in trs:
        assert [n for _, n, _, _ in tr.events] == ["dequeue"]


def test_disabled_is_zero_allocation():
    telemetry.set_enabled(False)
    assert flightrec.new_trace() is None
    assert flightrec.activate(None) is flightrec._NOOP_ACTIVATION
    assert flightrec.activate([None, None]) is flightrec._NOOP_ACTIVATION
    with flightrec.activate(None):
        assert not flightrec.tracing()
        flightrec.event("ignored")  # must not raise
    assert flightrec.get_recorder().finish(None, 1.0) is None


# ===================================================== unit: recorder
def test_classify_precedence_error_flagged_slow():
    rec = FlightRecorder(capacity=8, slow_threshold_s=0.1)
    tr = TraceContext()
    tr.flag()
    assert rec.classify(tr, 5.0, "error") == "error"
    assert rec.classify(tr, 5.0, "ok") == "flagged"
    assert rec.classify(TraceContext(), 5.0, "ok") == "slow"
    assert rec.classify(TraceContext(), 0.01, "ok") is None


def test_ring_eviction_and_lookup():
    rec = FlightRecorder(capacity=2, slow_threshold_s=0.0)
    ids = []
    for _ in range(3):
        tr = TraceContext()
        tr.add("enqueue")
        rec.finish(tr, 1.0, lane="cpu")
        ids.append(tr.trace_id)
    got = rec.records()
    assert [r["trace_id"] for r in got] == ids[1:]  # oldest evicted
    assert rec.get(ids[0]) is None
    assert rec.get(ids[2])["reason"] == "slow"
    summaries = rec.summaries()
    assert [s["trace_id"] for s in summaries] == ids[1:]
    assert summaries[0]["e2e_ms"] == 1000.0
    rec.reset()
    assert rec.records() == []


def test_retention_counters_tick():
    rec = FlightRecorder(capacity=4, slow_threshold_s=0.1)
    rec.finish(TraceContext(), 1.0)         # slow
    flagged = TraceContext()
    flagged.flag()
    rec.finish(flagged, 0.0)                # flagged
    rec.finish(TraceContext(), 0.0, status="error")
    rec.finish(TraceContext(), 0.0)         # dropped
    snap = telemetry.get_registry().snapshot()
    c = snap["counters"]
    assert c['flightrec_retained_total{reason=slow}'] == 1
    assert c['flightrec_retained_total{reason=flagged}'] == 1
    assert c['flightrec_retained_total{reason=error}'] == 1
    assert c['flightrec_dropped_total'] == 1


def test_partition_check():
    good = {"e2e_seconds": 1.0,
            "stages": {"queue_wait": 0.4, "sample": 0.35, "infer": 0.24}}
    bad = {"e2e_seconds": 1.0, "stages": {"sample": 0.1}}
    assert partition_check(good)
    assert not partition_check(bad)
    assert not partition_check({"e2e_seconds": 1.0})


# ===================================================== serving acceptance
class _SlowSampler:
    """CPU-lane sampler wrapper with a togglable injected stall."""

    def __init__(self, inner):
        self.inner = inner
        self.sleep_s = 0.0

    def sample(self, seeds, key=None):
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return self.inner.sample(seeds)


def _cpu_stack(small_graph, rng, dim=8, cache="2K", apply_fn=None):
    """CPU-lane serving stack with a budgeted feature so the
    HybridSampler lookahead actually stages rows on the prefetch pool."""
    n = small_graph.node_count
    feat = rng.normal(size=(n, dim)).astype(np.float32)
    feature = Feature(device_cache_size=cache).from_cpu_tensor(feat)
    sizes = [4, 3]
    tpu_sampler = GraphSageSampler(small_graph, sizes)
    slow = _SlowSampler(GraphSageSampler(small_graph, sizes, mode="CPU"))
    if apply_fn is None:
        model = GraphSAGE(hidden=16, out_dim=3, num_layers=2, dropout=0.0)
        b0 = tpu_sampler.sample(np.arange(4, dtype=np.int64))
        params = model.init(jax.random.PRNGKey(0),
                            feature[np.asarray(b0.n_id)], b0.layers)
        apply_fn = jax.jit(lambda p, x, blocks: model.apply(p, x, blocks))
    else:
        params = None
    stream = queue.Queue()
    rb = RequestBatcher([stream], mode="CPU").start()
    hs = HybridSampler(slow, rb.cpu_batched_queue, num_workers=1,
                       buckets=(4, 8, 16), feature=feature).start()
    server = InferenceServer_Debug(
        tpu_sampler, feature, apply_fn, params,
        rb.device_batched_queue, hs.sampled_queue, fused=False)
    server.BUCKETS = (4, 8, 16)
    server.start()
    return stream, rb, hs, server, slow


def _serve_one(stream, server, ids, seq):
    req = ServingRequest(ids=np.asarray(ids, dtype=np.int64),
                         client=0, seq=seq)
    stream.put(req)
    got_req, out = server.result_queue.get(timeout=60)
    assert got_req.seq == seq
    return req, out


def test_slow_request_retained_fast_discarded(small_graph, rng):
    # generous threshold: the "fast" request still does a real CPU-lane
    # serve, which can take >250ms on a loaded CI machine — the margin
    # must dwarf scheduler noise, not just the happy-path latency
    config_mod.update(flightrec_slow_ms=1500.0)
    telemetry.reset()  # recorder re-reads the lowered threshold
    stream, rb, hs, server, slow = _cpu_stack(small_graph, rng)
    try:
        # warm the CPU-lane compile path so the "fast" request really is
        _serve_one(stream, server, [1, 2, 3], seq=0)
        flightrec.get_recorder().reset()

        slow.sleep_s = 2.0
        slow_req, _ = _serve_one(stream, server, [4, 5, 6], seq=1)
        slow.sleep_s = 0.0
        fast_req, _ = _serve_one(stream, server, [7, 8, 9], seq=2)
        # let the recorder see both finishes before asserting
        deadline = time.time() + 5
        while not server.flight_records() and time.time() < deadline:
            time.sleep(0.01)

        records = server.flight_records()
        assert [r["trace_id"] for r in records] == [slow_req.trace.trace_id]
        rec = records[0]
        assert rec["status"] == "ok"
        assert rec["reason"] == "slow"
        assert rec["lane"] == "cpu"
        assert flightrec.get_recorder().get(fast_req.trace.trace_id) is None

        names = [e["name"] for e in rec["events"]]
        for expected in ("enqueue", "route", "sample", "gather", "infer",
                         "finish"):
            assert expected in names, f"missing {expected} in {names}"
        assert names[0] == "enqueue" and names[-1] == "finish"
        ts = [e["t"] for e in rec["events"]]
        assert ts == sorted(ts)

        # cross-thread attribution: the lookahead staging ran on the
        # feature-prefetch pool under this request's context
        threads = {e["thread"] for e in rec["events"]}
        assert any(t.startswith("feature-prefetch") for t in threads), \
            threads
        assert "feature.prefetch" in names

        # stage intervals partition end-to-end latency
        assert rec["e2e_seconds"] > 0.5
        assert partition_check(rec), (rec["stages"], rec["e2e_seconds"])
        assert rec["stages"]["sample"] >= 0.5  # the injected stall
    finally:
        rb.stop()
        hs.stop()
        server.stop()


def test_errored_request_retained_with_error_event(small_graph, rng):
    calls = {"n": 0}
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=2, dropout=0.0)
    sampler0 = GraphSageSampler(small_graph, [4, 3])
    n = small_graph.node_count
    feat0 = rng.normal(size=(n, 8)).astype(np.float32)
    feature0 = Feature(device_cache_size="1G").from_cpu_tensor(feat0)
    b0 = sampler0.sample(np.arange(4, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature0[np.asarray(b0.n_id)], b0.layers)

    def apply_fn(p, x, blocks):
        calls["n"] += 1
        # fail the CPU-lane attempt AND the device failover retry: the
        # server reroutes a failed lane before erroring, so a request
        # only surfaces an exception when every route is exhausted
        if calls["n"] <= 2:
            raise RuntimeError("boom")
        return model.apply(p if p is not None else params, x, blocks)

    stream, rb, hs, server, _ = _cpu_stack(small_graph, rng,
                                           apply_fn=apply_fn)
    try:
        req = ServingRequest(ids=np.array([1, 2], dtype=np.int64),
                             client=0, seq=0)
        stream.put(req)
        got_req, out = server.result_queue.get(timeout=60)
        assert isinstance(out, Exception)
        # the lane survives: a second request still serves
        _serve_one(stream, server, [3, 4], seq=1)

        rec = flightrec.get_recorder().get(req.trace.trace_id)
        assert rec is not None
        assert rec["status"] == "error" and rec["reason"] == "error"
        errs = [e for e in rec["events"] if e["name"] == "error"]
        assert errs and errs[0]["attrs"]["type"] == "RuntimeError"
        assert "boom" in errs[0]["attrs"]["message"]
    finally:
        rb.stop()
        hs.stop()
        server.stop()


def test_flagged_request_retained_even_when_fast():
    rec = flightrec.get_recorder()
    tr = flightrec.new_trace()
    tr.add("enqueue")
    with flightrec.activate(tr):
        flightrec.flag()
    assert rec.finish(tr, 0.001, lane="cpu") == "flagged"
    assert rec.get(tr.trace_id)["reason"] == "flagged"


# ===================================================== loader propagation
def test_loader_prefetch_worker_attributes_to_active_trace(small_graph,
                                                           rng):
    n = small_graph.node_count
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    feature = Feature(device_cache_size="2K").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [4, 3])
    loader = SeedLoader(np.arange(n, dtype=np.int64), sampler, feature,
                        batch_size=64, shuffle=False, prefetch=2)
    tr = flightrec.new_trace()
    with flightrec.activate(tr):
        for _ in loader:
            pass
    names = [nm for _, nm, _, _ in tr.events]
    assert "loader.batch" in names
    assert "feature.prefetch" in names
    main = threading.current_thread().name
    batch_threads = {th for _, nm, th, _ in tr.events
                     if nm == "loader.batch"}
    # prefetch=2 runs _make on the Prefetcher worker, which carries the
    # consumer's contextvars snapshot across the thread boundary
    assert batch_threads and all(th != main for th in batch_threads)
    pf_threads = {th for _, nm, th, _ in tr.events
                  if nm == "feature.prefetch"}
    assert all(th.startswith("feature-prefetch") for th in pf_threads)


# ===================================================== SLO watchdog
def _mk_watchdog(**kw):
    kw.setdefault("interval_s", 60.0)
    return SLOWatchdog(**kw)


def test_slo_p99_breach_ticks_counter():
    wd = _mk_watchdog(p99_ms=10.0, error_ratio=0.5)
    h = telemetry.histogram("serving_request_seconds", lane="cpu")
    for _ in range(5):
        h.observe(0.5)
    results = {r["objective"]: r for r in wd.evaluate_once()}
    p99 = results["p99_latency"]
    assert p99["breaching"] and p99["samples"] == 5
    assert p99["value"] > 10.0 and p99["burn"] > 1.0
    snap = telemetry.get_registry().snapshot()
    assert snap["counters"][
        "slo_breaches_total{objective=p99_latency}"] == 1
    # empty next window: no samples, no breach, no double-count
    results2 = {r["objective"]: r for r in wd.evaluate_once()}
    assert results2["p99_latency"]["samples"] == 0
    assert not results2["p99_latency"]["breaching"]
    snap2 = telemetry.get_registry().snapshot()
    assert snap2["counters"][
        "slo_breaches_total{objective=p99_latency}"] == 1


def test_slo_error_ratio_and_coldcache_floor():
    wd = _mk_watchdog(p99_ms=1e9, error_ratio=0.1,
                      coldcache_hit_floor=0.9)
    for _ in range(8):
        telemetry.counter("serving_requests_total", status="ok").inc()
    for _ in range(2):
        telemetry.counter("serving_requests_total", status="error").inc()
    telemetry.counter("feature_coldcache_rows_total",
                      result="hit").inc(5)
    telemetry.counter("feature_coldcache_rows_total",
                      result="miss").inc(5)
    results = {r["objective"]: r for r in wd.evaluate_once()}
    err = results["error_ratio"]
    assert err["breaching"] and err["value"] == pytest.approx(0.2)
    cc = results["coldcache_hit_rate"]
    assert cc["breaching"] and cc["value"] == pytest.approx(0.5)
    assert cc["burn"] > 1.0
    assert not results["p99_latency"]["breaching"]


def test_slo_status_json_and_thread_lifecycle():
    wd = _mk_watchdog(interval_s=0.05, p99_ms=100.0)
    st = wd.status()  # thread not running: evaluates on demand
    assert st["running"] is False
    assert {o["objective"] for o in st["objectives"]} >= {
        "p99_latency", "error_ratio"}
    json.dumps(st)  # must be plain JSON
    wd.start()
    assert wd.start() is wd  # idempotent
    deadline = time.time() + 5
    while wd.status()["ticks"] < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert wd.status()["running"] is True
    assert wd.status()["ticks"] >= 2
    wd.stop()
    assert wd.status()["running"] is False


def test_watchdog_singleton_reset():
    from quiver_tpu.telemetry import slo as slo_mod

    wd = get_watchdog()
    assert get_watchdog() is wd
    slo_mod.reset()
    assert get_watchdog() is not wd


# ===================================================== /debug endpoints
def test_debug_http_endpoints_round_trip():
    from urllib.error import HTTPError
    from urllib.request import Request, urlopen

    from quiver_tpu.telemetry.export import start_http_server

    rec = flightrec.get_recorder()
    tr = flightrec.new_trace()
    tr.add("enqueue", {"n_ids": 2})
    tr.add("finish")
    rec.finish(tr, 1.0, lane="cpu", stages={"sample": 1.0})

    srv = start_http_server(port=0)
    try:
        idx = json.loads(urlopen(srv.url + "/debug/requests",
                                 timeout=5).read().decode())
        assert idx["count"] == 1
        assert idx["capacity"] == rec.capacity
        assert idx["records"][0]["trace_id"] == tr.trace_id
        assert "events" not in idx["records"][0]  # index omits the log

        full = json.loads(urlopen(
            srv.url + f"/debug/requests/{tr.trace_id}",
            timeout=5).read().decode())
        assert [e["name"] for e in full["events"]] == ["enqueue", "finish"]
        assert full["stages"] == {"sample": 1.0}

        with pytest.raises(HTTPError) as ei:
            urlopen(srv.url + "/debug/requests/nonesuch", timeout=5)
        assert ei.value.code == 404

        slo = json.loads(urlopen(srv.url + "/debug/slo",
                                 timeout=5).read().decode())
        assert slo["running"] is False
        assert any(o["objective"] == "p99_latency"
                   for o in slo["objectives"])

        head = urlopen(Request(srv.url + "/debug/requests",
                               method="HEAD"), timeout=5)
        assert head.headers["Content-Type"].startswith("application/json")
        assert head.read() == b""
    finally:
        srv.close()


# ===================================================== retrace budget
def test_steady_state_replay_builds_nothing_with_tracing_on(small_graph,
                                                            rng):
    """Tracing must not perturb jit caching: after warmup, a traced
    replay over the same buckets compiles zero new executables."""
    config_mod.update(flightrec_slow_ms=1e9)  # retain nothing
    telemetry.reset()
    n = small_graph.node_count
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [4, 3])
    model = GraphSAGE(hidden=16, out_dim=3, num_layers=2, dropout=0.0)
    b0 = sampler.sample(np.arange(4, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    traces = []

    @jax.jit
    def apply_fn(p, x, blocks):
        traces.append(1)  # body runs only on (re)trace
        return model.apply(p, x, blocks)

    stream = queue.Queue()
    rb = RequestBatcher([stream], mode="Device").start()
    server = InferenceServer(
        sampler, feature, apply_fn, params, rb.device_batched_queue,
        max_coalesce=1, fused=False)
    server.BUCKETS = (4, 8, 16)
    server.start()
    try:
        sizes = [3, 7, 12]  # one per bucket
        for seq, sz in enumerate(sizes):  # warmup: compiles each bucket
            _serve_one(stream, server, np.arange(sz), seq)
        n_traces = len(traces)
        with count_jit_builds() as c:
            for seq, sz in enumerate(sizes * 3):  # steady-state replay
                req, _ = _serve_one(stream, server, np.arange(sz),
                                    100 + seq)
                assert req.trace is not None  # tracing really was on
        assert c.builds == 0
        assert len(traces) == n_traces
    finally:
        rb.stop()
        server.stop()
