"""Durability + warm-restart tier tests (docs/RECOVERY.md).

Correctness bar, in order of importance:

* **Zero acked loss** — an edge op answered ``("ok", ...)`` on the
  ingest results queue is on durable media: replaying the WAL into a
  fresh graph reproduces every acked mutation, and sampling the
  recovered graph is BIT-IDENTICAL to the uninterrupted one.
* **Crash debris is data, not poison** — a torn tail ends its segment
  and a checksum-corrupt record is skipped, each with its counter
  ticked; neither ever crashes boot.  Version-skewed snapshots refuse
  with a typed :class:`SnapshotFormatError`, never a stack trace from
  half-parsed bytes.
* **Durability faults are answered** — an injected ``recovery.fsync``
  / ``recovery.wal_write`` fault surfaces as :class:`WALWriteError` on
  the submitting request, with the graph untouched.
* **Warm restarts re-earn nothing** — checkpointed coldcache residency
  restores (values refilled from the cold tier), the program registry
  accounts every executable, and a sealed registry turns post-warmup
  compiles into typed budget violations.

The kill-9 crash harness lives in ``test_recovery_crash.py`` (``crash``
marker, ``make crash``).
"""

import json
import os
import struct
import threading
import time

import numpy as np
import pytest

import quiver_tpu.config as config_mod
from quiver_tpu import Feature, GraphSageSampler, telemetry
from quiver_tpu.ops.coldcache import ColdRowCache
from quiver_tpu.recovery import blockio
from quiver_tpu.recovery.checkpoint import (
    CHECKPOINT_FORMAT, load_checkpoint, read_checkpoint, restore_graph,
    save_checkpoint)
from quiver_tpu.recovery.errors import (
    RecoveryDeadlineExceeded, RecoveryError, RetraceBudgetExceeded,
    SnapshotFormatError, WALError, WALWriteError)
from quiver_tpu.recovery.manager import (
    RecoveryManager, health_status, set_active)
from quiver_tpu.recovery.registry import get_program_registry
from quiver_tpu.recovery.wal import (
    WriteAheadLog, decode_edge_op, encode_edge_op)
from quiver_tpu.resilience import chaos
from quiver_tpu.stream import IngestLane, StreamingGraph
from quiver_tpu.telemetry import metric_key
from quiver_tpu.utils.rng import make_key
from quiver_tpu.utils.topology import CSRTopo

pytestmark = pytest.mark.recovery

_CFG_KEYS = (
    "recovery_dir", "recovery_fsync", "recovery_segment_bytes",
    "recovery_batch_bytes", "recovery_checkpoint_interval_s",
    "recovery_checkpoint_keep", "recovery_deadline_s",
    "recovery_retrace_budget", "recovery_cache_dir",
)


@pytest.fixture(autouse=True)
def _clean_recovery():
    cfg = config_mod.get_config()
    saved = {k: getattr(cfg, k) for k in _CFG_KEYS}
    telemetry.set_enabled(True)
    telemetry.reset()
    yield
    chaos.uninstall()
    get_program_registry().unseal()
    set_active(None)
    config_mod.update(**saved)
    telemetry.set_enabled(True)
    telemetry.reset()


def counter_value(name, **labels):
    return telemetry.snapshot()["counters"].get(metric_key(name, labels), 0)


def _ring_topo(n=64):
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    return CSRTopo(edge_index=np.stack([src, dst]))


def _sampler(g):
    return GraphSageSampler(g, sizes=[5, 3], gather_mode="xla",
                            dedup="none")


def _assert_same_samples(ga, gb, seeds=None, rounds=3):
    sa, sb = _sampler(ga), _sampler(gb)
    seeds = np.arange(8) if seeds is None else seeds
    for s in range(rounds):
        a = sa.sample(seeds, key=make_key(s))
        b = sb.sample(seeds, key=make_key(s))
        np.testing.assert_array_equal(np.asarray(a.n_id),
                                      np.asarray(b.n_id))
        np.testing.assert_array_equal(np.asarray(a.n_id_mask),
                                      np.asarray(b.n_id_mask))


def _drain_ok(lane, n, timeout=10.0):
    outs = []
    for _ in range(n):
        item, out = lane.results.get(timeout=timeout)
        outs.append((item, out))
    return outs


# ---------------------------------------------------------------- blockio
class TestBlockIO:
    def test_crc32c_known_answer(self):
        # the iSCSI check value for "123456789"
        assert blockio.crc32c(b"123456789") == 0xE3069283
        assert blockio.crc32c(b"") == 0

    def test_crc32c_incremental(self):
        whole = blockio.crc32c(b"hello world")
        half = blockio.crc32c(b" world", blockio.crc32c(b"hello"))
        assert whole == half

    def test_record_round_trip(self, tmp_path):
        p = tmp_path / "seg"
        payloads = [b"a", b"bb" * 100, b""]
        with open(p, "ab") as f:
            for pl in payloads:
                blockio.write_record(f, pl)
        kinds_payloads = [(k, pl) for k, _off, pl in
                          blockio.scan_records(p.read_bytes())]
        assert kinds_payloads == [("ok", pl) for pl in payloads]

    def test_torn_tail_stops_scan(self, tmp_path):
        p = tmp_path / "seg"
        with open(p, "ab") as f:
            blockio.write_record(f, b"first")
            blockio.write_record(f, b"second-record-payload")
        data = p.read_bytes()
        torn = data[:-5]  # crash mid-write of the second record
        kinds = [k for k, _o, _p in blockio.scan_records(torn)]
        assert kinds == ["ok", "torn"]

    def test_corrupt_record_resyncs_when_frame_holds(self, tmp_path):
        p = tmp_path / "seg"
        with open(p, "ab") as f:
            blockio.write_record(f, b"victim-payload")
            blockio.write_record(f, b"survivor")
        data = bytearray(p.read_bytes())
        data[blockio.RECORD_HEADER_SIZE] ^= 0xFF  # bit rot in payload 0
        scanned = list(blockio.scan_records(bytes(data)))
        assert [k for k, _o, _p in scanned] == ["corrupt", "ok"]
        assert scanned[1][2] == b"survivor"

    def test_suspect_length_is_torn_not_seek(self):
        # a corrupt record whose claimed end lands on garbage must stop
        # the scan — trusting the length would misframe the whole log
        hdr = struct.Struct("<2sII").pack(b"QW", 4, 0xDEADBEEF)
        buf = hdr + b"ABCDgarbage-not-a-frame"
        kinds = [k for k, _o, _p in blockio.scan_records(buf)]
        assert kinds == ["torn"]

    def test_atomic_publish(self, tmp_path):
        target = tmp_path / "pub.bin"
        blockio.atomic_publish(str(target), b"v1")
        blockio.atomic_publish(str(target), b"v2")
        assert target.read_bytes() == b"v2"
        assert [n for n in os.listdir(tmp_path) if ".tmp." in n] == []


# ---------------------------------------------------------------- WAL
class TestWAL:
    def test_append_replay_round_trip(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        assert w.append(encode_edge_op("add", [1, 2], [3, 4])) == 0
        assert w.append(encode_edge_op("remove", [5], [6])) == 1
        w.close()
        w2 = WriteAheadLog(tmp_path / "wal")
        recs = list(w2.replay())
        assert [lsn for lsn, _ in recs] == [0, 1]
        op, src, dst, ts = decode_edge_op(recs[0][1])
        assert (op, ts) == ("add", None)
        assert src.tolist() == [1, 2] and dst.tolist() == [3, 4]
        assert w2.next_lsn == 2  # numbering resumes from disk
        w2.close()

    def test_edge_codec_pins_timestamps_and_dtype(self):
        payload = encode_edge_op(
            "add", np.array([7], np.int32), np.array([9], np.int32),
            ts=np.array([123], np.int16))
        op, src, dst, ts = decode_edge_op(payload)
        assert src.dtype == np.int64 and ts.dtype == np.int64
        assert ts.tolist() == [123]
        with pytest.raises(WALError):
            decode_edge_op(payload[:-3])
        with pytest.raises(WALError):
            encode_edge_op("frobnicate", [1], [2])

    def test_segment_rotation_and_truncation(self, tmp_path):
        root = tmp_path / "wal"
        w = WriteAheadLog(root, segment_bytes=64, fsync="off")
        for i in range(8):
            w.append(encode_edge_op("add", [i], [i + 1]))
        segs = sorted(os.listdir(root))
        assert len(segs) > 1
        assert [lsn for lsn, _ in w.replay()] == list(range(8))
        w.roll()  # seal the active segment so truncation may take it
        removed = w.truncate_through(w.last_lsn)
        assert removed >= 1
        assert counter_value("recovery_wal_truncated_segments_total") \
            == removed
        # everything the watermark covers is gone; the log still opens
        assert list(w.replay()) == []
        w.close()

    def test_torn_tail_detected_on_replay(self, tmp_path):
        root = tmp_path / "wal"
        w = WriteAheadLog(root, fsync="always")
        for i in range(3):
            w.append(encode_edge_op("add", [i], [i + 1]))
        w.close()
        seg = os.path.join(root, sorted(os.listdir(root))[-1])
        with open(seg, "rb+") as f:
            f.truncate(os.path.getsize(seg) - 4)  # kill -9 mid-write
        w2 = WriteAheadLog(root)
        assert [lsn for lsn, _ in w2.replay()] == [0, 1]
        assert counter_value("recovery_wal_torn_tails_total") >= 1
        # the torn slot is reused: the next append claims lsn 2
        assert w2.next_lsn == 2
        w2.close()

    def test_corrupt_record_skipped_with_telemetry(self, tmp_path):
        root = tmp_path / "wal"
        w = WriteAheadLog(root, fsync="always")
        for i in range(3):
            w.append(encode_edge_op("add", [i], [i + 1]))
        w.close()
        seg = os.path.join(root, sorted(os.listdir(root))[0])
        with open(seg, "rb+") as f:
            f.seek(blockio.RECORD_HEADER_SIZE)  # first payload byte
            b = f.read(1)
            f.seek(blockio.RECORD_HEADER_SIZE)
            f.write(bytes([b[0] ^ 0xFF]))
        w2 = WriteAheadLog(root)
        recs = list(w2.replay())
        # record 0 is skipped but still owns its LSN slot
        assert [lsn for lsn, _ in recs] == [1, 2]
        assert counter_value("recovery_wal_corrupt_records_total") == 1
        assert w2.next_lsn == 3
        w2.close()

    def test_fsync_fault_is_typed_error(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        chaos.install(chaos.ChaosPlan(seed=7).fail(
            "recovery.fsync", exc=OSError("disk gone"), times=1))
        with pytest.raises(WALWriteError):
            w.append(encode_edge_op("add", [1], [2]))
        # the fault is transient; the log keeps working afterwards
        assert isinstance(w.append(encode_edge_op("add", [1], [2])), int)
        w.close()

    def test_closed_wal_refuses_appends(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal")
        w.close()
        with pytest.raises(WALWriteError):
            w.append(b"late")

    def test_reused_segment_after_torn_first_record(self, tmp_path):
        """Crash mid-FIRST-write: the whole segment is torn, the slot
        count is 0, and the next roll reuses the same ``wal-<start>.seg``
        name.  Opening the log truncates the debris first, so fresh
        acked records never land behind bytes replay refuses to cross."""
        root = tmp_path / "wal"
        w = WriteAheadLog(root, fsync="always")
        w.append(encode_edge_op("add", [1], [2]))
        w.close()
        seg = os.path.join(root, sorted(os.listdir(root))[-1])
        with open(seg, "rb+") as f:
            f.truncate(3)  # an unframeable stub: kill -9 mid-header
        w2 = WriteAheadLog(root)
        assert w2.next_lsn == 0  # the torn slot was never acked
        assert counter_value("recovery_wal_torn_tails_total") == 1
        lsns = [w2.append(encode_edge_op("add", [i], [i + 1]))
                for i in range(3)]
        assert lsns == [0, 1, 2]
        # every fresh record is visible to replay — nothing stranded,
        # no duplicate LSNs on the next boot
        assert [lsn for lsn, _ in w2.replay()] == [0, 1, 2]
        w2.close()
        w3 = WriteAheadLog(root)
        assert w3.next_lsn == 3
        w3.close()

    def test_batch_policy_reaches_page_cache_per_append(self, tmp_path):
        """Under ``batch`` an acked record belongs to the kernel the
        moment ``append`` returns — kill -9 may lose a user-space
        buffer, never the page cache, so the segment read back through
        the filesystem must already frame the record."""
        root = tmp_path / "wal"
        w = WriteAheadLog(root, fsync="batch", batch_bytes=1 << 20)
        w.append(encode_edge_op("add", [1], [2]))
        seg = os.path.join(root, sorted(os.listdir(root))[0])
        with open(seg, "rb") as f:
            kinds = [k for k, _o, _p in blockio.scan_records(f.read())]
        assert kinds == ["ok"]
        w.close()

    def test_fsync_fault_ignored_under_policy_off(self, tmp_path):
        """``off`` promises no fsync, so an injected fsync fault has
        nothing real to stand in for — appends must keep succeeding."""
        w = WriteAheadLog(tmp_path / "wal", fsync="off")
        chaos.install(chaos.ChaosPlan(seed=7).fail(
            "recovery.fsync", exc=OSError("disk gone"), times=100))
        for i in range(3):
            w.append(encode_edge_op("add", [i], [i + 1]))
        w.sync()  # an explicit sync is equally a no-op under "off"
        assert counter_value("recovery_wal_fsyncs_total") == 0
        assert [lsn for lsn, _ in w.replay()] == [0, 1, 2]
        w.close()


# ---------------------------------------------------------------- snapshots
class TestCheckpoint:
    def _mutated_graph(self):
        g = StreamingGraph(_ring_topo(), delta_capacity=512)
        g.add_edges([0, 1], [5, 7])
        g.remove_edges([2], [3])
        return g

    def test_round_trip_bit_identical_sampling(self, tmp_path):
        g = self._mutated_graph()
        save_checkpoint(tmp_path, g, wal_lsn=41)
        ckpt = load_checkpoint(str(tmp_path))
        assert ckpt.wal_lsn == 41
        g2 = restore_graph(ckpt)
        assert g2.version == g.version
        _assert_same_samples(g, g2)

    def test_on_disk_dtypes_are_endianness_pinned(self, tmp_path):
        g = self._mutated_graph()
        path = save_checkpoint(tmp_path, g, wal_lsn=0)
        raw = open(path, "rb").read()
        prefix = struct.Struct("<4sII")
        magic, fmt, hdr_len = prefix.unpack_from(raw)
        assert magic == b"QCKP" and fmt == CHECKPOINT_FORMAT
        header = json.loads(raw[prefix.size:prefix.size + hdr_len])
        assert header["arrays"], "empty array directory"
        for spec in header["arrays"]:
            # every array is explicitly little-endian on disk — a
            # snapshot from any producer restores bit-identically
            assert spec["dtype"].startswith("<"), spec
        assert header["crc"] == blockio.crc32c(
            raw[prefix.size + hdr_len:])

    def test_version_skew_is_typed_refusal(self, tmp_path):
        g = self._mutated_graph()
        path = save_checkpoint(tmp_path, g, wal_lsn=0)
        raw = bytearray(open(path, "rb").read())
        struct.pack_into("<I", raw, 4, CHECKPOINT_FORMAT + 13)
        blockio.atomic_publish(path, bytes(raw))
        with pytest.raises(SnapshotFormatError) as ei:
            read_checkpoint(path)
        assert "not supported" in str(ei.value)

    def test_corrupt_body_and_bad_magic_refuse(self, tmp_path):
        g = self._mutated_graph()
        path = save_checkpoint(tmp_path, g, wal_lsn=0)
        raw = bytearray(open(path, "rb").read())
        raw[-1] ^= 0xFF
        blockio.atomic_publish(path, bytes(raw))
        with pytest.raises(SnapshotFormatError, match="checksum"):
            read_checkpoint(path)
        blockio.atomic_publish(path, b"PKZZ" + bytes(raw[4:]))
        with pytest.raises(SnapshotFormatError, match="magic"):
            read_checkpoint(path)
        with pytest.raises(SnapshotFormatError):
            blockio.atomic_publish(path, b"QC")
            read_checkpoint(path)

    def test_load_falls_back_past_corrupt_newest(self, tmp_path):
        g = self._mutated_graph()
        good = save_checkpoint(tmp_path, g, wal_lsn=5)
        g.add_edges([3], [9])
        newest = save_checkpoint(tmp_path, g, wal_lsn=9)
        assert newest != good
        raw = bytearray(open(newest, "rb").read())
        raw[-1] ^= 0xFF
        blockio.atomic_publish(newest, bytes(raw))
        ckpt = load_checkpoint(str(tmp_path))
        assert ckpt.path == good and ckpt.wal_lsn == 5
        assert counter_value("recovery_checkpoint_load_errors_total") == 1

    def test_all_corrupt_raises_not_none(self, tmp_path):
        g = self._mutated_graph()
        path = save_checkpoint(tmp_path, g, wal_lsn=0)
        blockio.atomic_publish(path, b"QCKPgarbage")
        with pytest.raises(SnapshotFormatError):
            load_checkpoint(str(tmp_path))
        assert load_checkpoint(str(tmp_path / "empty")) is None

    def test_retention_prunes_old_snapshots(self, tmp_path):
        g = StreamingGraph(_ring_topo(), delta_capacity=512)
        for i in range(4):
            g.add_edges([i], [i + 2])
            save_checkpoint(tmp_path, g, wal_lsn=i, keep=2)
        files = [n for n in os.listdir(tmp_path) if n.endswith(".qgr")]
        assert len(files) == 2


# ---------------------------------------------------------------- coldcache
class TestColdcacheState:
    def test_cache_state_round_trip(self):
        c = ColdRowCache(capacity=8, n_rows=100, admit_threshold=2)
        ids = np.array([3, 7, 11], dtype=np.int64)
        for _ in range(2):
            hit, _ = c.probe(ids)
            c.admit(ids[~hit])
        state = c.export_state()
        c2 = ColdRowCache(capacity=8, n_rows=100, admit_threshold=2)
        c2.restore_state(state)
        hit, slots = c2.probe(ids)
        assert hit.all()
        assert c2.resident == c.resident and c2.hand == c.hand

    def test_geometry_mismatch_refuses(self):
        c = ColdRowCache(capacity=8, n_rows=100)
        state = c.export_state()
        with pytest.raises(ValueError, match="capacity"):
            ColdRowCache(capacity=4, n_rows=100).restore_state(state)
        with pytest.raises(ValueError, match="cold-row"):
            ColdRowCache(capacity=8, n_rows=50).restore_state(state)

    def test_feature_restore_refills_overlay_values(self):
        rng = np.random.default_rng(3)
        feats = rng.standard_normal((64, 8)).astype(np.float32)
        f = Feature(device_cache_size=16,
                    cache_unit="rows").from_cpu_tensor(feats)
        f.enable_cold_cache(rows=8, admit_threshold=1)
        hot_ids = np.array([40, 41, 42, 43], dtype=np.int64)
        for _ in range(3):
            f[hot_ids]
        state = f.export_coldcache_state()
        assert state is not None and (state["node_of"] >= 0).any()

        f2 = Feature(device_cache_size=16,
                     cache_unit="rows").from_cpu_tensor(feats)
        f2.enable_cold_cache(rows=8, admit_threshold=1)
        warmed = f2.restore_coldcache_state(state)
        assert warmed == int((state["node_of"] >= 0).sum())
        # restored residency serves as device hits AND the values are
        # the real rows, not zeros left over from the fresh overlay
        before = f2.cold_cache.hits
        out = np.asarray(f2[hot_ids])
        np.testing.assert_allclose(out, feats[hot_ids], rtol=1e-6)
        assert f2.cold_cache.hits > before


# ---------------------------------------------------------------- ingest
class TestDurableIngest:
    def test_ack_implies_durable_and_replayable(self, tmp_path):
        g = StreamingGraph(_ring_topo(), delta_capacity=512)
        wal = WriteAheadLog(tmp_path / "wal", fsync="always")
        lane = IngestLane(g, wal=wal).start()
        n = 6
        for i in range(n):
            lane.submit([i], [(i + 7) % 64])
        outs = _drain_ok(lane, n)
        assert all(out[0] == "ok" for _, out in outs)
        lane.stop()
        # a fresh log handle sees every acked record without any close()
        w2 = WriteAheadLog(tmp_path / "wal")
        recs = list(w2.replay())
        assert len(recs) >= n
        g2 = StreamingGraph(_ring_topo(), delta_capacity=512)
        for _lsn, payload in recs:
            op, src, dst, ts = decode_edge_op(payload)
            g2.add_edges(src, dst) if op == "add" \
                else g2.remove_edges(src, dst)
        _assert_same_samples(g, g2)
        wal.close()
        w2.close()

    def test_wal_fault_answers_request_and_skips_apply(self, tmp_path):
        g = StreamingGraph(_ring_topo(), delta_capacity=512)
        wal = WriteAheadLog(tmp_path / "wal", fsync="always")
        lane = IngestLane(g, wal=wal).start()
        v0 = g.version
        chaos.install(chaos.ChaosPlan(seed=1).fail(
            "recovery.fsync", exc=OSError("dead disk"), times=1))
        lane.submit([1], [2])
        item, out = lane.results.get(timeout=10)
        assert isinstance(out, WALWriteError)
        assert g.version == v0  # the graph was never touched
        # next op rides the recovered log
        lane.submit([1], [2])
        _item, out = lane.results.get(timeout=10)
        assert out[0] == "ok"
        lane.stop()
        wal.close()

    def test_volatile_lane_unchanged_without_wal(self):
        g = StreamingGraph(_ring_topo(), delta_capacity=512)
        lane = IngestLane(g).start()
        lane.submit([0], [9])
        _item, out = lane.results.get(timeout=10)
        assert out[0] == "ok"
        lane.stop()


# ---------------------------------------------------------------- manager
class TestRecoveryManager:
    def _factory(self):
        return lambda: StreamingGraph(_ring_topo(), delta_capacity=512)

    def test_boot_cycle_and_replay_equivalence(self, tmp_path):
        root = str(tmp_path / "r")
        mgr = RecoveryManager(root, graph_factory=self._factory())
        g = mgr.boot()
        lane = IngestLane(g).start()
        mgr.attach_lane(lane)
        for i in range(5):
            lane.submit([i], [(i + 3) % 64])
        lane.submit([1], [2], op="remove")
        _drain_ok(lane, 6)
        lane.stop()
        mgr.close()  # clean shutdown; crash-path covered by the harness

        mgr2 = RecoveryManager(root, graph_factory=self._factory())
        g2 = mgr2.boot()
        assert mgr2.state == "serving"
        assert g2.version == g.version  # monotone across the restart
        _assert_same_samples(g, g2)
        mgr2.close()

    def test_checkpoint_barrier_truncates_replay(self, tmp_path):
        root = str(tmp_path / "r")
        mgr = RecoveryManager(root, graph_factory=self._factory())
        g = mgr.boot()
        lane = IngestLane(g).start()
        mgr.attach_lane(lane)
        for i in range(4):
            lane.submit([i], [i + 9])
        _drain_ok(lane, 4)
        mgr.checkpoint()
        for i in range(2):
            lane.submit([i + 20], [i + 30])
        _drain_ok(lane, 2)
        lane.stop()
        mgr.close()

        mgr2 = RecoveryManager(root, graph_factory=self._factory())
        g2 = mgr2.boot()
        # only the post-checkpoint tail replays
        assert mgr2.health()["replayed_records"] == 2
        _assert_same_samples(g, g2)
        mgr2.close()

    def test_boot_survives_torn_and_corrupt_wal(self, tmp_path):
        root = str(tmp_path / "r")
        mgr = RecoveryManager(root, graph_factory=self._factory())
        g = mgr.boot()
        lane = IngestLane(g).start()
        mgr.attach_lane(lane)
        for i in range(4):
            lane.submit([i], [i + 1])
        _drain_ok(lane, 4)
        lane.stop()
        mgr.close()
        wal_root = os.path.join(root, "wal")
        seg = os.path.join(wal_root, sorted(os.listdir(wal_root))[0])
        with open(seg, "rb+") as f:
            f.seek(blockio.RECORD_HEADER_SIZE)
            b = f.read(1)
            f.seek(blockio.RECORD_HEADER_SIZE)
            f.write(bytes([b[0] ^ 0xFF]))          # corrupt record 0
            f.truncate(os.path.getsize(seg) - 3)   # tear the tail
        mgr2 = RecoveryManager(root, graph_factory=self._factory())
        g2 = mgr2.boot()  # must not crash
        assert mgr2.state == "serving"
        assert counter_value("recovery_wal_corrupt_records_total") == 1
        assert counter_value("recovery_wal_torn_tails_total") == 1
        assert g2.version == 2  # records 1..2 replayed; 0 lost, 3 torn
        mgr2.close()

    def test_nacked_apply_is_aborted_not_replayed(self, tmp_path):
        """An op durably appended but REJECTED by the graph (delta
        overflow with compaction disabled) is nacked live and
        compensated with a WAL abort record — replay must not
        resurrect a mutation the serving process disclaimed."""
        root = str(tmp_path / "r")
        factory = lambda: StreamingGraph(  # noqa: E731
            _ring_topo(), delta_capacity=2)
        mgr = RecoveryManager(root, graph_factory=factory)
        g = mgr.boot()
        lane = IngestLane(g, compact_on_full=False).start()
        mgr.attach_lane(lane)
        lane.submit([1], [2])
        lane.submit([3], [4])
        _drain_ok(lane, 2)
        lane.submit([5], [6])  # delta full: apply fails AFTER the append
        _item, out = lane.results.get(timeout=10)
        assert isinstance(out, BufferError)
        assert counter_value("recovery_wal_abort_records_total") == 1
        live_version = g.version
        lane.stop()
        mgr.close()

        mgr2 = RecoveryManager(root, graph_factory=factory)
        g2 = mgr2.boot()
        assert counter_value("recovery_replay_aborted_total") == 1
        # the rejected op stayed dead: recovered state == acked state
        assert g2.version == live_version == 2
        _assert_same_samples(g, g2)
        mgr2.close()

    def test_replay_deadline_is_typed(self, tmp_path):
        root = str(tmp_path / "r")
        mgr = RecoveryManager(root, graph_factory=self._factory())
        g = mgr.boot()
        lane = IngestLane(g).start()
        mgr.attach_lane(lane)
        lane.submit([0], [1])
        _drain_ok(lane, 1)
        lane.stop()
        mgr.close()
        config_mod.update(recovery_deadline_s=1e-9)
        mgr2 = RecoveryManager(root, graph_factory=self._factory())
        mgr2.boot_degraded()
        with pytest.raises(RecoveryDeadlineExceeded):
            mgr2.finish_boot()
        assert counter_value("recovery_deadline_exceeded_total") == 1
        mgr2.close()

    def test_health_ladder_and_staleness(self, tmp_path):
        assert health_status() == {"state": "serving", "ready": True,
                                   "stale": False, "managed": False}
        mgr = RecoveryManager(str(tmp_path / "r"),
                              graph_factory=self._factory())
        mgr.boot_degraded()
        h = health_status()
        assert h["managed"] and h["state"] == "replaying"
        assert h["stale"] and not h["ready"]
        mgr.finish_boot()
        h = health_status()
        assert h["ready"] and h["state"] == "serving" and not h["stale"]
        mgr.close()

    def test_no_root_and_no_factory_refuse(self, tmp_path):
        config_mod.update(recovery_dir="")
        with pytest.raises(RecoveryError, match="durability root"):
            RecoveryManager()
        mgr = RecoveryManager(str(tmp_path / "r"))
        with pytest.raises(RecoveryError, match="graph_factory"):
            mgr.boot_degraded()

    def test_periodic_checkpointer_reaps(self, tmp_path):
        mgr = RecoveryManager(str(tmp_path / "r"),
                              graph_factory=self._factory())
        mgr.boot()
        mgr.start_checkpointer(interval_s=0.05)
        deadline = time.time() + 5
        ckpt_dir = os.path.join(str(tmp_path / "r"), "ckpt")
        while time.time() < deadline:
            if os.listdir(ckpt_dir):
                break
            time.sleep(0.02)
        assert os.listdir(ckpt_dir), "checkpointer never fired"
        mgr.close()  # joins the thread via join_and_reap


# ---------------------------------------------------------------- registry
class TestProgramRegistry:
    def test_counts_hits_misses_builds(self):
        reg = get_program_registry()
        c = reg.cache("t_unit")
        assert c.get("k") is None
        c["k"] = "prog"
        # one logical lookup = one tick: the `in` probe counts, the
        # `[]` read riding behind it is silent — the common
        # probe-then-read idiom must not double-count
        assert "k" in c and c["k"] == "prog"
        assert c.get("k") == "prog"
        st = reg.stats()["t_unit"]
        assert st["builds"] == 1 and st["hits"] == 2 and st["misses"] == 1
        assert counter_value("registry_builds_total", subsystem="t_unit") \
            == 1
        assert counter_value("registry_hits_total", subsystem="t_unit") == 2
        assert counter_value("registry_misses_total", subsystem="t_unit") \
            == 1
        assert reg.export_metrics()["t_unit"]["size"] == 1

    def test_setdefault_builds_once(self):
        reg = get_program_registry()
        c = reg.cache("t_setdefault")
        assert c.setdefault("b", 1) == 1
        assert c.setdefault("b", 2) == 1
        assert reg.stats()["t_setdefault"]["builds"] == 1

    def test_seal_budget_gates_late_builds(self):
        reg = get_program_registry()
        c = reg.cache("t_seal")
        c["warm"] = 1
        reg.seal(budget=1)
        c["one-late-build-allowed"] = 2
        with pytest.raises(RetraceBudgetExceeded):
            c["second-late-build"] = 3
        assert counter_value("registry_retraces_post_seal_total",
                             subsystem="t_seal") == 2
        reg.unseal()
        c["fine-again"] = 4

    def test_sampler_caches_are_registered(self):
        g = StreamingGraph(_ring_topo(), delta_capacity=512)
        s = _sampler(g)
        s.sample(np.arange(4), key=make_key(0))
        s.sample(np.arange(4), key=make_key(1))
        st = get_program_registry().stats()["sampler"]
        assert st["builds"] >= 1 and st["hits"] >= 1


# ---------------------------------------------------------------- serving
class TestMetricsEndpoint:
    def test_server_restarts_twice_on_same_port(self):
        from quiver_tpu.telemetry.export import start_http_server

        srv = start_http_server()
        port = srv.port
        srv.close()
        for _ in range(2):  # the regression: rebind the exact port
            srv = start_http_server(port=port)
            assert srv.port == port
            srv.close()

    def test_healthz_503_while_replaying_200_serving(self, tmp_path):
        from urllib.error import HTTPError
        from urllib.request import urlopen

        from quiver_tpu.telemetry.export import start_http_server

        srv = start_http_server()
        try:
            # unmanaged process: healthy by definition
            doc = json.loads(urlopen(f"{srv.url}/healthz",
                                     timeout=5).read())
            assert doc["ready"] and not doc["managed"]
            mgr = RecoveryManager(
                str(tmp_path / "r"),
                graph_factory=lambda: StreamingGraph(
                    _ring_topo(), delta_capacity=512))
            mgr.boot_degraded()
            with pytest.raises(HTTPError) as ei:
                urlopen(f"{srv.url}/healthz", timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["state"] == "replaying" and body["stale"]
            mgr.finish_boot()
            doc = json.loads(urlopen(f"{srv.url}/healthz",
                                     timeout=5).read())
            assert doc["ready"] and doc["state"] == "serving"
            mgr.close()
        finally:
            srv.close()
