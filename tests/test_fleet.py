"""Elastic replicated serving fleet suite (``make fleet``).

Covers the four fleet modules plus their satellites:

  * consistent-hash ring — determinism across instances, bounded
    reshuffle on member change, distinct preference walks;
  * membership directory — announce/scan/deregister, heartbeat
    freshness, tolerance of torn/garbage records, leader election by
    freshest heartbeat;
  * WAL follower — live shipping onto a follower graph, abort
    holdback + late-abort resync, the three tailing edge cases the
    issue names (open mid-segment-rotation, torn tail waits instead of
    erroring, leader ``truncate_through`` resyncs instead of
    stranding), staleness gauges;
  * replica lifecycle + router — warm join ladder, per-instance
    ``/healthz``+``/metrics`` on ephemeral ports (two replicas on one
    host), drain choreography, dead-replica re-dispatch with zero lost
    answers, typed-shed answers never retried, typed
    ``NoReplicaAvailable`` when the fleet is empty, ``/debug/fleet``;
  * chaos points — ``fleet.route`` fires deterministically from a
    seeded plan;
  * the failover harness — ``benchmarks/fleet_chaos.py`` smoke report
    asserted end to end (marked slow: three real child processes).
"""

import io
import json
import os
import socket
import time
import urllib.request

import numpy as np
import pytest

from quiver_tpu import telemetry
from quiver_tpu.fleet import (FLEET_STATES, ConsistentHashRing,
                              FleetReplica, FleetRouter,
                              MembershipDirectory, ReplicaInfo,
                              WALFollower, fleet_status)
from quiver_tpu.recovery import blockio
from quiver_tpu.recovery.wal import (WriteAheadLog, encode_abort,
                                     encode_edge_op)
from quiver_tpu.resilience import chaos
from quiver_tpu.resilience.breaker import reset as breakers_reset
from quiver_tpu.resilience.errors import (ChaosFault, LoadShed,
                                          NoReplicaAvailable)
from quiver_tpu.stream import StreamingGraph
from quiver_tpu.utils.topology import CSRTopo

pytestmark = pytest.mark.fleet

N_NODES = 64


def _topo():
    src = np.arange(N_NODES, dtype=np.int64)
    dst = (src + 1) % N_NODES
    return CSRTopo(edge_index=np.stack([src, dst]))


def _graph():
    return StreamingGraph(_topo(), delta_capacity=4096)


def counter_value(name, **labels):
    from quiver_tpu.telemetry.registry import metric_key

    return telemetry.snapshot()["counters"].get(
        metric_key(name, labels), 0)


def gauge_value(name, **labels):
    from quiver_tpu.telemetry.registry import metric_key

    return telemetry.snapshot()["gauges"].get(metric_key(name, labels))


@pytest.fixture(autouse=True)
def _clean():
    yield
    chaos.uninstall()
    breakers_reset()


# ------------------------------------------------------------- ring
class TestConsistentHashRing:
    def test_deterministic_across_instances(self):
        a, b = ConsistentHashRing(vnodes=32), ConsistentHashRing(vnodes=32)
        a.set_members(["r0", "r1", "r2"])
        b.set_members(["r2", "r0", "r1"])  # order must not matter
        for p in range(32):
            assert a.preference(p) == b.preference(p)

    def test_preference_walk_distinct_and_complete(self):
        r = ConsistentHashRing(vnodes=16)
        r.set_members(["a", "b", "c"])
        for p in range(16):
            prefs = r.preference(p)
            assert sorted(prefs) == ["a", "b", "c"]
            assert len(set(prefs)) == 3
        assert r.preference(0, n=2) == r.preference(0)[:2]

    def test_member_change_reshuffles_partially(self):
        r = ConsistentHashRing(vnodes=64)
        r.set_members(["a", "b", "c"])
        before = {p: r.preference(p, 1)[0] for p in range(256)}
        r.set_members(["a", "b", "c", "d"])
        after = {p: r.preference(p, 1)[0] for p in range(256)}
        moved = sum(1 for p in before if after[p] != before[p])
        # consistent hashing: only partitions adopted by the new member
        # move — everything that moved must have moved TO d, and the
        # move fraction stays near 1/N, never a full reshuffle
        assert all(after[p] == "d" for p in before if after[p] != before[p])
        assert 0 < moved < 128

    def test_empty_ring(self):
        assert ConsistentHashRing(vnodes=4).preference(0) == []


# ------------------------------------------------------- membership
class TestMembership:
    def test_announce_scan_deregister(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=5.0)
        d.announce(ReplicaInfo("r0", state="serving", port=1234,
                               role="leader"))
        d.announce(ReplicaInfo("r1", state="booting", port=1235))
        got = d.replicas()
        assert [r.replica_id for r in got] == ["r0", "r1"]
        assert d.get("r0").port == 1234
        assert d.leader().replica_id == "r0"
        assert d.deregister("r1") is True
        assert d.deregister("r1") is False
        assert [r.replica_id for r in d.replicas()] == ["r0"]

    def test_freshness_window(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=0.05)
        d.announce(ReplicaInfo("r0", state="serving"))
        assert [r.replica_id for r in d.replicas(fresh_only=True)] \
            == ["r0"]
        time.sleep(0.1)
        assert d.replicas(fresh_only=True) == []
        # stale records remain visible to operators
        assert [r.replica_id for r in d.replicas()] == ["r0"]
        assert gauge_value("fleet_replicas_total", state="serving") == 0.0

    def test_garbage_record_skipped_not_fatal(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=5.0)
        d.announce(ReplicaInfo("r0", state="serving"))
        (tmp_path / "replica-torn.json").write_bytes(b'{"repl')
        before = counter_value("fleet_membership_parse_errors_total")
        assert [r.replica_id for r in d.replicas()] == ["r0"]
        assert counter_value(
            "fleet_membership_parse_errors_total") == before + 1

    def test_unknown_state_rejected(self, tmp_path):
        d = MembershipDirectory(tmp_path)
        with pytest.raises(ValueError, match="unknown fleet state"):
            d.announce(ReplicaInfo("r0", state="zombie"))

    def test_states_ladder(self):
        assert FLEET_STATES == ("booting", "replaying", "warming",
                                "serving", "draining")

    def test_status_document(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=5.0)
        d.announce(ReplicaInfo("r0", state="serving"))
        doc = d.status()
        assert doc["replicas"][0]["fresh"] is True
        assert doc["replicas"][0]["heartbeat_age_s"] >= 0.0


# ----------------------------------------------------- WAL follower
class _Tail:
    """Follower-side sink recording every applied record."""

    def __init__(self):
        self.applied = []

    def __call__(self, lsn, op, src, dst, ts):
        self.applied.append((lsn, op, list(map(int, src)),
                             list(map(int, dst))))


def _follower(wal_dir, tail, **kw):
    kw.setdefault("grace_s", 30.0)  # holdback resolves via successors
    kw.setdefault("name", "t")
    return WALFollower(str(wal_dir), apply_fn=tail, **kw)


class TestWALFollower:
    def test_ships_committed_records(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        for i in range(5):
            w.append(encode_edge_op("add", [i], [i + 1]))
        tail = _Tail()
        f = _follower(tmp_path / "wal", tail)
        f.poll_once()
        # newest record held back (abort holdback), 4 committed
        assert [lsn for lsn, *_ in tail.applied] == [0, 1, 2, 3]
        assert f.status()["staleness_lsn"] == 1
        w.append(encode_edge_op("add", [9], [10]))
        f.poll_once()  # successor slot proves no abort for lsn 4
        assert [lsn for lsn, *_ in tail.applied] == [0, 1, 2, 3, 4]
        w.close()

    def test_grace_expiry_commits_tail(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        w.append(encode_edge_op("add", [1], [2]))
        tail = _Tail()
        f = _follower(tmp_path / "wal", tail, grace_s=0.02)
        f.poll_once()
        assert tail.applied == []  # inside the grace window
        time.sleep(0.05)
        f.poll_once()
        assert [lsn for lsn, *_ in tail.applied] == [0]
        assert f.status()["staleness_lsn"] == 0
        w.close()

    def test_abort_holdback_skips_aborted_record(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        w.append(encode_edge_op("add", [1], [2]))      # lsn 0: commits
        w.append(encode_edge_op("add", [3], [4]))      # lsn 1: aborted
        w.append(encode_abort(1))                      # lsn 2
        w.append(encode_edge_op("add", [5], [6]))      # lsn 3: commits
        w.append(encode_edge_op("add", [7], [8]))      # lsn 4: successor
        tail = _Tail()
        before = counter_value("fleet_ship_aborted_total", replica="t")
        f = _follower(tmp_path / "wal", tail)
        f.poll_once()
        assert [lsn for lsn, *_ in tail.applied] == [0, 3]
        assert counter_value("fleet_ship_aborted_total",
                             replica="t") == before + 1
        assert f.applied_lsn == 3  # lsn 4 held pending a successor
        w.close()

    def test_late_abort_triggers_resync(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        w.append(encode_edge_op("add", [1], [2]))      # lsn 0
        tail = _Tail()
        resyncs = []

        def resync():
            # a real resync_fn restores the newest checkpoint; here the
            # checkpoint "covers" both records, so resume past them
            resyncs.append(True)
            return 2

        f = _follower(tmp_path / "wal", tail, grace_s=0.0,
                      resync_fn=resync)
        f.poll_once()  # grace 0: lsn 0 commits immediately
        assert [lsn for lsn, *_ in tail.applied] == [0]
        w.append(encode_abort(0))                      # late abort
        before = counter_value("fleet_ship_late_aborts_total",
                               replica="t")
        f.poll_once()
        assert resyncs == [True]
        assert counter_value("fleet_ship_late_aborts_total",
                             replica="t") == before + 1
        assert f.applied_lsn == 1  # resumed at the resync watermark
        w.close()

    def test_torn_tail_waits_instead_of_erroring(self, tmp_path):
        """Satellite: a torn tail is a write in progress — the follower
        must keep its offset and re-poll, never raise or misframe."""
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        w.append(encode_edge_op("add", [1], [2]))
        w.append(encode_edge_op("add", [3], [4]))
        seg = os.path.join(str(tmp_path / "wal"),
                           sorted(os.listdir(tmp_path / "wal"))[0])
        # frame the next record out-of-band and append only half of it:
        # exactly what a reader racing the leader's write() observes
        buf = io.BytesIO()
        blockio.write_record(buf, encode_edge_op("add", [5], [6]))
        frame = buf.getvalue()
        with open(seg, "ab") as fh:
            fh.write(frame[:len(frame) // 2])
        tail = _Tail()
        f = _follower(tmp_path / "wal", tail)
        before = counter_value("fleet_ship_torn_waits_total", replica="t")
        f.poll_once()
        f.poll_once()  # still torn: waits again, no error, no re-count
        assert [lsn for lsn, *_ in tail.applied] == [0]  # lsn 1 held
        assert counter_value("fleet_ship_torn_waits_total",
                             replica="t") == before + 1
        assert f.status()["last_error"] is None
        with open(seg, "ab") as fh:  # the leader finishes its write
            fh.write(frame[len(frame) // 2:])
        f.poll_once()
        assert [lsn for lsn, *_ in tail.applied] == [0, 1]
        w.close()

    def test_opens_mid_segment_rotation(self, tmp_path):
        """Satellite: a follower whose start watermark lands inside a
        sealed middle segment repositions correctly and ships across
        the rotation boundary."""
        w = WriteAheadLog(tmp_path / "wal", fsync="always",
                          segment_bytes=1)  # roll after every record
        for i in range(6):
            w.append(encode_edge_op("add", [i], [i + 1]))
        assert len(os.listdir(tmp_path / "wal")) > 1
        tail = _Tail()
        f = _follower(tmp_path / "wal", tail, start_lsn=2)
        f.poll_once()
        assert [lsn for lsn, *_ in tail.applied] == [3, 4]  # 5 held
        w.append(encode_edge_op("add", [9], [9]))
        f.poll_once()
        assert [lsn for lsn, *_ in tail.applied] == [3, 4, 5]
        w.close()

    def test_truncate_through_resyncs_not_strands(self, tmp_path):
        """Satellite: leader checkpoint + ``truncate_through`` deletes
        segments a lagging follower needed — it must resync from the
        checkpoint watermark, not strand or silently skip."""
        w = WriteAheadLog(tmp_path / "wal", fsync="always",
                          segment_bytes=1)
        for i in range(6):
            w.append(encode_edge_op("add", [i], [i + 1]))
        # barrier checkpoint covered lsns 0..3; the log drops them
        w.truncate_through(3)
        tail = _Tail()
        resyncs = []

        def resync():
            resyncs.append(True)
            return 4  # checkpoint watermark + 1

        f = _follower(tmp_path / "wal", tail, start_lsn=-1,
                      resync_fn=resync)
        before = counter_value("fleet_ship_resyncs_total", replica="t")
        f.poll_once()
        assert resyncs == [True]
        assert counter_value("fleet_ship_resyncs_total",
                             replica="t") == before + 1
        assert [lsn for lsn, *_ in tail.applied] == [4]  # 5 held
        assert f.status()["resyncs"] == 1
        # without a resync_fn the same situation is a loud error
        f2 = _follower(tmp_path / "wal", _Tail(), start_lsn=-1)
        from quiver_tpu.recovery.errors import WALError

        with pytest.raises(WALError, match="stranded"):
            f2.poll_once()
        w.close()

    def test_staleness_gauges_published(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")
        w.append(encode_edge_op("add", [1], [2]))
        f = _follower(tmp_path / "wal", _Tail(), name="stale-t")
        f.poll_once()
        assert gauge_value("fleet_replica_staleness_lsn",
                           replica="stale-t") == 1.0
        assert gauge_value("fleet_replica_staleness_seconds",
                           replica="stale-t") >= 0.0
        w.append(encode_edge_op("add", [3], [4]))
        w.append(encode_edge_op("add", [5], [6]))
        time.sleep(0.0)
        f.poll_once()
        assert f.status()["applied_lsn"] == 1
        w.close()

    def test_thread_loop_survives_apply_errors(self, tmp_path):
        w = WriteAheadLog(tmp_path / "wal", fsync="always")

        def bad_apply(*a):
            raise RuntimeError("apply exploded")

        w.append(encode_edge_op("add", [1], [2]))
        w.append(encode_edge_op("add", [3], [4]))
        f = WALFollower(str(tmp_path / "wal"), apply_fn=bad_apply,
                        grace_s=0.0, poll_interval_s=0.01,
                        name="bad").start()
        deadline = time.time() + 5
        while time.time() < deadline and \
                f.status()["last_error"] is None:
            time.sleep(0.01)
        assert "apply exploded" in (f.status()["last_error"] or "")
        assert f.is_running()
        f.stop()
        assert not f.is_running()
        w.close()


# ------------------------------------------- replica + router (e2e)
@pytest.fixture
def fleet(tmp_path):
    """One in-process leader + one follower over a shared root, plus a
    router; tears everything down in reverse order."""
    import quiver_tpu.config as config_mod

    cfg = config_mod.get_config()
    saved = {k: getattr(cfg, k) for k in
             ("fleet_ship_poll_ms", "fleet_ship_grace_ms")}
    config_mod.update(fleet_ship_poll_ms=10.0, fleet_ship_grace_ms=60.0)
    root = str(tmp_path / "dur")
    fdir = str(tmp_path / "fleet")
    members = []

    def spawn(rid, role, **kw):
        rep = FleetReplica(rid, fleet_dir=fdir, root=root,
                           graph_factory=_graph, role=role,
                           heartbeat_s=0.1, **kw).boot()
        members.append(rep)
        return rep

    directory = MembershipDirectory(fdir, heartbeat_timeout_s=2.0)
    routers = []

    def make_router(**kw):
        kw.setdefault("scan_ttl_s", 0.0)
        kw.setdefault("request_timeout_s", 1.0)
        r = FleetRouter(directory, **kw)
        routers.append(r)
        return r

    yield type("F", (), {"spawn": staticmethod(spawn),
                         "router": staticmethod(make_router),
                         "directory": directory, "root": root,
                         "fleet_dir": fdir, "members": members})
    for r in routers:
        r.close()
    for rep in reversed(members):
        rep.stop()
    config_mod.update(**saved)


def _ingest(leader, n, start=0):
    for i in range(start, start + n):
        leader.lane.submit([i % N_NODES], [(i * 7 + 3) % N_NODES])
    for _ in range(n):
        _u, res = leader.lane.results.get(timeout=10)
        assert not isinstance(res, Exception), res


class TestFleetEndToEnd:
    def test_join_ladder_and_replication(self, fleet):
        leader = fleet.spawn("r0", "leader")
        _ingest(leader, 10)
        leader.manager.checkpoint(timeout=10)
        follower = fleet.spawn("r1", "follower")
        assert follower.state == "serving"
        assert follower.graph.version == leader.graph.version
        # live shipping: new leader writes reach the follower
        _ingest(leader, 10, start=10)
        deadline = time.time() + 10
        while time.time() < deadline and \
                follower.graph.version != leader.graph.version:
            time.sleep(0.02)
        assert follower.graph.version == leader.graph.version
        assert gauge_value("fleet_join_seconds", replica="r1") > 0.0
        info = fleet.directory.get("r1")
        assert info.state == "serving" and info.role == "follower"

    def test_two_replicas_metrics_coexist_one_host(self, fleet):
        """Satellite: two replicas' /healthz + /metrics must coexist on
        one host via ephemeral ports, each reporting ITS OWN ladder."""
        leader = fleet.spawn("r0", "leader")
        leader.manager.checkpoint(timeout=10)
        follower = fleet.spawn("r1", "follower")
        m0, m1 = leader.expose_metrics(), follower.expose_metrics()
        assert m0.port != m1.port and m0.port > 0 and m1.port > 0
        docs = {}
        for port in (m0.port, m1.port):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
                doc = json.loads(r.read())
                docs[doc["replica_id"]] = doc
                assert r.status == 200
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                assert r.status == 200
        assert docs["r0"]["role"] == "leader"
        assert docs["r1"]["role"] == "follower"
        assert "staleness_lsn" in docs["r1"]

    def test_router_routes_and_debug_fleet(self, fleet):
        leader = fleet.spawn("r0", "leader")
        leader.manager.checkpoint(timeout=10)
        fleet.spawn("r1", "follower")
        router = fleet.router()
        for i in range(20):
            reply = router.request([i, i + 1], seq=i)
            assert reply["status"] == "ok"
            assert reply["seq"] == i
            assert reply["replica"] in ("r0", "r1")
        served = {rid: counter_value("fleet_router_requests_total",
                                     replica=rid, status="ok")
                  for rid in ("r0", "r1")}
        assert sum(served.values()) >= 20
        doc = fleet_status()
        assert doc["active"] is True
        assert sorted(doc["eligible"]) == ["r0", "r1"]
        ms = leader.expose_metrics()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{ms.port}/debug/fleet",
                timeout=5) as r:
            served_doc = json.loads(r.read())
        assert served_doc["active"] is True
        assert served_doc["membership"]["replicas"]

    def test_dead_replica_redispatch_zero_lost(self, fleet):
        """A replica that vanishes without drain: its requests must be
        re-dispatched and answered, never lost."""
        leader = fleet.spawn("r0", "leader")
        leader.manager.checkpoint(timeout=10)
        follower = fleet.spawn("r1", "follower")
        # wide partition space so the 2-member ring gives r1 ownership
        # of some partitions (8 partitions can all land on one member)
        router = fleet.router(partitions=64)
        # hard-stop the follower's endpoint WITHOUT deregistering —
        # membership still says serving, exactly like a kill -9
        follower._server.shutdown()
        follower._server.server_close()
        answered = 0
        for i in range(32):
            reply = router.request([i], seq=i)
            assert reply["status"] == "ok"
            assert reply["replica"] == "r0"
            answered += 1
        assert answered == 32
        redis = counter_value("fleet_router_redispatch_total",
                              replica="r1")
        assert redis > 0

    def test_shed_is_an_answer_not_a_retry(self, fleet):
        def shedding_service(ids, tenant):
            raise LoadShed("saturated", lane="test")

        leader = fleet.spawn("r0", "leader",
                             service_fn=shedding_service)
        router = fleet.router()
        before = counter_value("fleet_router_redispatch_total",
                               replica="r0")
        reply = router.request([1])
        assert reply["status"] == "shed"
        assert reply["error"] == "LoadShed"
        # a typed shed is final — no re-dispatch happened for it
        assert counter_value("fleet_router_redispatch_total",
                             replica="r0") == before

    def test_empty_fleet_is_typed_answer(self, fleet):
        router = fleet.router(route_retries=1)
        with pytest.raises(NoReplicaAvailable):
            router.request([1])
        assert counter_value("fleet_router_unroutable_total") >= 1

    def test_drain_stops_admission_then_deregisters(self, fleet):
        leader = fleet.spawn("r0", "leader")
        leader.manager.checkpoint(timeout=10)
        follower = fleet.spawn("r1", "follower")
        assert fleet.directory.get("r1") is not None
        follower.drain(timeout=5)
        assert follower.state == "draining"
        assert fleet.directory.get("r1") is None
        # direct dispatch to a draining replica is an honest refusal
        with socket.create_connection(("127.0.0.1", follower.port),
                                      timeout=5) as conn:
            conn.sendall(b'{"ids": [1]}\n')
            with conn.makefile("rb") as fh:
                reply = json.loads(fh.readline())
        assert reply["status"] == "unavailable"
        # the router no longer sees it
        router = fleet.router()
        for i in range(8):
            assert router.request([i])["replica"] == "r0"

    def test_chaos_point_route_fires_from_seeded_plan(self, fleet):
        leader = fleet.spawn("r0", "leader")
        router = fleet.router()
        assert router.request([1])["status"] == "ok"
        chaos.install(chaos.ChaosPlan(seed=7).fail(
            "fleet.route", exc=ChaosFault("fleet.route", 0), times=1))
        with pytest.raises(ChaosFault):
            router.request([2])
        # deterministic: the plan spent its single shot
        assert router.request([3])["status"] == "ok"


# ------------------------------------------------- failover harness
@pytest.mark.slow
class TestFleetChaosHarness:
    def test_smoke_report_contract(self):
        from benchmarks.fleet_chaos import check, run_fleet_chaos

        report = run_fleet_chaos(smoke=True, seed=0)
        # zero lost answers across all phases, kill -9 confirmed
        assert report["lost_answers"] == 0
        assert report["failover"]["kill_returncode"] == -9
        for phase in ("baseline", "burst", "cool"):
            p = report["phases"][phase]
            assert p["offered"] == p["ok"] + p["shed"] + p["error"] \
                + p["unroutable"]
            assert p["unanswered"] == 0
        # warm rejoin through the shared compilation cache, staleness
        # back under the configured bound
        assert report["rejoin"]["pcache_hits"] > 0
        assert report["rejoin"]["within_bound"] is True
        # the non-latency acceptance criteria all hold
        assert [f for f in check(report) if "p99" not in f] == []


# ------------------------------------------- fleet autonomy satellites
class TestMembershipAutonomy:
    def test_leader_epoch_wins_and_conflict_counted(self, tmp_path):
        """Split-brain window: a deposed leader's still-fresh record
        must lose to the successor's higher epoch, and the overlap must
        be observable."""
        d = MembershipDirectory(str(tmp_path), heartbeat_timeout_s=5.0)
        d.announce(ReplicaInfo("old", state="serving", role="leader",
                               epoch=3))
        d.announce(ReplicaInfo("new", state="serving", role="leader",
                               epoch=4))
        before = counter_value("fleet_leader_conflicts_total")
        leader = d.leader()
        assert leader.replica_id == "new"
        assert counter_value("fleet_leader_conflicts_total") == before + 1
        # single fresh leader: no conflict tick
        d.deregister("old")
        mid = counter_value("fleet_leader_conflicts_total")
        assert d.leader().replica_id == "new"
        assert counter_value("fleet_leader_conflicts_total") == mid

    def test_epoch_roundtrip_and_legacy_default(self):
        info = ReplicaInfo("r0", epoch=7)
        assert ReplicaInfo.from_dict(info.to_dict()).epoch == 7
        legacy = info.to_dict()
        legacy.pop("epoch")  # a record from a pre-election build
        assert ReplicaInfo.from_dict(legacy).epoch == -1

    def test_record_unlinked_between_listdir_and_open(self, tmp_path,
                                                      monkeypatch):
        """Satellite: a record deregistered between the directory scan's
        listdir and its open must be skipped and counted, never fatal."""
        d = MembershipDirectory(str(tmp_path), heartbeat_timeout_s=5.0)
        d.announce(ReplicaInfo("real", state="serving"))
        real_listdir = os.listdir

        def ghost_listdir(path):
            return list(real_listdir(path)) + ["replica-ghost.json"]

        monkeypatch.setattr(os, "listdir", ghost_listdir)
        before = counter_value("fleet_membership_parse_errors_total")
        out = d.replicas()
        assert [r.replica_id for r in out] == ["real"]
        assert counter_value(
            "fleet_membership_parse_errors_total") == before + 1


class TestFleetAutonomySatellites:
    def test_draining_healthz_is_503_with_state(self, fleet):
        """Satellite: /healthz during drain answers 503 with the
        draining state in the body, so load balancers depool while
        operators still see a live, finishing process."""
        import urllib.error

        leader = fleet.spawn("r0", "leader")
        srv = leader.expose_metrics()
        leader.drain(timeout=5)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5)
        assert exc.value.code == 503
        body = json.loads(exc.value.read())
        assert body["state"] == "draining"
        assert body["ready"] is False

    def test_simultaneous_join_and_drain_consistent(self, fleet):
        """Satellite: a join stretched across a concurrent drain leaves
        router eligibility and ring membership consistent — the joiner
        in, the drained member out, nothing half-present."""
        import threading

        leader = fleet.spawn("r0", "leader")
        leader.manager.checkpoint(timeout=10)
        f1 = fleet.spawn("r1", "follower")
        router = fleet.router()
        router.refresh(force=True)
        assert sorted(router.ring.members) == ["r0", "r1"]
        # stretch r2's join window across r1's drain
        chaos.install(chaos.ChaosPlan(seed=3).delay(
            "fleet.join", delay_s=0.3, times=1))
        joined = {}

        def join():
            joined["rep"] = fleet.spawn("r2", "follower")

        t = threading.Thread(target=join)
        t.start()
        f1.drain(timeout=5)
        t.join(timeout=30)
        assert "rep" in joined and joined["rep"].state == "serving"
        assert fleet.directory.get("r1") is None
        router.refresh(force=True)
        assert sorted(router.ring.members) == ["r0", "r2"]
        with router._lock:
            eligible = sorted(router._eligible)
        assert eligible == ["r0", "r2"]
        for i in range(8):
            assert router.request([i])["replica"] in ("r0", "r2")
