"""Fleet observability plane suite (``make fleet``).

Covers ``quiver_tpu/fleet/federation.py`` and the cross-process trace
plumbing it joins together:

  * Prometheus text parsing — the round-trip twin of the exporter:
    hostile label values (backslashes, quotes, newlines, braces)
    survive ``render → parse`` exactly; malformed exposition counts
    parse errors and never raises out of a sweep;
  * federation math — counters summed, histograms merged bucket-wise,
    gauges min/max/avg, bounds mismatches dropped with a merge error,
    per-replica series re-keyed under a ``replica`` label;
  * clock alignment — ``estimate_offsets`` recovers known skews and
    the median rejects a pair torn by a scheduling stall;
  * merged timelines — one Perfetto-loadable document, one process
    track per member, per-track timestamps stay monotone after
    re-basing;
  * scrape loop — a 3-replica ``/metrics/fleet`` aggregate matches
    hand-computed sums; unreachable and garbage-serving targets tick
    their counters and leave the previous view standing;
  * cross-process tracing e2e — a routed request's reply carries the
    fleet trace_id, the router hop record and the replica flight
    record join at ``/debug/fleet/trace/<id>``;
  * the off path — federation off means no scraper thread, no
    ``fleet_federation_*`` metric keys, no trace stamped on the wire.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from urllib.parse import quote

import numpy as np
import pytest

from quiver_tpu import telemetry
from quiver_tpu.fleet import (FleetReplica, FleetRouter,
                              MembershipDirectory, ReplicaInfo)
from quiver_tpu.fleet.federation import (FleetFederation, estimate_offsets,
                                         federate, parse_prometheus_text,
                                         render_fleet_text)
from quiver_tpu.resilience import chaos
from quiver_tpu.resilience.breaker import reset as breakers_reset
from quiver_tpu.stream import StreamingGraph
from quiver_tpu.telemetry import timeline
from quiver_tpu.telemetry.export import (MetricsServer, _fmt_labels,
                                         to_prometheus_text)
from quiver_tpu.telemetry.registry import MetricsRegistry
from quiver_tpu.utils.topology import CSRTopo

pytestmark = pytest.mark.fleet

N_NODES = 64


def _topo():
    src = np.arange(N_NODES, dtype=np.int64)
    dst = (src + 1) % N_NODES
    return CSRTopo(edge_index=np.stack([src, dst]))


def _graph():
    return StreamingGraph(_topo(), delta_capacity=4096)


def counter_value(name, **labels):
    from quiver_tpu.telemetry.registry import metric_key

    return telemetry.snapshot()["counters"].get(
        metric_key(name, labels), 0)


@pytest.fixture(autouse=True)
def _clean():
    yield
    chaos.uninstall()
    breakers_reset()


def _key(name, **labels):
    return name, tuple(sorted(labels.items()))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --------------------------------------------- exposition parsing
class TestPrometheusParsing:
    def test_hostile_label_values_round_trip(self):
        # the exact adversarial shapes the exporter escapes: a value
        # that fakes a sample terminator, embedded quotes, backslashes,
        # braces, commas, and '=' — all must come back byte-identical
        hostile = {
            "tenant": 'gold"} 9\n',
            "path": "a\\b\\\\c",
            "expr": 'x{le="0.5",q=1}',
            "kv": "a=b,c=d",
        }
        text = ("# TYPE fleet_demo_total counter\n"
                f"fleet_demo_total{_fmt_labels(hostile)} 3\n")
        parsed, errors = parse_prometheus_text(text)
        assert errors == 0
        assert parsed["counters"][_key("fleet_demo_total", **hostile)] == 3.0

    def test_malformed_lines_count_errors_not_fatal(self):
        text = "\n".join([
            "# TYPE ok_total counter",
            "ok_total 7",
            "broken{unclosed=\"quote 1",     # unterminated label value
            "no_value_here",                 # missing sample value
            'bad_escape{k="a\\qb"} 1',       # \q is not a valid escape
            "name with spaces{} 1",          # invalid metric name
            "not_a_number{} zebra",          # unparsable value
            "\x00\x01\x02",                  # binary garbage
        ])
        parsed, errors = parse_prometheus_text(text)
        assert errors == 6
        assert parsed["counters"][_key("ok_total")] == 7.0

    def test_registry_exposition_round_trips(self):
        reg = MetricsRegistry()
        reg.counter("demo_requests_total", status="ok").inc(5)
        reg.gauge("demo_depth_level").set(3)
        h = reg.histogram("demo_gather_seconds", bounds=[0.1, 1.0])
        for v in (0.05, 0.5, 2.0):
            h.observe(v)
        parsed, errors = parse_prometheus_text(
            to_prometheus_text(reg.snapshot()))
        assert errors == 0
        assert parsed["counters"][_key("demo_requests_total",
                                       status="ok")] == 5.0
        assert parsed["gauges"][_key("demo_depth_level")] == 3.0
        hist = parsed["histograms"][_key("demo_gather_seconds")]
        assert hist["bounds"] == [0.1, 1.0]
        assert hist["counts"] == [1, 1, 1]
        assert hist["sum"] == pytest.approx(2.55)

    def test_untyped_samples_classify_by_suffix(self):
        parsed, errors = parse_prometheus_text(
            "requests_total 3\nqueue_depth 2\n")
        assert errors == 0
        assert _key("requests_total") in parsed["counters"]
        assert _key("queue_depth") in parsed["gauges"]

    def test_inconsistent_histogram_counts_one_error(self):
        # cumulative bucket counts must be monotone; a torn scrape that
        # violates that drops the family and counts ONE error
        text = "\n".join([
            "# TYPE h_seconds histogram",
            'h_seconds_bucket{le="0.1"} 5',
            'h_seconds_bucket{le="1"} 3',
            'h_seconds_bucket{le="+Inf"} 5',
            "h_seconds_sum 1.0",
            "h_seconds_count 5",
        ])
        parsed, errors = parse_prometheus_text(text)
        assert errors == 1
        assert parsed["histograms"] == {}

    def test_histogram_missing_inf_bucket_is_error(self):
        text = "\n".join([
            "# TYPE h_seconds histogram",
            'h_seconds_bucket{le="0.1"} 1',
            "h_seconds_sum 0.05",
            "h_seconds_count 1",
        ])
        parsed, errors = parse_prometheus_text(text)
        assert errors == 1
        assert parsed["histograms"] == {}

    def test_trailing_timestamp_ignored(self):
        parsed, errors = parse_prometheus_text(
            "a_total 5 1712345678000\n")
        assert errors == 0
        assert parsed["counters"][_key("a_total")] == 5.0


# --------------------------------------------------- federation math
def _scrape(counters=None, gauges=None, histograms=None):
    return {"counters": counters or {}, "gauges": gauges or {},
            "histograms": histograms or {}}


class TestFederate:
    def test_counters_sum_gauges_spread_histograms_merge(self):
        hk = _key("lat_seconds")
        view = federate({
            "r0": _scrape(
                counters={_key("req_total", status="ok"): 3.0},
                gauges={_key("depth_level"): 1.0},
                histograms={hk: {"bounds": [0.1, 1.0], "counts": [1, 0, 0],
                                 "sum": 0.05, "min": None, "max": None}}),
            "r1": _scrape(
                counters={_key("req_total", status="ok"): 5.0},
                gauges={_key("depth_level"): 2.0},
                histograms={hk: {"bounds": [0.1, 1.0], "counts": [0, 1, 1],
                                 "sum": 2.5, "min": None, "max": None}}),
            "r2": _scrape(
                counters={_key("req_total", status="ok"): 7.0},
                gauges={_key("depth_level"): 6.0}),
        })
        assert view["replicas"] == ["r0", "r1", "r2"]
        assert view["counters"][_key("req_total", status="ok")] == 15.0
        agg = view["gauges"][_key("depth_level")]
        assert (agg["min"], agg["max"], agg["avg"]) == (1.0, 6.0, 3.0)
        merged = view["histograms"][hk]
        assert merged["counts"] == [1, 1, 1]
        assert merged["sum"] == pytest.approx(2.55)
        assert view["merge_errors"] == 0
        # every source series is re-exported with replica attribution
        assert view["per_replica"]["counters"][
            _key("req_total", replica="r1", status="ok")] == 5.0
        assert view["per_replica"]["gauges"][
            _key("depth_level", replica="r2")] == 6.0

    def test_bounds_mismatch_drops_family_and_counts_error(self):
        hk = _key("lat_seconds")
        view = federate({
            "r0": _scrape(histograms={
                hk: {"bounds": [0.1, 1.0], "counts": [1, 0, 0],
                     "sum": 0.05, "min": None, "max": None}}),
            "r1": _scrape(histograms={
                hk: {"bounds": [0.5, 5.0], "counts": [1, 0, 0],
                     "sum": 0.2, "min": None, "max": None}}),
        })
        assert hk not in view["histograms"]
        assert view["merge_errors"] == 1

    def test_source_replica_label_wins(self):
        # shipping's staleness gauges are already replica-scoped at the
        # source; federation must not re-attribute them to the scraped
        # member
        view = federate({
            "scraper-side": _scrape(
                gauges={_key("fleet_replica_staleness_lsn",
                             replica="r7"): 42.0}),
        })
        assert view["per_replica"]["gauges"][
            _key("fleet_replica_staleness_lsn", replica="r7")] == 42.0

    def test_render_round_trips_through_parser(self):
        hostile = 'evil"} 1\n'
        view = federate({
            "r0": _scrape(counters={_key("req_total"): 3.0},
                          gauges={_key("depth_level",
                                       tenant=hostile): 1.0}),
            "r1": _scrape(counters={_key("req_total"): 4.0}),
        })
        parsed, errors = parse_prometheus_text(render_fleet_text(view))
        assert errors == 0
        assert parsed["counters"][_key("req_total")] == 7.0
        assert parsed["counters"][_key("req_total", replica="r0")] == 3.0
        # gauge aggregates carry an agg= label; summing gauges is a lie
        assert parsed["gauges"][_key("depth_level", agg="avg",
                                     tenant=hostile)] == 1.0
        assert parsed["gauges"][_key("depth_level", replica="r0",
                                     tenant=hostile)] == 1.0


# --------------------------------------------------- clock alignment
class TestClockOffsets:
    def test_known_skews_recovered(self):
        offsets = {"ra": 1234.5, "rb": -86.25}
        samples = {
            rid: [(p, p + off + jitter)
                  for p, jitter in ((10.0, 0.0002), (11.0, -0.0001),
                                    (12.0, 0.0003))]
            for rid, off in offsets.items()
        }
        got = estimate_offsets(samples)
        for rid, off in offsets.items():
            assert got[rid] == pytest.approx(off, abs=1e-3)

    def test_median_rejects_stalled_pair(self):
        # one heartbeat torn apart by a 5s scheduling stall between the
        # two stamps must not drag the estimate
        got = estimate_offsets({"r0": [(0.0, 100.0), (1.0, 101.0),
                                       (2.0, 102.0), (3.0, 108.0)]})
        assert got["r0"] == pytest.approx(100.0, abs=1e-9)

    def test_empty_samples_skipped(self):
        assert estimate_offsets({"r0": []}) == {}


# ------------------------------------------------- merged timelines
def _timeline_doc(tid, ts_list):
    events = [{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
               "args": {"name": "overwritten"}}]
    for i, ts in enumerate(ts_list):
        events.append({"name": f"stage{i}", "ph": "X", "pid": 1,
                       "tid": tid, "ts": ts, "dur": 2.0,
                       "cat": "serving", "args": {}})
    return {"traceEvents": events}


class TestMergedTimeline:
    def test_tracks_rebased_monotone_and_loadable(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=30.0)
        skews = {"ra": 1000.0, "rb": 2000.0}
        for rid, off in skews.items():
            d.announce(ReplicaInfo(
                rid, state="serving",
                detail={"metrics_port": 1, "clock_perf": 5.0,
                        "clock_wall": 5.0 + off}))
        fed = FleetFederation(d, watchdog=False)
        try:
            fed._harvest_clock_pairs()
            docs = {"ra": _timeline_doc(1, [10.0, 20.0, 30.0]),
                    "rb": _timeline_doc(2, [15.0, 25.0])}

            def fake_fetch(rid, host, mport, path, count_errors=True):
                assert path == "/debug/timeline"
                return docs[rid]

            fed._fetch_json = fake_fetch
            doc = fed.fleet_chrome_trace()
            assert doc["otherData"]["processes"] == ["router", "ra", "rb"]
            # the document must survive a JSON round trip (what
            # export_fleet writes and Perfetto loads)
            doc = json.loads(json.dumps(doc))
            tracks = {e["pid"]: e["args"]["name"]
                      for e in doc["traceEvents"]
                      if e.get("ph") == "M"
                      and e.get("name") == "process_name"}
            assert sorted(tracks.values()) == ["replica ra", "replica rb",
                                               "router"]
            by_pid = {}
            for e in doc["traceEvents"]:
                if e.get("ph") == "M":
                    continue
                by_pid.setdefault(e["pid"], []).append(e["ts"])
            for pid, ts_list in by_pid.items():
                assert ts_list == sorted(ts_list), \
                    f"track {tracks[pid]} not monotone"
            # re-based onto the wall clock: ra's first event lands at
            # its local ts plus the 1000s skew (in microseconds)
            pid_ra = next(p for p, n in tracks.items()
                          if n == "replica ra")
            assert by_pid[pid_ra][0] == pytest.approx(10.0 + 1000.0 * 1e6)
            # the provider hook: export_fleet writes the same document
            out = timeline.export_fleet(str(tmp_path / "fleet.json"))
            with open(out) as f:
                exported = json.load(f)
            assert len(exported["traceEvents"]) \
                == len(doc["traceEvents"])
        finally:
            fed.stop()

    def test_replica_without_offset_is_skipped_not_fatal(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=30.0)
        d.announce(ReplicaInfo("rc", state="serving",
                               detail={"metrics_port": 1}))
        fed = FleetFederation(d, watchdog=False)
        try:
            fed._fetch_json = lambda *a, **k: _timeline_doc(1, [1.0])
            doc = fed.fleet_chrome_trace()
            assert doc["otherData"]["processes"] == ["router"]
            assert doc["otherData"]["skipped"] == ["rc"]
        finally:
            fed.stop()


# ------------------------------------------------------ scrape loop
class TestFederationScrape:
    def test_three_replica_aggregate_matches_hand_sums(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=30.0)
        servers, regs = [], {}
        tracer = telemetry.get_tracer()
        counters = {"m0": 3, "m1": 5, "m2": 7}
        depths = {"m0": 1.0, "m1": 2.0, "m2": 6.0}
        observations = {"m0": 0.05, "m1": 0.5, "m2": 2.0}
        fed = None
        local = None
        try:
            for rid in ("m0", "m1", "m2"):
                reg = MetricsRegistry()
                reg.counter("demo_requests_total",
                            status="ok").inc(counters[rid])
                reg.gauge("demo_depth_level").set(depths[rid])
                reg.histogram("demo_gather_seconds",
                              bounds=[0.1, 1.0]).observe(observations[rid])
                srv = MetricsServer(registry=reg, tracer=tracer)
                servers.append(srv)
                regs[rid] = reg
                d.announce(ReplicaInfo(
                    rid, state="serving",
                    detail={"metrics_port": srv.port,
                            "clock_perf": time.perf_counter(),
                            "clock_wall": time.time()}))
            fed = FleetFederation(d)
            assert fed.scrape_once() == 3
            view = fed.fleet_view()
            assert view["counters"][_key("demo_requests_total",
                                         status="ok")] == 15.0
            agg = view["gauges"][_key("demo_depth_level")]
            assert (agg["min"], agg["max"], agg["avg"]) == (1.0, 6.0, 3.0)
            hist = view["histograms"][_key("demo_gather_seconds")]
            assert hist["counts"] == [1, 1, 1]
            assert hist["sum"] == pytest.approx(2.55)
            # the HTTP surface re-serves the same numbers: GET
            # /metrics/fleet from any MetricsServer in this process
            local = MetricsServer()
            with urllib.request.urlopen(
                    f"{local.url}/metrics/fleet", timeout=5) as r:
                assert r.status == 200
                parsed, errors = parse_prometheus_text(
                    r.read().decode())
            assert errors == 0
            assert parsed["counters"][_key("demo_requests_total",
                                           status="ok")] == 15.0
            assert parsed["counters"][_key("demo_requests_total",
                                           replica="m1",
                                           status="ok")] == 5.0
            with urllib.request.urlopen(
                    f"{local.url}/debug/fleet/summary", timeout=5) as r:
                summary = json.loads(r.read())
            assert summary["active"] is True
            assert all(summary["replicas"][rid]["ok"]
                       for rid in ("m0", "m1", "m2"))
            assert set(summary["offsets_s"]) == {"m0", "m1", "m2"}
            assert "slo" in summary
            for rid in ("m0", "m1", "m2"):
                assert counter_value("fleet_federation_scrapes_total",
                                     replica=rid) >= 1
        finally:
            if fed is not None:
                fed.stop()
            if local is not None:
                local.close()
            for srv in servers:
                srv.close()

    def test_unreachable_target_ticks_scrape_errors(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=30.0)
        d.announce(ReplicaInfo("gone", state="serving",
                               detail={"metrics_port": _free_port()}))
        fed = FleetFederation(d, watchdog=False)
        try:
            before = counter_value("fleet_federation_scrape_errors_total",
                                   replica="gone")
            assert fed.scrape_once() == 0
            assert counter_value("fleet_federation_scrape_errors_total",
                                 replica="gone") == before + 1
            # the sweep completed and left a (empty) view standing
            assert fed.fleet_view()["replicas"] == []
            assert fed.summary()["replicas"]["gone"]["ok"] is False
        finally:
            fed.stop()

    def test_garbage_scrape_ticks_parse_errors_not_crash(self, tmp_path):
        d = MembershipDirectory(tmp_path, heartbeat_timeout_s=30.0)
        d.announce(ReplicaInfo("bad", state="serving",
                               detail={"metrics_port": 1}))
        fed = FleetFederation(d, watchdog=False)
        try:
            fed._fetch = lambda rid, url, count_errors=True: (
                b'this is { not prometheus\nx{y="z 1\n\x00\xff ok_total 1')
            before = counter_value("fleet_federation_parse_errors_total")
            assert fed.scrape_once() == 1  # scraped, degraded, survived
            assert counter_value(
                "fleet_federation_parse_errors_total") > before
            assert fed.summary()["replicas"]["bad"]["parse_errors"] > 0
        finally:
            fed.stop()


# -------------------------------------- cross-process tracing (e2e)
@pytest.fixture
def traced_fleet(tmp_path):
    """One in-process leader behind a federation-enabled router."""
    import quiver_tpu.config as config_mod

    cfg = config_mod.get_config()
    saved = {k: getattr(cfg, k) for k in
             ("fleet_ship_poll_ms", "fleet_ship_grace_ms")}
    config_mod.update(fleet_ship_poll_ms=10.0, fleet_ship_grace_ms=60.0)
    root = str(tmp_path / "dur")
    fdir = str(tmp_path / "fleet")
    leader = FleetReplica("r0", fleet_dir=fdir, root=root,
                          graph_factory=_graph, role="leader",
                          heartbeat_s=0.1).boot()
    directory = MembershipDirectory(fdir, heartbeat_timeout_s=2.0)
    router = FleetRouter(directory, scan_ttl_s=0.0, request_timeout_s=1.0,
                         federation=True)
    routers = [router]

    def make_router(**kw):
        kw.setdefault("scan_ttl_s", 0.0)
        kw.setdefault("request_timeout_s", 1.0)
        r = FleetRouter(directory, **kw)
        routers.append(r)
        return r

    yield type("F", (), {"leader": leader, "router": router,
                         "directory": directory,
                         "make_router": staticmethod(make_router)})
    for r in routers:
        r.close()
    leader.stop()
    config_mod.update(**saved)


def _wait_metrics_port(fed, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fed.targets():
            return
        time.sleep(0.05)
    raise AssertionError("replica never published its metrics port")


class TestFleetTraceEndToEnd:
    def test_reply_carries_fleet_qualified_trace_id(self, traced_fleet):
        reply = traced_fleet.router.request([1, 2], seq=0)
        assert reply["status"] == "ok"
        tid = reply["trace_id"]
        assert tid.startswith(traced_fleet.router.origin + ":")

    def test_hop_record_joins_replica_flight_record(self, traced_fleet):
        leader, router = traced_fleet.leader, traced_fleet.router
        ms = leader.expose_metrics()
        _wait_metrics_port(router.federation)
        reply = router.request([3, 4], seq=1)
        tid = reply["trace_id"]
        hop = router.hop_record(tid)
        assert hop is not None
        assert hop["status"] == "ok"
        assert hop["origin"] == router.origin
        assert hop["e2e_seconds"] >= 0.0
        assert [a["replica"] for a in hop["attempts"]] == ["r0"]
        assert hop["attempts"][0]["outcome"] == "ok"
        # the reconstruction joins that hop with the replica-side
        # flight record fetched over the replica's own debug endpoint
        doc = router.federation.reconstruct(tid)
        assert doc["found"] is True
        assert doc["router"]["trace_id"] == tid
        record = doc["replicas"]["r0"]
        assert record["trace_id"] == tid
        names = [e["name"] for e in record["events"]]
        assert "replica.queue" in names
        # ... and is served at GET /debug/fleet/trace/<id> (the id is
        # origin-qualified, so it travels percent-encoded)
        url = f"{ms.url}/debug/fleet/trace/{quote(tid, safe='')}"
        with urllib.request.urlopen(url, timeout=5) as r:
            assert r.status == 200
            served = json.loads(r.read())
        assert served["trace_id"] == tid
        assert served["found"] is True

    def test_unknown_trace_id_is_404(self, traced_fleet):
        ms = traced_fleet.leader.expose_metrics()
        url = (f"{ms.url}/debug/fleet/trace/"
               f"{quote('rtr-0:dead-beef', safe='')}")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=5)
        assert ei.value.code == 404

    def test_hop_ring_is_bounded(self, traced_fleet):
        import quiver_tpu.config as config_mod

        saved = config_mod.get_config().fleet_trace_ring
        config_mod.update(fleet_trace_ring=4)
        try:
            router = traced_fleet.make_router(federation=True)
            for i in range(10):
                assert router.request([i], seq=i)["status"] == "ok"
            assert router.hop_count() <= 4
            # the newest records survived, the oldest aged out
            kept = [h["trace_id"] for h in router.hop_records()]
            assert len(kept) == 4
        finally:
            config_mod.update(fleet_trace_ring=saved)


# ----------------------------------------------------- the off path
class TestFederationOff:
    def test_off_path_is_inert(self, traced_fleet):
        names_before = {t.name for t in threading.enumerate()}
        snap = telemetry.snapshot()
        keys_before = (set(snap["counters"]) | set(snap["gauges"])
                       | set(snap["histograms"]))
        router = traced_fleet.make_router(federation=False)
        assert router.federation is None
        assert router.federation_enabled is False
        for i in range(5):
            reply = router.request([i, i + 1], seq=i)
            assert reply["status"] == "ok"
            # no trace stamped on the wire, so the replica has nothing
            # to rehydrate and the reply carries no trace_id
            assert "trace_id" not in reply
        assert router.hop_count() == 0
        assert router.start_federation() is router  # documented no-op
        new_threads = {t.name for t in threading.enumerate()} \
            - names_before
        assert not [n for n in new_threads if "federation" in n]
        snap = telemetry.snapshot()
        new_keys = (set(snap["counters"]) | set(snap["gauges"])
                    | set(snap["histograms"])) - keys_before
        assert not [k for k in new_keys
                    if k.startswith("fleet_federation")]

    def test_default_config_is_off(self, traced_fleet):
        # cfg.fleet_federation defaults to "off": a router constructed
        # without the kwarg resolves the flag ONCE and stays inert
        router = traced_fleet.make_router()
        assert router.federation_enabled is False
        assert router.federation is None
