"""Row-sharded distributed sampling over the 8-device virtual mesh."""

import numpy as np
import jax
import pytest

from quiver_tpu.dist.sampler import DistGraphSampler, shard_csr_by_rows
from quiver_tpu.utils.mesh import make_mesh


def test_shard_csr_by_rows(small_graph):
    row_starts, lips, lids = shard_csr_by_rows(small_graph, 4)
    assert row_starts[0] == 0 and row_starts[-1] == small_graph.node_count
    # every edge lands in exactly one shard, contiguous rebuild matches
    rebuilt = np.concatenate(lids)
    np.testing.assert_array_equal(rebuilt, small_graph.indices)
    for s in range(4):
        lo, hi = row_starts[s], row_starts[s + 1]
        np.testing.assert_array_equal(
            lips[s],
            small_graph.indptr[lo: hi + 1] - small_graph.indptr[lo],
        )



def _assert_shard_edges_real(small_graph, seeds, n_id, blk, k):
    """Shared ground-truth check: every masked neighbor of every seed on
    every shard is a real edge of the graph."""
    n_id = np.asarray(n_id)
    local = np.asarray(blk.nbr_local)
    m = np.asarray(blk.mask)
    D, B = seeds.shape
    for d in range(D):
        for b in range(B):
            tgt = seeds[d, b]
            row = set(small_graph.indices[
                small_graph.indptr[tgt]: small_graph.indptr[tgt + 1]
            ].tolist())
            for j in range(local.shape[-1]):
                if m[d, b, j]:
                    assert n_id[d, local[d, b, j]] in row


def test_dist_sampler_edges_real(small_graph):
    mesh = make_mesh(("data",))
    s = DistGraphSampler(small_graph, mesh, sizes=[4, 3])
    rng = np.random.default_rng(0)
    B = 16
    seeds = rng.integers(0, small_graph.node_count, (8, B))
    n_id, n_mask, num, blocks = s.sample(seeds, key=7)
    n_id = np.asarray(n_id)
    n_mask = np.asarray(n_mask)
    assert n_id.shape[0] == 8
    # seeds occupy the frontier prefix per shard
    np.testing.assert_array_equal(n_id[:, :B], seeds)
    # spot-check sampled edges against ground truth on each shard
    blk = blocks[-1]  # innermost hop: targets = seeds
    for d in range(8):
        assert int(np.asarray(blk.num_targets)[d]) == B
        local = np.asarray(blk.nbr_local)[d]
        m = np.asarray(blk.mask)[d]
        for b in range(B):
            tgt = seeds[d, b]
            deg = small_graph.indptr[tgt + 1] - small_graph.indptr[tgt]
            got = m[b].sum()
            assert got == min(deg, 4) or deg > 4  # cap overflow only
    _assert_shard_edges_real(small_graph, seeds, n_id, blk, 4)


def test_dist_sampler_counts_match_single(small_graph):
    """Per-seed neighbor counts equal min(deg, k) when caps are exact."""
    mesh = make_mesh(("data",))
    s = DistGraphSampler(small_graph, mesh, sizes=[5],
                         request_cap_frac=1.0)
    B = 8
    seeds = np.tile(np.arange(B)[None], (8, 1))
    n_id, n_mask, num, blocks = s.sample(seeds, key=3)
    deg = small_graph.degree
    counts = np.asarray(blocks[0].mask).sum(axis=2)
    for d in range(8):
        np.testing.assert_array_equal(
            counts[d], np.minimum(deg[:B], 5)
        )


def test_dist_sampler_cap_overflow_drops(small_graph):
    """With a tiny request cap, overflowed seeds sample zero neighbors
    (documented degradation, never corruption)."""
    mesh = make_mesh(("data",))
    s = DistGraphSampler(small_graph, mesh, sizes=[4],
                         request_cap_frac=0.01)
    # all seeds in one shard's row range -> guaranteed bucket pressure
    seeds = np.zeros((8, 32), dtype=np.int64)
    n_id, n_mask, num, blocks = s.sample(seeds, key=1)
    m = np.asarray(blocks[0].mask)
    counts = m.sum(axis=2)
    deg0 = int(small_graph.degree[0])
    # every served seed got min(deg, 4); the rest got zero
    assert set(np.unique(counts)) <= {0, min(deg0, 4)}
    # frontier entries for dropped seeds are masked invalid
    nm = np.asarray(n_mask)
    assert nm.shape[1] == 32 + 32 * 4


def test_dist_sampler_hash_rng_executes(small_graph):
    """sample_rng='hash' (the TPU ship default) through the row-sharded
    dist sampler's shard_map pipeline: deterministic per key, edges real."""
    mesh = make_mesh(("data",))
    s = DistGraphSampler(small_graph, mesh, sizes=[4, 3],
                         sample_rng="hash")
    assert s.sample_rng == "hash"
    seeds = np.random.default_rng(1).integers(
        0, small_graph.node_count, (8, 8))
    n_id_a, mask_a, _, blocks = s.sample(seeds, key=11)
    n_id_b, mask_b, _, _ = s.sample(seeds, key=11)
    np.testing.assert_array_equal(np.asarray(n_id_a), np.asarray(n_id_b))
    np.testing.assert_array_equal(np.asarray(mask_a), np.asarray(mask_b))
    _assert_shard_edges_real(small_graph, seeds, n_id_a, blocks[-1], 4)


# ---------------------------------------------------------------------------
# >2^31-edge regime (VERDICT r4 weak #2): the papers100M claim rests on the
# row-split plan never letting a shard's local edge count overflow int32.
# Planning works from indptr alone, so the test builds a synthetic indptr
# from degrees without materializing an edge array.


def _big_indptr(n_nodes=1024, deg=4_300_000):
    indptr = np.arange(n_nodes + 1, dtype=np.int64) * deg
    assert indptr[-1] > 2**31  # ~4.4B edges
    return indptr


def test_plan_row_shards_raises_on_int32_overflow():
    from quiver_tpu.dist.sampler import plan_row_shards

    indptr = _big_indptr()
    with pytest.raises(ValueError, match="shard"):
        plan_row_shards(indptr, 2)  # ~2.2B edges/shard > 2^31


def test_plan_row_shards_big_graph_offsets():
    from quiver_tpu.dist.sampler import plan_row_shards

    indptr = _big_indptr()
    row_starts = plan_row_shards(indptr, 4)
    assert row_starts[0] == 0 and row_starts[-1] == len(indptr) - 1
    assert np.all(np.diff(row_starts) > 0)
    for s in range(4):
        lo, hi = row_starts[s], row_starts[s + 1]
        local_edges = int(indptr[hi] - indptr[lo])
        assert local_edges < 2**31
        # rebased local offsets stay int32-representable end to end
        local = indptr[lo: hi + 1] - indptr[lo]
        assert local[-1] == local_edges and local[-1] < 2**31


def test_dist_sampler_padded_indptr_is_monotone(small_graph):
    """Padded per-shard indptr rows must repeat the final offset, not
    read zero (zero padding makes padded rows look negative-degree —
    masked today, but a trap; mirror uva.py's edge-value padding)."""
    mesh = make_mesh(("data",))
    s = DistGraphSampler(small_graph, mesh, sizes=[3])
    ip = np.asarray(s.indptr_sh)
    for row in ip:
        assert np.all(np.diff(row.astype(np.int64)) >= 0)


def test_dist_sampler_degrades_pwindow_to_blocked(small_graph):
    """pallas_call outputs lack vma annotations under shard_map, so a
    tuned/env pwindow pick must degrade to the equivalent XLA blocked
    mode inside DistGraphSampler instead of failing at trace time."""
    mesh = make_mesh(("data",))
    s = DistGraphSampler(small_graph, mesh, sizes=[3],
                         gather_mode="pwindow:2", sample_rng="hash")
    assert s.gather_mode == "blocked:2"
    n_id, n_mask, num, blocks = s.sample(
        np.arange(16).reshape(8, 2) % small_graph.node_count, key=5)
    assert np.asarray(n_id).shape[0] == 8


def test_dist_sampler_degrades_all_pallas_modes(small_graph):
    mesh = make_mesh(("data",))
    for gm, want in (("pallas", "lanes"), ("lanes_fused", "lanes")):
        s = DistGraphSampler(small_graph, mesh, sizes=[3], gather_mode=gm)
        assert s.gather_mode == want, (gm, s.gather_mode)
