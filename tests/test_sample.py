"""Sampling property tests — the rebuild of the reference's C++ suite
(`is_sample_valid`, tests/cpp/test_quiver_cpp:33-50): sampled neighbors are
a subset of true neighbors, counts == min(deg, k), distinct when deg >= k.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu.ops.sample import sample_neighbors, to_ragged


def true_neighbors(topo, v):
    return set(topo.indices[topo.indptr[v]: topo.indptr[v + 1]].tolist())


@pytest.mark.parametrize("k", [1, 4, 16])
def test_sample_valid_subset(small_graph, k):
    indptr, indices = small_graph.to_device()
    seeds = np.arange(small_graph.node_count, dtype=np.int32)
    out = sample_neighbors(indptr, indices, jnp.asarray(seeds), k,
                           jax.random.PRNGKey(0))
    nbrs = np.asarray(out.nbrs)
    mask = np.asarray(out.mask)
    counts = np.asarray(out.counts)
    deg = small_graph.degree
    np.testing.assert_array_equal(counts, np.minimum(deg, k))
    for v in seeds:
        tn = true_neighbors(small_graph, v)
        got = nbrs[v][mask[v]].tolist()
        assert len(got) == min(deg[v], k)
        assert set(got) <= tn, (v, got, tn)
        # distinctness (without replacement)
        assert len(set(got)) == len(got)


def test_sample_masked_seeds(small_graph):
    indptr, indices = small_graph.to_device()
    seeds = jnp.asarray(np.array([1, 2, 3, 4], dtype=np.int32))
    sm = jnp.asarray(np.array([True, False, True, False]))
    out = sample_neighbors(indptr, indices, seeds, 4,
                           jax.random.PRNGKey(1), seed_mask=sm)
    counts = np.asarray(out.counts)
    assert counts[1] == 0 and counts[3] == 0
    assert not np.asarray(out.mask)[1].any()


def test_sample_randomness_covers_neighbors(small_graph):
    """Over many draws every neighbor of a high-degree node appears."""
    indptr, indices = small_graph.to_device()
    deg = small_graph.degree
    v = int(np.argmax(deg))
    k = max(2, int(deg[v]) // 2)
    seen = set()
    for i in range(50):
        out = sample_neighbors(indptr, indices,
                               jnp.asarray([v], dtype=jnp.int32), k,
                               jax.random.PRNGKey(i))
        seen |= set(np.asarray(out.nbrs)[0][np.asarray(out.mask)[0]].tolist())
    assert seen == true_neighbors(small_graph, v)


def test_sample_marginals_uniformish(small_graph):
    """Inclusion frequency of each neighbor ~ k/deg (chi-square-ish bound)."""
    indptr, indices = small_graph.to_device()
    deg = small_graph.degree
    v = int(np.argmax(deg))
    d = int(deg[v])
    k = d // 2
    trials = 400
    counts = {}
    for i in range(trials):
        out = sample_neighbors(indptr, indices,
                               jnp.asarray([v], dtype=jnp.int32), k,
                               jax.random.PRNGKey(1000 + i))
        for x in np.asarray(out.nbrs)[0][np.asarray(out.mask)[0]].tolist():
            counts[x] = counts.get(x, 0) + 1
    expect = trials * k / d
    for x, c in counts.items():
        assert abs(c - expect) < 6 * np.sqrt(expect), (x, c, expect)


def test_to_ragged_matches_reference_contract(small_graph):
    indptr, indices = small_graph.to_device()
    seeds = jnp.asarray(np.array([0, 1, 2, 3, 4], dtype=np.int32))
    out = sample_neighbors(indptr, indices, seeds, 3, jax.random.PRNGKey(2))
    flat, counts = to_ragged(out)
    flat, counts = np.asarray(flat), np.asarray(counts)
    off = 0
    nbrs = np.asarray(out.nbrs)
    mask = np.asarray(out.mask)
    for b in range(5):
        got = flat[off: off + counts[b]].tolist()
        assert got == nbrs[b][mask[b]].tolist()
        off += counts[b]
    assert off == len(flat)


def test_cal_neighbor_prob_exact():
    """Access-probability recurrence against hand-computed expectation."""
    import jax.numpy as jnp

    from quiver_tpu.ops.prob import cal_neighbor_prob

    # graph: 0 -> {1, 2}, 1 -> {2}, 2 -> {}
    indptr = jnp.asarray(np.array([0, 2, 3, 3], dtype=np.int32))
    indices = jnp.asarray(np.array([1, 2, 2], dtype=np.int32))
    last = jnp.asarray(np.array([1.0, 0.0, 0.0], dtype=np.float32))
    # k=1: node0 contributes 1 * min(1, 1/2) = 0.5 to each of 1, 2
    out = np.asarray(cal_neighbor_prob(indptr, indices, last, 1,
                                       num_edges=3))
    np.testing.assert_allclose(out, [0.0, 0.5, 0.5], rtol=1e-6)
    # k=2: node0 contributes min(1, 2/2)=1 to each neighbor
    out = np.asarray(cal_neighbor_prob(indptr, indices, last, 2,
                                       num_edges=3))
    np.testing.assert_allclose(out, [0.0, 1.0, 1.0], rtol=1e-6)
    # second layer from node1: k=1, deg=1 -> full weight to node2
    last2 = jnp.asarray(np.array([0.0, 1.0, 0.0], dtype=np.float32))
    out = np.asarray(cal_neighbor_prob(indptr, indices, last2, 1,
                                       num_edges=3))
    np.testing.assert_allclose(out, [0.0, 0.0, 1.0], rtol=1e-6)


def test_sample_returns_valid_eids(small_graph):
    """eid[b,j] indexes the CSR edge array at the sampled position."""
    indptr, indices = small_graph.to_device()
    seeds = jnp.asarray(np.arange(12, dtype=np.int32))
    out = sample_neighbors(indptr, indices, seeds, 4, jax.random.PRNGKey(1))
    eid = np.asarray(out.eid)
    nbrs = np.asarray(out.nbrs)
    mask = np.asarray(out.mask)
    E = small_graph.edge_count
    for b in range(12):
        for j in range(4):
            if mask[b, j]:
                assert 0 <= eid[b, j] < E
                assert small_graph.indices[eid[b, j]] == nbrs[b, j]
                assert (small_graph.indptr[b] <= eid[b, j]
                        < small_graph.indptr[b + 1])
            else:
                assert eid[b, j] == -1


def test_hash_rng_sampling(small_graph):
    """sample_rng='hash' (counter-hash uniforms, compile-trivial): valid
    edges, deterministic per key, different across keys, and the draws
    spread over the neighbor set."""
    from quiver_tpu import GraphSageSampler

    s = GraphSageSampler(small_graph, [4, 3], sample_rng="hash")
    k1, k2 = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
    b1 = s.sample(np.arange(16, dtype=np.int64), key=k1)
    b1b = s.sample(np.arange(16, dtype=np.int64), key=k1)
    b2 = s.sample(np.arange(16, dtype=np.int64), key=k2)
    np.testing.assert_array_equal(np.asarray(b1.n_id), np.asarray(b1b.n_id))
    assert not np.array_equal(np.asarray(b1.n_id), np.asarray(b2.n_id))
    n_id = np.asarray(b1.n_id)
    blk = b1.layers[-1]
    local, m = np.asarray(blk.nbr_local), np.asarray(blk.mask)
    for v in range(16):
        row = set(small_graph.indices[
            small_graph.indptr[v]: small_graph.indptr[v + 1]].tolist())
        for j in range(4):
            if m[v, j]:
                assert n_id[local[v, j]] in row


def test_hash_uniform_distribution():
    from quiver_tpu.ops.sample import _hash_uniform

    u = np.asarray(_hash_uniform(jax.random.PRNGKey(3), (200, 50)))
    assert (u >= 0).all() and (u < 1).all()
    assert 0.45 < u.mean() < 0.55
    assert 0.07 < u.std() < 0.3


class TestHashUniformCrossKey:
    """_hash_uniform is the accelerator-default RNG of the whole library
    (sample_rng='auto' -> 'hash'); these tests pin the cross-key
    guarantees the round-2 scheme lacked: keys must not share a counter
    stream (no replayed segments at shifted positions), and draws pooled
    across many keys must still be uniform."""

    def _draws(self, keydata, n=4096):
        from quiver_tpu.ops.sample import _hash_uniform

        key = jax.random.wrap_key_data(
            jnp.asarray(keydata, dtype=jnp.uint32), impl="threefry2x32")
        return np.asarray(_hash_uniform(key, (n,)))

    def test_no_segment_aliasing_adjacent_keys(self):
        """Keys crafted so the ROUND-2 fold would collide (same 32-bit
        offset modulo small shifts) must produce unrelated streams: at
        every small relative shift, exact-equality between the two
        streams stays at the 2^-24 chance level."""
        n = 4096
        # round-2 offset was data[1] + data[0]*golden; these pairs made
        # offsets differ by exactly 1 -> 100% segment replay at shift 1
        a = self._draws([7, 100], n)
        b = self._draws([7, 101], n)
        for shift in range(0, 8):
            frac = np.mean(a[shift:] == b[: n - shift])
            assert frac < 1e-3, (shift, frac)
            frac = np.mean(b[shift:] == a[: n - shift])
            assert frac < 1e-3, (shift, frac)

    def test_no_collision_across_word_swap(self):
        """(w0, w1) vs (w1, w0) and vs (w0^1, w1) are distinct streams."""
        n = 4096
        base = self._draws([123, 456], n)
        for other in ([456, 123], [122, 456], [123, 457]):
            o = self._draws(other, n)
            assert np.mean(base == o) < 1e-3, other

    def test_pooled_chi_square_over_split_keys(self):
        """Concatenated draws from 64 split keys: chi-square over 64
        equal bins must not reject uniformity (99.9% critical value)."""
        from quiver_tpu.ops.sample import _hash_uniform

        root = jax.random.PRNGKey(42)
        keys = jax.random.split(root, 64)
        pooled = np.concatenate(
            [np.asarray(_hash_uniform(k, (2048,))) for k in keys])
        counts, _ = np.histogram(pooled, bins=64, range=(0.0, 1.0))
        expected = pooled.size / 64
        chi2 = float(((counts - expected) ** 2 / expected).sum())
        # df=63; 99.9% critical value ~ 103.4
        assert chi2 < 103.4, chi2

    def test_cross_key_independence_correlation(self):
        """Pearson correlation between two keys' streams ~ 0."""
        a = self._draws([1, 2], 8192)
        b = self._draws([3, 4], 8192)
        r = float(np.corrcoef(a, b)[0, 1])
        assert abs(r) < 0.05, r

    def test_full_key_sensitivity(self):
        """Every word of the key matters: flipping ONE bit in either
        word decorrelates >99% of the draws."""
        base = self._draws([0x1234, 0x5678], 2048)
        for kd in ([0x1235, 0x5678], [0x1234, 0x5679],
                   [0x80001234, 0x5678], [0x1234, 0x80005678]):
            o = self._draws(kd, 2048)
            assert np.mean(base == o) < 0.01, kd


class TestHashPathExecutesUnderEveryKeyImpl:
    """The accelerator default is sample_rng='auto' -> 'hash' — so the
    hash path must EXECUTE (not just trace) for every key width a user
    can hold: threefry2x32 (2 words, the JAX default), rbg (4 words),
    and legacy raw uint32 keys.  Round 3's key fold crashed at trace
    time with OverflowError for every key of >=2 words, which meant the
    first ``sampler.sample()`` on a real TPU died; these tests pin the
    fix (``ops/sample.py`` uint32-domain fold)."""

    def _keys(self):
        out = [
            ("threefry", jax.random.key(7, impl="threefry2x32")),
            ("raw", jax.random.PRNGKey(7)),
        ]
        try:
            out.append(("rbg", jax.random.key(7, impl="rbg")))
        except Exception:  # pragma: no cover - rbg absent on a backend
            pass
        return out

    @pytest.mark.parametrize("k", [3])
    def test_sample_neighbors_hash_executes(self, small_graph, k):
        indptr, indices = small_graph.to_device()
        seeds = jnp.arange(16, dtype=jnp.int32)
        for name, key in self._keys():
            out = sample_neighbors(indptr, indices, seeds, k, key,
                                   sample_rng="hash")
            nbrs = np.asarray(out.nbrs)  # forces execution
            mask = np.asarray(out.mask)
            for v in range(16):
                tn = true_neighbors(small_graph, v)
                got = nbrs[v][mask[v]].tolist()
                assert set(got) <= tn, (name, v, got)

    def test_sample_neighbors_weighted_hash_executes(self, small_graph):
        from quiver_tpu.ops.sample import (row_cumsum_weights,
                                           sample_neighbors_weighted)

        indptr_h, indices_h = small_graph.indptr, small_graph.indices
        w = np.random.default_rng(0).random(len(indices_h)).astype(
            np.float32) + 0.1
        cw = row_cumsum_weights(jnp.asarray(indptr_h), jnp.asarray(w))
        indptr, indices = small_graph.to_device()
        seeds = jnp.arange(12, dtype=jnp.int32)
        for name, key in self._keys():
            out = sample_neighbors_weighted(
                indptr, indices, cw, seeds, 4, key, sample_rng="hash")
            nbrs = np.asarray(out.nbrs)
            mask = np.asarray(out.mask)
            for v in range(12):
                tn = true_neighbors(small_graph, v)
                got = [int(x) for x in nbrs[v][mask[v]]]
                assert set(got) <= tn, (name, v, got)

    def test_hash_uniform_every_width(self):
        """_hash_uniform executes for 2-word (threefry, typed + raw
        uint32 dtype path) and 4-word (rbg) key data, and the two widths
        give distinct streams."""
        from quiver_tpu.ops.sample import _hash_uniform

        streams = {}
        for name, key in self._keys():
            u = np.asarray(_hash_uniform(key, (1024,)))
            assert (u >= 0).all() and (u < 1).all(), name
            assert 0.4 < u.mean() < 0.6, name
            streams[name] = u
        if "rbg" in streams:
            assert not np.array_equal(streams["threefry"], streams["rbg"])
