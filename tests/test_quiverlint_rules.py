"""quiverlint rule tests: one true-positive and one clean-negative
fixture per rule, plus suppression and baseline round-trips.

All fixtures are tmp_path files run through the real ``analyze_paths``
entry point (not rule internals), so these tests also cover file
discovery, relpath handling, and suppression plumbing.
"""

import json
import textwrap

import pytest

from quiver_tpu.analysis import LintConfig, analyze_paths
from quiver_tpu.analysis import baseline as baseline_mod
from quiver_tpu.analysis.cli import main as lint_main

ALL_HOT = ("*.py",)          # fixtures opt into hot-path rules by config


def run_lint(tmp_path, source, name="mod.py", **cfg):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return analyze_paths([str(p)], config=LintConfig(**cfg), root=tmp_path)


def codes(result):
    return sorted(f.rule for f in result.findings)


# ------------------------------------------------------------ QT001
class TestHostSync:
    def test_flags_device_get_and_block_until_ready(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            def hot_loop(x):
                y = jax.device_get(x)
                x.block_until_ready()
                return y
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT001", "QT001"]

    def test_flags_cast_of_tracked_device_value(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax.numpy as jnp

            def f(a):
                x = jnp.cumsum(a)
                total = x * 2 + 1
                return int(total[-1])
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT001"]
        assert "int(...)" in r.findings[0].message

    def test_host_numpy_cast_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import numpy as np

            def f(a):
                y = np.cumsum(a)
                return int(y[-1])
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_materialized_value_is_host_afterwards(self, tmp_path):
        # the np.asarray IS the (single) sync; casting the result is free
        r = run_lint(tmp_path, """
            import jax.numpy as jnp
            import numpy as np

            def f(a):
                h = np.asarray(jnp.cumsum(a))
                return int(h[-1]), float(h[0])
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT001"]
        assert "np.asarray" in r.findings[0].snippet

    def test_attribute_target_does_not_poison_self(self, tmp_path):
        # regression: `self.x = jnp...` must not mark `self` as a device
        # value and flag every later `int(self.anything)`
        r = run_lint(tmp_path, """
            import jax.numpy as jnp

            class G:
                def __init__(self, a):
                    self.dev = jnp.asarray(a)
                    self.n = int(len(a))
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_cold_module_is_exempt(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            def f(x):
                return jax.device_get(x)
        """, name="cold.py", hot_modules=("hot_*.py",))
        assert r.findings == []


# ------------------------------------------------------------ QT002
class TestRetrace:
    def test_flags_jit_lambda(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            def make(f):
                return jax.jit(lambda x: f(x))
        """)
        assert codes(r) == ["QT002"]
        assert "lambda" in r.findings[0].message

    def test_flags_jit_in_loop(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            def run(fs, x):
                for f in fs:
                    x = jax.jit(f)(x)
                return x
        """)
        assert codes(r) == ["QT002"]
        assert "loop" in r.findings[0].message

    def test_cached_named_jit_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            def pipeline(x):
                return x

            _fn = jax.jit(pipeline)

            def run(x):
                return _fn(x)
        """)
        assert r.findings == []

    def test_flags_traced_param_in_shape(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def pad(x, n):
                return jnp.zeros((n, 4)) + x
        """)
        assert codes(r) == ["QT002"]
        assert "`n`" in r.findings[0].message

    def test_static_argnames_makes_shape_param_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import functools
            import jax
            import jax.numpy as jnp

            @functools.partial(jax.jit, static_argnames=("n",))
            def pad(x, n):
                return jnp.zeros((n, 4)) + x
        """)
        assert r.findings == []

    def test_flags_jit_method_tracing_self(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            class S:
                @jax.jit
                def fwd(self, x):
                    return x * self.scale
        """)
        assert codes(r) == ["QT002"]
        assert "self" in r.findings[0].message

    def test_flags_jit_in_compactor_loop(self, tmp_path):
        # the stream-compactor shape: a background fold loop that mints
        # a fresh executable per compaction instead of keying a cache
        r = run_lint(tmp_path, """
            import jax

            class Compactor:
                def run(self, graph):
                    while not self._stop.is_set():
                        fold = jax.jit(lambda i: graph.merge(i))
                        fold(graph.snapshot())
        """, name="quiver_tpu/stream/compactor.py")
        assert "QT002" in codes(r)

    def test_snapshot_keyed_stream_cache_is_clean(self, tmp_path):
        # the shipped sampler idiom: executables cached on snapshot
        # SHAPE keys, content arrives as traced operands
        r = run_lint(tmp_path, """
            import jax

            class S:
                def _build_stream_jit(self, batch_size, windowed):
                    def fn(indptr, indices, seeds, key):
                        return seeds
                    return jax.jit(fn)

                def sample(self, snap, seeds, key):
                    jk = ("stream", len(seeds), snap.epad)
                    fn = self._jitted.get(jk)
                    if fn is None:
                        fn = self._jitted[jk] = self._build_stream_jit(
                            len(seeds), False)
                    return fn(snap.indptr, snap.indices, seeds, key)
        """, name="quiver_tpu/stream/sampler.py")
        assert r.findings == []

    def test_flags_jit_per_page_in_fault_loop(self, tmp_path):
        # paged-store retrace hazard: building a fresh executable per
        # faulted page turns every fault batch into a compile storm
        r = run_lint(tmp_path, """
            import jax

            class Store:
                def fault(self, pages, frames):
                    for p in pages:
                        frames = jax.jit(
                            lambda f: f.at[p].set(0))(frames)
                    return frames
        """, name="quiver_tpu/ops/paged.py")
        assert "QT002" in codes(r)

    def test_page_table_as_traced_operand_is_clean(self, tmp_path):
        # the shipped paged idiom: the gather program is cached on the
        # batch SIZE; page ids / offsets arrive as traced operands —
        # never baked into the trace, never a Python-dict key
        r = run_lint(tmp_path, """
            import jax

            class Store:
                def _paged_fn(self, B):
                    fn = self._cache.get(("paged", B))
                    if fn is None:
                        @jax.jit
                        def fn(frames, pages, offs, rank):
                            return frames
                        self._cache[("paged", B)] = fn
                    return fn

                def gather(self, frames, pages, offs, rank, B):
                    return self._paged_fn(B)(frames, pages, offs, rank)
        """, name="quiver_tpu/ops/paged.py")
        assert r.findings == []


# ------------------------------------------------------------ QT003
class TestLockDiscipline:
    GUARDED = """
        import threading

        class S:
            _guarded_by = {{"_cache": "_lock"}}

            def __init__(self):
                self._lock = threading.Lock()
                self._cache = {{}}

            def touch(self, k, v):
                {body}
    """

    def test_flags_unlocked_mutation(self, tmp_path):
        r = run_lint(tmp_path, self.GUARDED.format(
            body="self._cache[k] = v"))
        assert codes(r) == ["QT003"]
        assert "_lock" in r.findings[0].message

    def test_flags_unlocked_mutator_method(self, tmp_path):
        r = run_lint(tmp_path, self.GUARDED.format(
            body="self._cache.setdefault(k, v)"))
        assert codes(r) == ["QT003"]

    def test_locked_mutation_is_clean(self, tmp_path):
        r = run_lint(tmp_path, self.GUARDED.format(
            body="with self._lock:\n                    self._cache[k] = v"))
        assert r.findings == []

    def test_init_and_reads_are_exempt(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class S:
                _guarded_by = {"_cache": "_lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self._cache = {}   # construction: exempt

                def get(self, k):
                    return self._cache.get(k)   # racy read: allowed
        """)
        assert r.findings == []

    def test_nested_def_does_not_inherit_lock(self, tmp_path):
        # a worker closure defined inside `with self._lock:` runs LATER,
        # outside the lock — writing there must still be flagged
        r = run_lint(tmp_path, """
            import threading

            class S:
                _guarded_by = {"_cache": "_lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self._cache = {}

                def schedule(self, pool, k, v):
                    with self._lock:
                        def work():
                            self._cache[k] = v
                        pool.submit(work)
        """)
        assert codes(r) == ["QT003"]


# ------------------------------------------------------------ QT004
class TestImportLayering:
    def test_flags_module_level_exporter_import(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu.telemetry.export import start_http_server

            def serve():
                return start_http_server()
        """)
        assert codes(r) == ["QT004"]

    def test_flags_http_server_import(self, tmp_path):
        r = run_lint(tmp_path, "import http.server\n")
        assert codes(r) == ["QT004"]

    def test_function_local_import_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            def expose_metrics():
                from quiver_tpu.telemetry.export import start_http_server
                return start_http_server()
        """)
        assert r.findings == []

    def test_exempt_module_is_clean(self, tmp_path):
        r = run_lint(tmp_path, "import http.server\n",
                     name="exporter.py",
                     layering_exempt=("exporter.py",))
        assert r.findings == []


# ------------------------------------------------------------ QT005
class TestHygiene:
    def test_flags_mutable_default_and_bare_except(self, tmp_path):
        r = run_lint(tmp_path, """
            def f(xs=[]):
                try:
                    return xs
                except:
                    return None
        """)
        assert codes(r) == ["QT005", "QT005"]

    def test_clean_defaults_and_typed_except(self, tmp_path):
        r = run_lint(tmp_path, """
            def f(xs=None, n=3, name="x"):
                try:
                    return xs or []
                except ValueError:
                    return None
        """)
        assert r.findings == []


# ------------------------------------------------------------ QT006
class TestMetricNames:
    def test_flags_fstring_name(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def f(bucket):
                telemetry.counter(f"requests_{bucket}_total").inc()
        """)
        assert codes(r) == ["QT006"]
        assert "f-string" in r.findings[0].message

    def test_flags_variable_name(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def f(name):
                telemetry.gauge(name).set(1)
        """)
        assert codes(r) == ["QT006"]
        assert "literal" in r.findings[0].message

    def test_flags_missing_unit_suffix_and_bad_case(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def f():
                telemetry.counter("requestsServed").inc()
                telemetry.histogram("gather_latency").observe(0.1)
        """)
        assert codes(r) == ["QT006", "QT006"]
        msgs = " ".join(f.message for f in r.findings)
        assert "snake_case" in msgs and "unit suffix" in msgs

    def test_flags_star_label_expansion(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def f(labels):
                telemetry.counter("requests_total", **labels).inc()
        """)
        assert codes(r) == ["QT006"]
        assert "label keys" in r.findings[0].message

    def test_bare_factory_import_is_matched(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu.telemetry import counter

            def f():
                counter("badName").inc()
        """)
        assert codes(r) == ["QT006"]

    def test_clean_calls_pass(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def f():
                telemetry.counter("requests_total", lane="cpu",
                                  help="Requests served").inc()
                telemetry.gauge("queue_depth_total").set(3)
                telemetry.histogram("gather_seconds", bounds=[0.1, 1.0],
                                    tier="hot").observe(0.2)
        """)
        assert r.findings == []

    def test_registry_internals_not_matched(self, tmp_path):
        # forwarding paths (merge) re-create metrics from parsed keys;
        # names there were validated at their facade call site
        r = run_lint(tmp_path, """
            class R:
                def counter(self, name, **labels):
                    return name

                def merge(self, snap):
                    for key, v in snap.items():
                        name, labels = key, {}
                        self.counter(name, **labels)
        """)
        assert r.findings == []

    def test_fleet_federation_metric_names_pass(self, tmp_path):
        # the fleet observability plane's metric families
        # (docs/OBSERVABILITY.md) must lint clean as written
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def sweep(rid, errors, merge_errors):
                telemetry.counter("fleet_federation_scrapes_total",
                                  replica=rid).inc()
                telemetry.counter("fleet_federation_scrape_errors_total",
                                  replica=rid).inc()
                telemetry.counter(
                    "fleet_federation_parse_errors_total").inc(errors)
                telemetry.counter(
                    "fleet_federation_merge_errors_total").inc(merge_errors)

            def serve(status, e2e):
                telemetry.counter("fleet_replica_requests_total",
                                  status=status).inc()
                telemetry.histogram(
                    "fleet_replica_request_seconds").observe(e2e)
        """)
        assert r.findings == []

    def test_mesh_metric_names_pass(self, tmp_path):
        # the mesh tier's metric families (docs/SHARDING.md /
        # docs/OBSERVABILITY.md) must lint clean exactly as written:
        # _rows and _members are recognized count-unit suffixes
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def gather(seconds, halo, owned, shard):
                telemetry.histogram(
                    "mesh_shard_gather_seconds").observe(seconds)
                telemetry.counter("mesh_halo_bytes_total",
                                  direction="send").inc(halo)
                telemetry.counter("mesh_halo_bytes_total",
                                  direction="recv").inc(halo)
                telemetry.gauge("mesh_shard_frontier_rows",
                                shard=shard).set(owned)

            def route(gid, n):
                telemetry.gauge("fleet_shard_group_members",
                                group=gid).set(n)
        """)
        assert r.findings == []

    def test_fleet_metric_name_drift_flagged(self, tmp_path):
        # the shapes a federation patch is most likely to regress into:
        # camelCase and a unitless duration name
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def f(e2e):
                telemetry.counter("fleetFederationScrapes").inc()
                telemetry.histogram(
                    "fleet_replica_request_time").observe(e2e)
        """)
        assert codes(r) == ["QT006", "QT006"]
        assert "snake_case" in r.findings[0].message
        assert "unit suffix" in r.findings[1].message


# ------------------------------------------------------------ QT007
class TestSilentExcept:
    def test_flags_swallowed_exception_in_loop(self, tmp_path):
        r = run_lint(tmp_path, """
            def _worker(q):
                while True:
                    try:
                        q.get_nowait()
                    except Exception:
                        pass
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT007"]
        assert "swallows" in r.findings[0].message

    def test_flags_bare_except_without_forwarding(self, tmp_path):
        r = run_lint(tmp_path, """
            def _device_loop(q, n):
                for _ in range(n):
                    try:
                        q.get()
                    except BaseException as e:
                        n = 0  # drops e on the floor
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT007"]

    def test_recording_via_telemetry_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            def _loop(q):
                while True:
                    try:
                        q.get()
                    except Exception:
                        telemetry.counter("worker_errors_total").inc()
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_forwarding_the_exception_is_clean(self, tmp_path):
        # self._reject(item, e) / results.put((e, ...)) both forward the
        # exception object to a consumer — the serving/mixed idiom
        r = run_lint(tmp_path, """
            class B:
                def _worker(self, q):
                    while True:
                        item = q.get()
                        try:
                            self.route(item)
                        except Exception as e:
                            self._reject(item, e)

            def worker(q, results):
                while True:
                    try:
                        q.get()
                    except BaseException as e:
                        results.put((e, "error"))
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_flags_silent_compactor_loop(self, tmp_path):
        # a fold failure swallowed here would stall compaction forever
        # with no ledger entry — exactly what QT007 exists to reject
        r = run_lint(tmp_path, """
            class Compactor:
                def run(self):
                    while not self._stop.wait(self.poll_s):
                        try:
                            self._maybe_compact()
                        except Exception:
                            continue
        """, name="quiver_tpu/stream/compactor.py")
        assert codes(r) == ["QT007"]

    def test_compactor_recording_failures_is_clean(self, tmp_path):
        # the shipped idiom: tick the error counter and log, keep going
        r = run_lint(tmp_path, """
            import logging

            from quiver_tpu import telemetry

            log = logging.getLogger(__name__)

            class Compactor:
                def run(self):
                    while not self._stop.wait(self.poll_s):
                        try:
                            self._maybe_compact()
                        except Exception as e:
                            telemetry.counter(
                                "stream_compact_errors_total").inc()
                            log.warning("compaction failed: %s", e)
        """, name="quiver_tpu/stream/compactor.py")
        assert r.findings == []

    def test_flags_silent_admission_loop_in_qos_module(self, tmp_path):
        # the qos module rides the default resilience/*.py hot glob: an
        # admission loop that swallows quota failures would silently
        # starve a tenant with no rejected-counter evidence
        r = run_lint(tmp_path, """
            class QoSController:
                def _admit_loop(self, q):
                    while True:
                        req = q.get()
                        try:
                            self._take_tokens(req)
                        except Exception:
                            continue
        """, name="quiver_tpu/resilience/qos.py")
        assert codes(r) == ["QT007"]

    def test_qos_answering_rejections_is_clean(self, tmp_path):
        # the shipped idiom: every quota failure is answered on the
        # result queue and ticked, never dropped
        r = run_lint(tmp_path, """
            from quiver_tpu import telemetry

            class QoSController:
                def _admit_loop(self, q, results):
                    while True:
                        req = q.get()
                        try:
                            self._take_tokens(req)
                        except Exception as e:
                            telemetry.counter(
                                "serving_qos_rejected_total").inc()
                            results.put((req, e))
        """, name="quiver_tpu/resilience/qos.py")
        assert r.findings == []

    def test_reraise_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            def run(q):
                try:
                    q.get()
                except Exception:
                    raise
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_narrow_handler_and_non_loop_fn_are_exempt(self, tmp_path):
        # queue.Empty is control flow; a swallow outside the thread-loop
        # naming convention is left to review
        r = run_lint(tmp_path, """
            import queue

            def _drain(q):
                try:
                    return q.get_nowait()
                except queue.Empty:
                    return None

            def probe(x):
                try:
                    return x.value
                except Exception:
                    return None
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_cold_module_is_exempt(self, tmp_path):
        r = run_lint(tmp_path, """
            def _worker(q):
                try:
                    q.get()
                except Exception:
                    pass
        """, name="cold.py", hot_modules=("hot_*.py",))
        assert r.findings == []


# ------------------------------------------------ suppression plumbing
class TestSuppression:
    def test_same_line_suppression(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            def f(x):
                return jax.device_get(x)  # quiverlint: ignore[QT001] -- probe
        """, hot_modules=ALL_HOT)
        assert r.findings == []
        assert [f.rule for f in r.suppressed] == ["QT001"]

    def test_comment_line_above_covers_justification_block(self, tmp_path):
        r = run_lint(tmp_path, """
            import jax

            def f(x):
                # quiverlint: ignore[QT001]
                # this sync is the serialized baseline arm of the A/B
                return jax.device_get(x)
        """, hot_modules=ALL_HOT)
        assert r.findings == []
        assert [f.rule for f in r.suppressed] == ["QT001"]

    def test_suppression_is_rule_specific(self, tmp_path):
        # an ignore[QT005] must not hide a QT001 on the same line
        r = run_lint(tmp_path, """
            import jax

            def f(x):
                return jax.device_get(x)  # quiverlint: ignore[QT005]
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT001"]


# ------------------------------------------------------------ baseline
class TestBaseline:
    SRC = """
        import jax

        def f(x):
            return jax.device_get(x)
    """

    def test_round_trip_and_partition(self, tmp_path):
        r = run_lint(tmp_path, self.SRC, hot_modules=ALL_HOT)
        bl = tmp_path / "bl.json"
        baseline_mod.save(bl, r.findings)
        accepted = baseline_mod.load(bl)
        assert [f.fingerprint() for f in accepted] \
            == [f.fingerprint() for f in r.findings]
        new, known = baseline_mod.partition(r.findings, accepted)
        assert new == [] and len(known) == 1

    def test_baseline_survives_line_shift_not_edit(self, tmp_path):
        r1 = run_lint(tmp_path, self.SRC, hot_modules=ALL_HOT)
        bl = tmp_path / "bl.json"
        baseline_mod.save(bl, r1.findings)
        # unrelated lines above: finding moves, fingerprint doesn't
        r2 = run_lint(tmp_path, "import os\nX = 1\n"
                      + textwrap.dedent(self.SRC), hot_modules=ALL_HOT)
        new, known = baseline_mod.partition(
            r2.findings, baseline_mod.load(bl))
        assert new == [] and len(known) == 1
        # editing the flagged line itself invalidates the entry
        r3 = run_lint(tmp_path, self.SRC.replace(
            "jax.device_get(x)", "jax.device_get(x[:1])"),
            hot_modules=ALL_HOT)
        new, known = baseline_mod.partition(
            r3.findings, baseline_mod.load(bl))
        assert len(new) == 1 and known == []

    def test_second_copy_of_baselined_violation_is_new(self, tmp_path):
        r1 = run_lint(tmp_path, self.SRC, hot_modules=ALL_HOT)
        bl = tmp_path / "bl.json"
        baseline_mod.save(bl, r1.findings)
        doubled = textwrap.dedent(self.SRC) + textwrap.dedent("""
            def g(x):
                return jax.device_get(x)
        """)
        r2 = run_lint(tmp_path, doubled, hot_modules=ALL_HOT)
        new, known = baseline_mod.partition(
            r2.findings, baseline_mod.load(bl))
        # same snippet, different scope -> g's copy is NEW
        assert len(known) == 1 and len(new) == 1
        assert new[0].scope == "g"


# ------------------------------------------------------------ QT011
class TestDurability:
    SCOPE = dict(durability_scope=("*.py",), durability_exempt=("blessed.py",))

    def test_flags_write_mode_open(self, tmp_path):
        r = run_lint(tmp_path, """
            def persist(path, data):
                with open(path, "w") as f:
                    f.write(data)
        """, **self.SCOPE)
        assert codes(r) == ["QT011"]
        assert "write-mode open" in r.findings[0].message

    def test_flags_append_plus_and_exclusive_modes(self, tmp_path):
        r = run_lint(tmp_path, """
            def persist(path, data):
                open(path, "ab").write(data)
                open(path, "r+b").write(data)
                open(path, mode="x").write(data)
        """, **self.SCOPE)
        assert codes(r) == ["QT011", "QT011", "QT011"]

    def test_flags_unprovable_mode(self, tmp_path):
        r = run_lint(tmp_path, """
            def persist(path, data, mode):
                with open(path, mode) as f:
                    f.write(data)
        """, **self.SCOPE)
        assert codes(r) == ["QT011"]
        assert "cannot prove" in r.findings[0].message

    def test_flags_path_write_helpers(self, tmp_path):
        r = run_lint(tmp_path, """
            def persist(path, data):
                path.write_text(data)
                path.write_bytes(data.encode())
        """, **self.SCOPE)
        assert codes(r) == ["QT011", "QT011"]

    def test_reads_are_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            def replay(path):
                with open(path, "rb") as f:
                    head = f.read()
                with open(path) as f:
                    return head, f.read()
        """, **self.SCOPE)
        assert r.findings == []

    def test_exempt_module_may_write(self, tmp_path):
        r = run_lint(tmp_path, """
            def atomic_publish(path, data):
                with open(path, "wb") as f:
                    f.write(data)
        """, name="blessed.py", **self.SCOPE)
        assert r.findings == []

    def test_out_of_scope_module_unaffected(self, tmp_path):
        # default scope is quiver_tpu/recovery/*.py; a plain module
        # writing files is not this rule's business
        r = run_lint(tmp_path, """
            def dump(path, data):
                with open(path, "w") as f:
                    f.write(data)
        """)
        assert r.findings == []


# ------------------------------------------------------------ QT012
class TestWallClock:
    def test_flags_direct_wall_clock_subtraction(self, tmp_path):
        r = run_lint(tmp_path, """
            import time

            def serve(fn):
                t0 = time.time()
                fn()
                return time.time() - t0
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT012"]
        assert "perf_counter" in r.findings[0].message

    def test_flags_subtraction_through_assigned_names(self, tmp_path):
        r = run_lint(tmp_path, """
            import time

            def serve(fn):
                start = time.time()
                fn()
                now = time.time()
                return (now - start) * 1e3
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT012"]

    def test_flags_bare_time_import(self, tmp_path):
        r = run_lint(tmp_path, """
            from time import time

            def serve(fn):
                t0 = time()
                fn()
                return time() - t0
        """, hot_modules=ALL_HOT)
        assert codes(r) == ["QT012"]

    def test_perf_counter_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import time

            def serve(fn):
                t0 = time.perf_counter()
                fn()
                return time.perf_counter() - t0
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_timestamp_and_deadline_uses_are_clean(self, tmp_path):
        # wall-clock TIMESTAMPS are fine: record fields, absolute
        # deadlines built by addition, threshold comparisons
        r = run_lint(tmp_path, """
            import time

            def audit(history, timeout):
                history.append({"t_wall": time.time()})
                deadline = time.time() + timeout
                return time.time() > deadline
        """, hot_modules=ALL_HOT)
        assert r.findings == []

    def test_cold_module_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import time

            def offline_report(fn):
                t0 = time.time()
                fn()
                return time.time() - t0
        """, hot_modules=("nothing/*.py",))
        assert r.findings == []


# ------------------------------------------------------------ CLI
class TestCli:
    def test_exit_codes_and_baseline_flow(self, tmp_path, capsys):
        mod = tmp_path / "quiver_tpu" / "sampler.py"
        mod.parent.mkdir()
        mod.write_text("import jax\n\n"
                       "def f(x):\n"
                       "    return jax.device_get(x)\n")
        root = str(tmp_path)
        assert lint_main(["quiver_tpu", "--root", root]) == 1
        assert lint_main(["quiver_tpu", "--root", root,
                          "--write-baseline"]) == 0
        assert (tmp_path / "quiverlint.baseline.json").exists()
        assert lint_main(["quiver_tpu", "--root", root]) == 0
        assert lint_main(["quiver_tpu", "--root", root,
                          "--no-baseline"]) == 1
        capsys.readouterr()
        assert lint_main(["quiver_tpu", "--root", root, "--no-baseline",
                          "--format", "json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in doc["findings"]] == ["QT001"]

    def test_rule_selection(self, tmp_path):
        mod = tmp_path / "quiver_tpu" / "sampler.py"
        mod.parent.mkdir()
        mod.write_text("import jax\n\n"
                       "def f(x):\n"
                       "    return jax.device_get(x)\n")
        assert lint_main(["quiver_tpu", "--root", str(tmp_path),
                          "--no-baseline", "--rules", "QT005"]) == 0

    def test_syntax_error_exits_2(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text("def f(:\n")
        assert lint_main([str(bad), "--root", str(tmp_path),
                          "--no-baseline"]) == 2
        assert "error" in capsys.readouterr().err
