"""Cross-check sampling with gather_mode='lanes' vs 'xla' — identical
results (the lane-select path is a pure gather reimplementation)."""

import numpy as np
import jax
import pytest

from quiver_tpu import GraphSageSampler


def test_lanes_equals_xla(small_graph):
    seeds = np.arange(32, dtype=np.int64)
    key = jax.random.PRNGKey(9)
    b_x = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="xla").sample(seeds, key=key)
    b_l = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="lanes").sample(seeds, key=key)
    np.testing.assert_array_equal(np.asarray(b_x.n_id),
                                  np.asarray(b_l.n_id))
    np.testing.assert_array_equal(np.asarray(b_x.n_id_mask),
                                  np.asarray(b_l.n_id_mask))
    for lx, ll in zip(b_x.layers, b_l.layers):
        np.testing.assert_array_equal(np.asarray(lx.nbr_local),
                                      np.asarray(ll.nbr_local))
        np.testing.assert_array_equal(np.asarray(lx.mask),
                                      np.asarray(ll.mask))
