"""Cross-check sampling with gather_mode='lanes' vs 'xla' — identical
results (the lane-select path is a pure gather reimplementation)."""

import numpy as np
import jax
import pytest

from quiver_tpu import GraphSageSampler


def test_lanes_equals_xla(small_graph):
    seeds = np.arange(32, dtype=np.int64)
    key = jax.random.PRNGKey(9)
    b_x = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="xla").sample(seeds, key=key)
    b_l = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="lanes").sample(seeds, key=key)
    np.testing.assert_array_equal(np.asarray(b_x.n_id),
                                  np.asarray(b_l.n_id))
    np.testing.assert_array_equal(np.asarray(b_x.n_id_mask),
                                  np.asarray(b_l.n_id_mask))
    for lx, ll in zip(b_x.layers, b_l.layers):
        np.testing.assert_array_equal(np.asarray(lx.nbr_local),
                                      np.asarray(ll.nbr_local))
        np.testing.assert_array_equal(np.asarray(lx.mask),
                                      np.asarray(ll.mask))


def test_blocked_equals_xla(small_graph):
    """blocked window gather (one covering-block gather serves all k
    draws of a seed) samples identically to the xla reference path."""
    seeds = np.arange(32, dtype=np.int64)
    key = jax.random.PRNGKey(9)
    b_x = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="xla").sample(seeds, key=key)
    b_b = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="blocked").sample(seeds, key=key)
    np.testing.assert_array_equal(np.asarray(b_x.n_id),
                                  np.asarray(b_b.n_id))
    for lx, lb in zip(b_x.layers, b_b.layers):
        np.testing.assert_array_equal(np.asarray(lx.mask),
                                      np.asarray(lb.mask))
        np.testing.assert_array_equal(np.asarray(lx.nbr_local),
                                      np.asarray(lb.nbr_local))


@pytest.mark.parametrize("U", [1, 2, 3])
@pytest.mark.parametrize("frac", [0.25, 0.02])
def test_blocked_op_exact_with_fallback_and_overflow(U, frac):
    """Op-level: graphs with degrees far beyond U*128 route through the
    compacted fallback (frac=0.25) and the lax.cond wholesale-classic
    path (frac=0.02 with many huge rows) — all bitwise equal to take."""
    import jax.numpy as jnp

    from quiver_tpu.ops.blockgather import blocked_window_gather

    rng = np.random.default_rng(U * 100 + int(frac * 100))
    B, k = 64, 7
    # half the seeds get windows much wider than U rows
    deg = np.where(rng.random(B) < 0.5,
                   rng.integers(1, 50, B),
                   rng.integers(U * 128 + 1, 1000, B)).astype(np.int32)
    total = int(deg.sum())
    pad = (-total) % 128
    table = rng.integers(0, 1 << 30, total + pad).astype(np.int32)
    start = np.concatenate([[0], np.cumsum(deg)[:-1]]).astype(np.int32)
    pos = (rng.random((B, k)) * deg[:, None]).astype(np.int32)
    got = np.asarray(blocked_window_gather(
        jnp.asarray(table).reshape(-1, 128), jnp.asarray(start),
        jnp.asarray(deg), jnp.asarray(pos), U=U, fallback_frac=frac))
    want = table[start[:, None] + pos]
    np.testing.assert_array_equal(got, want)


def test_blocked_weighted_equals_xla(small_graph):
    """Weighted sampling: the one-pass CDF count over the gathered block
    must reproduce the binary search's draws exactly."""
    rng = np.random.default_rng(5)
    w = rng.random(small_graph.edge_count).astype(np.float32) + 0.01
    seeds = np.arange(24, dtype=np.int64)
    key = jax.random.PRNGKey(11)
    b_x = GraphSageSampler(small_graph, [6, 3], gather_mode="xla",
                           edge_weights=w).sample(seeds, key=key)
    b_b = GraphSageSampler(small_graph, [6, 3], gather_mode="blocked",
                           edge_weights=w).sample(seeds, key=key)
    np.testing.assert_array_equal(np.asarray(b_x.n_id),
                                  np.asarray(b_b.n_id))
    for lx, lb in zip(b_x.layers, b_b.layers):
        np.testing.assert_array_equal(np.asarray(lx.mask),
                                      np.asarray(lb.mask))
        np.testing.assert_array_equal(np.asarray(lx.nbr_local),
                                      np.asarray(lb.nbr_local))


def test_blocked_weighted_marginals():
    """High-degree rows (forcing both block and fallback CDF routes):
    draw frequencies track the edge weights."""
    import jax.numpy as jnp

    from quiver_tpu.ops.sample import (row_cumsum_weights,
                                       sample_neighbors_weighted)
    from quiver_tpu.ops.fastgather import pad_table_128

    rng = np.random.default_rng(0)
    N, deg = 4, 300  # deg 300 > 2*128: does NOT fit U=2 windows
    indptr = np.arange(N + 1, dtype=np.int32) * deg
    indices = np.tile(np.arange(deg, dtype=np.int32), N)
    w = np.tile((np.arange(deg) % 3 + 1).astype(np.float32), N)
    cw = pad_table_128(jnp.asarray(row_cumsum_weights(indptr, w)),
                       fill=np.float32(3 * deg))
    idx_pad = pad_table_128(jnp.asarray(indices))
    ip = pad_table_128(jnp.asarray(indptr), fill=np.int32(indptr[-1]))
    counts = np.zeros(deg)
    k = 32
    for t in range(40):
        out = sample_neighbors_weighted(
            ip, idx_pad, cw, jnp.arange(N, dtype=jnp.int32), k,
            jax.random.PRNGKey(t), sample_rng="key",
            gather_mode="blocked:2")
        nb = np.asarray(out.nbrs)[np.asarray(out.mask)]
        np.add.at(counts, nb, 1)
    # aggregate by weight class: class-c mass must be proportional to
    # c+1 (robust at this draw count, unlike per-neighbor frequencies)
    wclass = np.arange(deg) % 3
    mass = np.array([counts[wclass == c].sum() for c in range(3)])
    frac = mass / mass.sum()
    np.testing.assert_allclose(frac, np.array([1, 2, 3]) / 6, atol=0.02)


def test_lanes_fused_equals_xla(small_graph):
    """Pallas-fused lane select produces identical samples (interpret mode
    covers the kernel on CPU via the pure-XLA fallback equivalence)."""
    import jax as _jax

    if _jax.default_backend() == "cpu":
        # the fused kernel needs real TPU or interpret=True; on CPU verify
        # via the op-level test instead (test_fastgather) and the flag wiring
        from quiver_tpu.ops.sample import _gather
        import jax.numpy as jnp
        import numpy as _np

        table = jnp.asarray(_np.arange(256, dtype=_np.int32))
        idx = jnp.asarray(_np.array([3, 200, 128], dtype=_np.int32))
        # lanes mode must match plain take
        _np.testing.assert_array_equal(
            _np.asarray(_gather(table, idx, "lanes")),
            _np.asarray(jnp.take(table, idx)),
        )


def test_pwindow_equals_xla_through_sampler(small_graph):
    """The fused Pallas window-sampling hop (gather_mode='pwindow')
    samples bitwise identically to the XLA hash path through the full
    2-hop sampler (interpret mode on CPU)."""
    seeds = np.arange(24, dtype=np.int64)
    key = jax.random.PRNGKey(9)
    b_x = GraphSageSampler(small_graph, [5, 4], gather_mode="xla",
                           sample_rng="hash").sample(seeds, key=key)
    b_p = GraphSageSampler(small_graph, [5, 4], gather_mode="pwindow:2",
                           sample_rng="hash").sample(seeds, key=key)
    np.testing.assert_array_equal(np.asarray(b_x.n_id),
                                  np.asarray(b_p.n_id))
    for lx, lp in zip(b_x.layers, b_p.layers):
        np.testing.assert_array_equal(np.asarray(lx.mask),
                                      np.asarray(lp.mask))
        np.testing.assert_array_equal(np.asarray(lx.nbr_local),
                                      np.asarray(lp.nbr_local))


def test_pwindow_requires_hash_rng(small_graph):
    with pytest.raises(ValueError, match="hash"):
        GraphSageSampler(small_graph, [4], gather_mode="pwindow",
                         sample_rng="key").sample(np.arange(8))
