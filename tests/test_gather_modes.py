"""Cross-check sampling with gather_mode='lanes' vs 'xla' — identical
results (the lane-select path is a pure gather reimplementation)."""

import numpy as np
import jax
import pytest

from quiver_tpu import GraphSageSampler


def test_lanes_equals_xla(small_graph):
    seeds = np.arange(32, dtype=np.int64)
    key = jax.random.PRNGKey(9)
    b_x = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="xla").sample(seeds, key=key)
    b_l = GraphSageSampler(small_graph, [5, 4],
                           gather_mode="lanes").sample(seeds, key=key)
    np.testing.assert_array_equal(np.asarray(b_x.n_id),
                                  np.asarray(b_l.n_id))
    np.testing.assert_array_equal(np.asarray(b_x.n_id_mask),
                                  np.asarray(b_l.n_id_mask))
    for lx, ll in zip(b_x.layers, b_l.layers):
        np.testing.assert_array_equal(np.asarray(lx.nbr_local),
                                      np.asarray(ll.nbr_local))
        np.testing.assert_array_equal(np.asarray(lx.mask),
                                      np.asarray(ll.mask))


def test_lanes_fused_equals_xla(small_graph):
    """Pallas-fused lane select produces identical samples (interpret mode
    covers the kernel on CPU via the pure-XLA fallback equivalence)."""
    import jax as _jax

    if _jax.default_backend() == "cpu":
        # the fused kernel needs real TPU or interpret=True; on CPU verify
        # via the op-level test instead (test_fastgather) and the flag wiring
        from quiver_tpu.ops.sample import _gather
        import jax.numpy as jnp
        import numpy as _np

        table = jnp.asarray(_np.arange(256, dtype=_np.int32))
        idx = jnp.asarray(_np.array([3, 200, 128], dtype=_np.int32))
        # lanes mode must match plain take
        _np.testing.assert_array_equal(
            _np.asarray(_gather(table, idx, "lanes")),
            _np.asarray(jnp.take(table, idx)),
        )
