"""Unified timeline profiler suite (ISSUE 11).

Covers the cross-subsystem event bus (:mod:`quiver_tpu.telemetry.
timeline`), per-program attribution (:mod:`..profile`), the perf gate
(``benchmarks/perfgate.py``), the hostile-label Prometheus escaping
fix, and the hardened XLA-profiler wrapper.

The load-bearing tests:

  * the OFF path is pinned at exactly one module-global read per emit
    site (``on.__code__.co_names``) and instrumented subsystems create
    NO rings while the timeline is off;
  * a >=8-thread hammer with a live export mid-emission: per-thread
    monotone ordering, bounded ring capacity with honest drop counts,
    and a merged Chrome trace Perfetto can load;
  * perfgate exit codes: seed -> 0, unchanged re-run -> 0, injected
    synthetic regression -> 1 (through the real compare path).
"""

import json
import os
import re
import sys
import threading
import time
from pathlib import Path

import pytest

from quiver_tpu import telemetry
from quiver_tpu.telemetry import flightrec, profile, timeline

pytestmark = pytest.mark.timeline

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.set_enabled(True)
    telemetry.reset()
    yield
    telemetry.set_enabled(False)
    telemetry.reset()


# ------------------------------------------------------------ gating
class TestGating:
    def test_off_path_is_one_global_read(self):
        # THE zero-overhead-off contract: the guard every hot emit site
        # uses compiles to a single module-global load.  If this fails,
        # someone added work to the off path — that is a perf
        # regression at every instrumented call site in the library.
        assert timeline.on.__code__.co_names == ("_ON",)
        assert profile.on.__code__.co_names == ("_ON",)

    def test_off_timeline_records_nothing_from_subsystems(self):
        assert not timeline.on()
        # exercise instrumented subsystems with the timeline off
        with telemetry.span("off.scope"):
            pass
        ctx = flightrec.new_trace()
        with flightrec.activate(ctx):
            flightrec.event("off.event", {"seconds": 0.001})
        flightrec.get_recorder().finish(ctx, 0.001)
        st = timeline.status()
        assert st["enabled"] is False
        assert st["threads"] == 0 and st["events"] == 0

    def test_enable_respects_telemetry_kill_switch(self):
        telemetry.set_enabled(False)
        assert timeline.enable() is False
        assert profile.enable() is False
        assert not timeline.on() and not profile.on()

    def test_spans_and_flightrec_land_when_on(self):
        timeline.enable()
        with telemetry.span("demo.scope"):
            pass
        ctx = flightrec.new_trace()
        with flightrec.activate(ctx):
            flightrec.event("sample", {"seconds": 0.002})
        flightrec.get_recorder().finish(ctx, 0.01, lane="test")
        names = {e[2] for r in timeline._seen_rings() for e in r.ordered()}
        assert {"demo.scope", "sample", "request"} <= names
        # correlation: the flightrec-originated events carry the trace id
        doc = timeline.chrome_trace()
        tids = {e["args"].get("trace_id") for e in doc["traceEvents"]
                if e.get("name") in ("sample", "request")}
        assert tids == {ctx.trace_id}


# ------------------------------------------------------------ hammer
class TestConcurrentHammer:
    N_THREADS = 8
    PER_THREAD = 3000
    CAP = 512

    def test_hammer_with_live_export(self):
        timeline.enable(capacity=self.CAP)
        start = threading.Barrier(self.N_THREADS + 2)
        done = threading.Event()
        export_docs = []

        def emitter(t):
            start.wait()
            for i in range(self.PER_THREAD):
                timeline.emit(f"hammer.t{t}", cat="app", dur_s=1e-7,
                              attrs={"i": i})

        def exporter():
            start.wait()
            while not done.is_set():
                # live export DURING emission must never crash or
                # return a malformed doc
                doc = timeline.chrome_trace()
                json.dumps(doc)
                export_docs.append(len(doc["traceEvents"]))

        threads = [threading.Thread(target=emitter, args=(t,))
                   for t in range(self.N_THREADS)]
        exp = threading.Thread(target=exporter)
        for th in threads:
            th.start()
        exp.start()
        start.wait()
        for th in threads:
            th.join()
        done.set()
        exp.join()

        st = timeline.status()
        # bounded capacity: each ring kept at most CAP events and the
        # overflow is counted, not silently lost
        assert st["events"] <= self.N_THREADS * self.CAP + self.CAP
        total = self.N_THREADS * self.PER_THREAD
        assert st["dropped"] >= total - self.N_THREADS * self.CAP
        assert export_docs, "live exporter never ran"

        doc = timeline.chrome_trace()
        by_tid = {}
        for e in doc["traceEvents"]:
            if e["ph"] == "M" or not e["name"].startswith("hammer."):
                continue
            by_tid.setdefault(e["tid"], []).append(e)
        assert len(by_tid) == self.N_THREADS
        for tid, evs in by_tid.items():
            # per-thread ordering: the ring unwraps oldest-first, and
            # one thread's timestamps are monotone
            ts = [e["ts"] for e in evs]
            assert ts == sorted(ts), f"tid {tid} out of order"
            idx = [e["args"]["i"] for e in evs]
            assert idx == sorted(idx)
            assert len(evs) <= self.CAP

    def test_reset_during_emission_is_safe(self):
        timeline.enable(capacity=64)
        stop = threading.Event()

        def emitter():
            while not stop.is_set():
                if timeline.on():
                    timeline.emit("churn", cat="app")

        th = threading.Thread(target=emitter)
        th.start()
        try:
            for _ in range(20):
                timeline.reset()
                timeline.enable(capacity=64)
                timeline.chrome_trace()
        finally:
            stop.set()
            th.join()
        timeline.reset()
        assert timeline.status()["threads"] == 0


# ------------------------------------------------------------ chrome trace
class TestChromeTrace:
    def test_slices_instants_and_metadata(self, tmp_path):
        timeline.enable()
        timeline.emit("dur.ev", cat="wal", dur_s=0.005)
        timeline.instant("inst.ev", cat="chaos", attrs={"k": 1})
        path = timeline.export(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        phases = {e["ph"] for e in doc["traceEvents"]}
        assert {"M", "X", "i"} <= phases
        x = next(e for e in doc["traceEvents"] if e["ph"] == "X")
        assert x["dur"] == pytest.approx(5000, rel=0.01)  # microseconds
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names == {"quiver_tpu"}

    def test_category_inference(self):
        timeline.enable()
        timeline.emit("sample")            # serving stage map
        timeline.emit("feature.page_fault")  # dotted prefix remap
        timeline.emit("wal.fsync")
        doc = timeline.chrome_trace()
        cats = {e["name"]: e["cat"] for e in doc["traceEvents"]
                if e["ph"] != "M"}
        assert cats["sample"] == "serving"
        assert cats["feature.page_fault"] == "paged"
        assert cats["wal.fsync"] == "wal"


# ------------------------------------------------------------ profile
class TestProgramAttribution:
    def test_cache_insertions_are_wrapped_and_attributed(self):
        from quiver_tpu.recovery.registry import get_program_registry

        profile.enable()
        cache = get_program_registry().cache("testsub")
        cache["k1"] = lambda x: x + 1
        assert type(cache["k1"]).__name__ == "_ProfiledProgram"
        assert cache["k1"](41) == 42
        rows = profile.top_programs(5)
        row = next(r for r in rows if r["subsystem"] == "testsub")
        assert row["calls"] == 1
        assert row["total_s"] >= row["host_s"] >= 0
        # honest device stamping: this suite pins the CPU backend
        assert row["device"] is False
        payload = profile.debug_payload()
        assert payload["enabled"] and payload["programs"] >= 1

    def test_disable_unwraps(self):
        from quiver_tpu.recovery.registry import get_program_registry

        profile.enable()
        cache = get_program_registry().cache("unwrapsub")
        fn = lambda x: x  # noqa: E731
        cache["k"] = fn
        profile.disable()
        assert cache["k"] is fn

    def test_retro_wrap_of_existing_programs(self):
        from quiver_tpu.recovery.registry import get_program_registry

        cache = get_program_registry().cache("warmsub")
        cache["old"] = lambda x: x * 2
        assert type(cache["old"]).__name__ != "_ProfiledProgram"
        profile.enable()
        assert type(cache["old"]).__name__ == "_ProfiledProgram"
        assert cache["old"](3) == 6
        assert any(r["subsystem"] == "warmsub"
                   for r in profile.top_programs(50))

    def test_wrapped_program_lands_on_timeline(self):
        from quiver_tpu.recovery.registry import get_program_registry

        timeline.enable()
        profile.enable()
        cache = get_program_registry().cache("tlsub")
        cache["k"] = lambda: None
        cache["k"]()
        doc = timeline.chrome_trace()
        ev = next(e for e in doc["traceEvents"]
                  if e.get("name") == "program.tlsub")
        assert ev["ph"] == "X" and ev["cat"] == "registry"
        assert ev["args"]["device"] is False


# ------------------------------------------------------------ endpoints
class TestHttpEndpoints:
    def test_debug_timeline_and_programs_roundtrip(self):
        from urllib.request import urlopen

        from quiver_tpu.telemetry.export import start_http_server

        timeline.enable()
        profile.enable()
        timeline.emit("http.ev", cat="app", dur_s=0.001)
        from quiver_tpu.recovery.registry import get_program_registry

        cache = get_program_registry().cache("httpsub")
        cache["k"] = lambda: 7
        cache["k"]()
        srv = start_http_server(port=0)
        try:
            doc = json.loads(urlopen(f"{srv.url}/debug/timeline",
                                     timeout=5).read())
            assert any(e.get("name") == "http.ev"
                       for e in doc["traceEvents"])
            prog = json.loads(urlopen(f"{srv.url}/debug/programs",
                                      timeout=5).read())
            assert prog["enabled"] is True
            assert any(r["subsystem"] == "httpsub" for r in prog["top"])
        finally:
            srv.close()


# ------------------------------------------------------------ escaping
_SERIES_RE = re.compile(r'^(\w+)\{(.*)\} ([0-9.eE+-]+)$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
             .replace("\\\\", "\\"))


class TestPrometheusEscaping:
    def test_backslash_label_roundtrips_end_to_end(self):
        # the registry's reserved-character check blocks , = { } " \n
        # at metric-creation time, but a backslash sails through — and
        # unescaped it corrupts the exposition format (prometheus reads
        # `\\` as one backslash, a lone `\t` as an escape sequence)
        from quiver_tpu.telemetry.export import to_prometheus_text

        hostile = 'dom\\ain\\tenant'
        telemetry.counter("escape_test_total", tenant=hostile).inc(3)
        text = to_prometheus_text(telemetry.snapshot())
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("escape_test_total{"))
        m = _SERIES_RE.match(line)
        assert m, f"unparseable series line: {line!r}"
        labels = dict(_LABEL_RE.findall(m.group(2)))
        assert _unescape(labels["tenant"]) == hostile
        assert float(m.group(3)) == 3.0

    def test_formatter_escapes_fully_hostile_values(self):
        # _fmt_labels is also fed labels the registry never vetted
        # (histogram `le`, snapshot post-processors): it must escape
        # quote/newline/backslash itself, one series per LINE
        from quiver_tpu.telemetry.export import _fmt_labels

        hostile = 'ev"il\\ten\nant'
        rendered = _fmt_labels({"tenant": hostile})
        assert "\n" not in rendered
        labels = dict(_LABEL_RE.findall(rendered.strip("{}")))
        assert _unescape(labels["tenant"]) == hostile

    def test_plain_labels_unchanged(self):
        from quiver_tpu.telemetry.export import to_prometheus_text

        telemetry.counter("plain_total", tenant="tenant-a").inc()
        text = to_prometheus_text(telemetry.snapshot())
        assert 'plain_total{tenant="tenant-a"} 1' in text


# ------------------------------------------------------------ perfgate
def _perfgate():
    sys.path.insert(0, str(REPO / "benchmarks"))
    import perfgate

    return perfgate


class TestPerfgate:
    @pytest.fixture()
    def fast_metrics(self, monkeypatch):
        pg = _perfgate()
        ticker = {"n": 0}

        def fast():
            ticker["n"] += 1
            return 5.0  # deterministic "measurement"

        monkeypatch.setattr(pg, "METRICS", {"fast": fast})
        return pg

    def test_seed_then_pass_then_injected_regression(self, tmp_path,
                                                     fast_metrics,
                                                     monkeypatch):
        pg = fast_metrics
        state = str(tmp_path / "state.json")
        out = str(tmp_path / "PERFGATE.json")
        argv = ["--state", state, "--out", out, "--k", "3"]
        assert pg.main(argv) == 0
        assert json.load(open(out))["status"] == "seeded"
        # baseline persisted under the top-level "perfgate" key without
        # clobbering bench.py's resume state
        disk = json.load(open(state))
        assert "perfgate" in disk and "states" in disk

        assert pg.main(argv) == 0
        assert json.load(open(out))["status"] == "pass"

        monkeypatch.setenv("QUIVER_PERFGATE_INJECT", "2.0")
        assert pg.main(argv) == 1
        verdict = json.load(open(out))
        assert verdict["status"] == "regression"
        assert verdict["regressions"] == ["fast"]
        assert verdict["metrics"]["fast"]["injected_factor"] == 2.0
        # honest stamping: this suite pins the CPU backend
        assert verdict["source"] == "cpu_rehearsal"

        # report-only (the CPU CI mode): verdict written, exit 0
        assert pg.main(argv + ["--report-only"]) == 0
        assert json.load(open(out))["status"] == "regression"

    def test_skipped_metric_degrades_not_dies(self, tmp_path,
                                              monkeypatch):
        pg = _perfgate()

        def boom():
            raise RuntimeError("native dep missing")

        monkeypatch.setattr(pg, "METRICS", {"ok": lambda: 1.0,
                                            "broken": boom})
        state = str(tmp_path / "state.json")
        out = str(tmp_path / "PERFGATE.json")
        argv = ["--state", state, "--out", out, "--k", "2"]
        assert pg.main(argv) == 0  # seeds with the one working metric
        assert pg.main(argv) == 0
        verdict = json.load(open(out))
        assert "error" in verdict["measured"]["broken"]

    def test_noise_below_threshold_passes(self, tmp_path, monkeypatch):
        pg = _perfgate()
        val = {"v": 10.0}
        monkeypatch.setattr(pg, "METRICS", {"m": lambda: val["v"]})
        state = str(tmp_path / "s.json")
        out = str(tmp_path / "o.json")
        argv = ["--state", state, "--out", out, "--k", "3"]
        assert pg.main(argv) == 0
        val["v"] = 11.0  # +10%: under the 30% relative floor
        assert pg.main(argv) == 0
        val["v"] = 20.0  # +100%: a real regression
        assert pg.main(argv) == 1


# ------------------------------------------------------------ xla profiler
class TestProfileTraceHardening:
    def test_degrades_to_noop_and_warns_once(self, tmp_path, capsys,
                                             monkeypatch):
        import quiver_tpu.utils.trace as trace_mod

        monkeypatch.setattr(trace_mod, "_PROFILE_WARNED", False)
        # double-start: the inner span must degrade, never raise
        with trace_mod.profile_trace(str(tmp_path / "a")):
            with trace_mod.profile_trace(str(tmp_path / "b")):
                pass
            with trace_mod.profile_trace(str(tmp_path / "c")):
                pass
        err = capsys.readouterr().err
        assert err.count("profiler unavailable") == 1  # warn ONCE
