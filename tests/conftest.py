"""Test config: force CPU backend with 8 virtual devices BEFORE jax import.

This gives every test a simulated 8-chip mesh (the multi-host coverage the
reference never had — SURVEY.md §4's lesson), and keeps the suite runnable
anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon site hook re-exports JAX_PLATFORMS=axon after env setup; the
# config API takes final precedence, so pin the platform here too.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# retrace_guard hooks (@pytest.mark.retrace_budget).  Re-exported here —
# NOT listed via `-p` in pytest.ini — so the import happens after the
# JAX_PLATFORMS / XLA_FLAGS staging above (the plugin pulls in
# quiver_tpu, which imports jax).
from quiver_tpu.analysis.retrace_guard import *  # noqa: F401,F403


# ---------------------------------------------------------------------------
# Lock-witness sanitizer harness (`make sanitize` sets QUIVER_SANITIZE=1;
# quiver_tpu/__init__.py installed the witness before jax even imported).
# Seed the canonical acquisition order once from the static analyzer, then
# drain after every test and fail the owner on any recorded violation.
_SANITIZING = os.environ.get("QUIVER_SANITIZE") == "1"

if _SANITIZING:
    from quiver_tpu.analysis import witness as _witness

    @pytest.fixture(scope="session", autouse=True)
    def _witness_seed():
        from quiver_tpu.analysis.concurrency import canonical_lock_edges
        from quiver_tpu.analysis.core import load_contexts

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ctxs = load_contexts([os.path.join(root, "quiver_tpu")])
        _witness.seed_order(canonical_lock_edges(ctxs))
        yield

    @pytest.fixture(autouse=True)
    def _witness_drain(request):
        from quiver_tpu.analysis import transfer_witness as _transfer

        _witness.drain()  # don't blame this test for prior leftovers
        _transfer.drain()
        yield
        vs = [("lock-witness", v) for v in _witness.drain()]
        vs += [("transfer-witness", v) for v in _transfer.drain()]
        if vs:
            lines = [f"  [{src}:{v.kind}] {v.message} (thread {v.thread})"
                     for src, v in vs]
            pytest.fail(
                "sanitizer recorded %d violation(s):\n%s"
                % (len(vs), "\n".join(lines)), pytrace=False)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# ---------------------------------------------------------------------------
# Multi-device / multi-process helpers for the mesh tier (docs/SHARDING.md).
# The suite itself already runs on 8 virtual CPU devices (above); tests
# that need a SEPARATE process with its own device count (shard-group
# members, device-count isolation) spawn one through this helper.
def run_devices_subprocess(code, n_devices=8, env=None, timeout=120):
    """Run ``code`` in a fresh python with ``n_devices`` virtual CPU
    devices; returns the CompletedProcess (caller asserts on
    returncode/stdout).  The child re-stages JAX_PLATFORMS/XLA_FLAGS
    before its first jax import, exactly like this conftest."""
    import subprocess
    import sys

    child_env = dict(os.environ)
    child_env["JAX_PLATFORMS"] = "cpu"
    child_env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={int(n_devices)}")
    if env:
        child_env.update(env)
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout, env=child_env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def devices_subprocess():
    """Fixture form of :func:`run_devices_subprocess` for mesh tests."""
    return run_devices_subprocess


def make_random_csr(n_nodes=200, avg_deg=8, seed=0, power_law=False):
    """Random graph fixture (parity: gen_random_graph,
    tests/cpp/test_quiver.cu:17-85)."""
    rng = np.random.default_rng(seed)
    if power_law:
        deg = np.minimum(
            rng.zipf(1.6, n_nodes) + 1, n_nodes - 1
        ).astype(np.int64)
    else:
        deg = rng.poisson(avg_deg, n_nodes).astype(np.int64)
    src = np.repeat(np.arange(n_nodes), deg)
    dst = rng.integers(0, n_nodes, size=src.shape[0])
    # drop parallel edges so "k distinct positions" == "k distinct ids"
    # in the property tests (samplers pick positions, as the reference does)
    pairs = np.unique(np.stack([src, dst], axis=1), axis=0)
    return pairs[:, 0], pairs[:, 1]


@pytest.fixture
def small_graph():
    from quiver_tpu import CSRTopo

    src, dst = make_random_csr(n_nodes=200, avg_deg=8, seed=1)
    return CSRTopo(edge_index=np.stack([src, dst]))


@pytest.fixture
def power_graph():
    from quiver_tpu import CSRTopo

    src, dst = make_random_csr(n_nodes=500, avg_deg=8, seed=2,
                               power_law=True)
    return CSRTopo(edge_index=np.stack([src, dst]))
