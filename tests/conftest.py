"""Test config: force CPU backend with 8 virtual devices BEFORE jax import.

This gives every test a simulated 8-chip mesh (the multi-host coverage the
reference never had — SURVEY.md §4's lesson), and keeps the suite runnable
anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon site hook re-exports JAX_PLATFORMS=axon after env setup; the
# config API takes final precedence, so pin the platform here too.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# retrace_guard hooks (@pytest.mark.retrace_budget).  Re-exported here —
# NOT listed via `-p` in pytest.ini — so the import happens after the
# JAX_PLATFORMS / XLA_FLAGS staging above (the plugin pulls in
# quiver_tpu, which imports jax).
from quiver_tpu.analysis.retrace_guard import *  # noqa: F401,F403


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def make_random_csr(n_nodes=200, avg_deg=8, seed=0, power_law=False):
    """Random graph fixture (parity: gen_random_graph,
    tests/cpp/test_quiver.cu:17-85)."""
    rng = np.random.default_rng(seed)
    if power_law:
        deg = np.minimum(
            rng.zipf(1.6, n_nodes) + 1, n_nodes - 1
        ).astype(np.int64)
    else:
        deg = rng.poisson(avg_deg, n_nodes).astype(np.int64)
    src = np.repeat(np.arange(n_nodes), deg)
    dst = rng.integers(0, n_nodes, size=src.shape[0])
    # drop parallel edges so "k distinct positions" == "k distinct ids"
    # in the property tests (samplers pick positions, as the reference does)
    pairs = np.unique(np.stack([src, dst], axis=1), axis=0)
    return pairs[:, 0], pairs[:, 1]


@pytest.fixture
def small_graph():
    from quiver_tpu import CSRTopo

    src, dst = make_random_csr(n_nodes=200, avg_deg=8, seed=1)
    return CSRTopo(edge_index=np.stack([src, dst]))


@pytest.fixture
def power_graph():
    from quiver_tpu import CSRTopo

    src, dst = make_random_csr(n_nodes=500, avg_deg=8, seed=2,
                               power_law=True)
    return CSRTopo(edge_index=np.stack([src, dst]))
