"""Resolution precedence for gather_mode / sample_rng.

Explicit kwarg > env (QUIVER_TPU_*) / tuned file > backend default.
Backend default on CPU (the test backend): gather_mode="xla",
sample_rng="key".  The accelerator branch ("lanes"/"hash",
docs/TPU_MEASUREMENTS.md round 2) can't execute here; the precedence
logic it shares is what's under test.

All env mutation goes through ``monkeypatch`` so it is restored even on
assertion failure — the round-3 hand-rolled save/restore leaked
``QUIVER_TPU_SAMPLE_RNG=hash`` into the rest of the pytest session and
flipped 94 unrelated tests onto the accelerator RNG path.
"""

import pytest

import quiver_tpu.config as qconfig
from quiver_tpu.config import resolve_gather_mode, resolve_sample_rng


@pytest.fixture(autouse=True)
def _clean_config(monkeypatch):
    """Reset the config singleton, scrub env overrides, and disable the
    tuned-file loader around each test (a locally-written
    .quiver_tpu_tuned.json must not leak into backend-default asserts).

    monkeypatch records and restores everything it touches — including
    deleting vars a test adds via ``monkeypatch.setenv`` — so nothing
    this module does survives past its own tests."""
    monkeypatch.delenv("QUIVER_TPU_GATHER_MODE", raising=False)
    monkeypatch.delenv("QUIVER_TPU_SAMPLE_RNG", raising=False)
    monkeypatch.delenv("QUIVER_TPU_DEDUP", raising=False)
    monkeypatch.setattr(qconfig, "_load_tuned", lambda cfg, path=None: None)
    qconfig._config = None
    yield
    qconfig._config = None


def test_explicit_wins():
    assert resolve_gather_mode("pallas") == "pallas"
    assert resolve_sample_rng("hash") == "hash"


def test_backend_default_cpu():
    assert resolve_gather_mode("auto") == "xla"
    assert resolve_sample_rng("auto") == "key"


def test_env_overrides_auto(monkeypatch):
    monkeypatch.setenv("QUIVER_TPU_GATHER_MODE", "lanes")
    monkeypatch.setenv("QUIVER_TPU_SAMPLE_RNG", "hash")
    qconfig._config = None
    assert resolve_gather_mode("auto") == "lanes"
    assert resolve_sample_rng("auto") == "hash"


def test_explicit_beats_env(monkeypatch):
    monkeypatch.setenv("QUIVER_TPU_GATHER_MODE", "lanes")
    monkeypatch.setenv("QUIVER_TPU_SAMPLE_RNG", "hash")
    qconfig._config = None
    assert resolve_gather_mode("xla") == "xla"
    assert resolve_sample_rng("key") == "key"


def test_invalid_values_raise():
    with pytest.raises(ValueError):
        resolve_gather_mode("fast")
    with pytest.raises(ValueError):
        resolve_sample_rng("Hash")


def test_invalid_env_raises_not_silently_defaults(monkeypatch):
    monkeypatch.setenv("QUIVER_TPU_SAMPLE_RNG", "keyed")
    qconfig._config = None
    with pytest.raises(ValueError):
        resolve_sample_rng("auto")


# captured at import time, before the autouse fixture stubs the attribute
_ORIG_LOAD_TUNED = qconfig._load_tuned


def test_malformed_tuned_blocked_is_ignored(tmp_path):
    """A tuned file carrying 'blocked:0' / 'blockedx' must be skipped like
    any other invalid tuned value, not crash resolve_gather_mode later."""
    import json

    import jax

    backend = jax.default_backend()
    p = tmp_path / ".quiver_tpu_tuned.json"
    for bad in ("blocked:0", "blocked:-2", "blockedx", "blocked:"):
        p.write_text(json.dumps({"backend": backend, "gather_mode": bad}))
        cfg = qconfig.Config()
        _ORIG_LOAD_TUNED(cfg, path=str(p))
        assert cfg.gather_mode == "auto", bad
    # a WELL-FORMED blocked value is accepted
    p.write_text(json.dumps(
        {"backend": backend, "gather_mode": "blocked:3"}))
    cfg = qconfig.Config()
    _ORIG_LOAD_TUNED(cfg, path=str(p))
    assert cfg.gather_mode == "blocked:3"


def test_sampler_resolves_at_init():
    import numpy as np

    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.utils.synthetic import synthetic_csr

    indptr, indices = synthetic_csr(500, 4000, 0)
    topo = CSRTopo(indptr=indptr, indices=indices)
    s = GraphSageSampler(topo, [3], gather_mode="auto", sample_rng="auto")
    assert s.gather_mode == "xla" and s.sample_rng == "key"
    b = s.sample(np.arange(8, dtype=np.int32))
    assert int(b.num_nodes) >= 8


def test_auto_rng_resolves_hash_under_pwindow(monkeypatch):
    """gather_mode='pwindow' only supports the in-kernel counter-hash;
    'auto' must resolve to 'hash' under it even on CPU (where auto
    otherwise resolves to 'key')."""
    from quiver_tpu.config import resolve_sample_rng

    assert resolve_sample_rng("auto", "pwindow") == "hash"
    assert resolve_sample_rng("auto", "pwindow:2") == "hash"
    # explicit choice is surfaced, not overridden (the op raises)
    assert resolve_sample_rng("key", "pwindow") == "key"
    # other modes keep the backend default (cpu -> key in this suite)
    assert resolve_sample_rng("auto", "lanes") == "key"


def test_env_pinned_key_rng_warns_under_pwindow(monkeypatch):
    """gather_mode='pwindow' forces 'hash'; when the displaced 'key' pin
    came from env/tuned (not an explicit kwarg) the override must be
    surfaced as a warning, not silent."""
    import warnings

    monkeypatch.setenv("QUIVER_TPU_SAMPLE_RNG", "key")
    qconfig._config = None
    with pytest.warns(UserWarning, match="overridden to 'hash'"):
        assert resolve_sample_rng("auto", "pwindow:2") == "hash"
    # no pin -> no warning (the override changes nothing the user chose)
    monkeypatch.delenv("QUIVER_TPU_SAMPLE_RNG")
    qconfig._config = None
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert resolve_sample_rng("auto", "pwindow:2") == "hash"


def test_pwindow_rejects_unsupported_backend(monkeypatch, small_graph):
    """An unsupported backend must fail with a clear ValueError before
    Mosaic lowering is attempted (ops/sample.py pwindow branch)."""
    import jax

    from quiver_tpu.ops.fastgather import pad_table_128
    from quiver_tpu.ops.sample import sample_neighbors
    from quiver_tpu.utils.rng import make_key

    indptr, indices = small_graph.to_device()
    indices = pad_table_128(indices)
    monkeypatch.setattr(jax, "default_backend", lambda: "gpu")
    with pytest.raises(ValueError, match="pwindow.*needs backend"):
        # unique k so the jit cache can't serve a pre-gate trace
        sample_neighbors(indptr, indices,
                         jax.numpy.arange(13, dtype=jax.numpy.int32),
                         7, make_key(0), gather_mode="pwindow:2",
                         sample_rng="hash")


def test_auto_gather_degrades_pwindow_for_explicit_key_rng(monkeypatch):
    """A tuned/env 'pwindow' pick must not crash a user who explicitly
    chose sample_rng='key': auto resolution degrades to the equivalent
    XLA blocked mode.  An explicit pwindow+key still raises at the op."""
    from quiver_tpu import config as qc

    monkeypatch.setenv("QUIVER_TPU_GATHER_MODE", "pwindow:3")
    monkeypatch.setattr(qc, "_config", None)
    assert qc.resolve_gather_mode("auto", "key") == "blocked:3"
    assert qc.resolve_gather_mode("auto", "hash") == "pwindow:3"
    assert qc.resolve_gather_mode("auto", "auto") == "pwindow:3"
    # explicit kwarg is never rewritten
    assert qc.resolve_gather_mode("pwindow:3", "key") == "pwindow:3"
    monkeypatch.setattr(qc, "_config", None)


def test_dedup_resolution(monkeypatch, tmp_path):
    """'auto' dedup follows env > tuned file (the on-chip e2e A/B's
    winner) > 'none'; explicit values pass through; bad values raise."""
    from quiver_tpu import config as qc

    monkeypatch.setattr(qc, "_config", None)
    monkeypatch.delenv("QUIVER_TPU_DEDUP", raising=False)
    assert qc.resolve_dedup("auto") == "none"
    assert qc.resolve_dedup("hop") == "hop"
    with pytest.raises(ValueError, match="dedup"):
        qc.resolve_dedup("both")
    monkeypatch.setenv("QUIVER_TPU_DEDUP", "hop")
    monkeypatch.setattr(qc, "_config", None)
    assert qc.resolve_dedup("auto") == "hop"
    # tuned-file overlay (same backend) flips the default — the suite
    # fixture no-ops qc._load_tuned, so call the saved original against
    # a scratch tuned file
    monkeypatch.delenv("QUIVER_TPU_DEDUP", raising=False)
    import jax, json
    tuned = tmp_path / "tuned.json"
    tuned.write_text(json.dumps(
        {"backend": jax.default_backend(), "dedup": "hop"}))
    cfg = qc.Config()
    _ORIG_LOAD_TUNED(cfg, str(tuned))
    monkeypatch.setattr(qc, "_config", cfg)
    assert qc.resolve_dedup("auto") == "hop"
    monkeypatch.setattr(qc, "_config", None)


def test_persist_dedup_winner_gate(tmp_path, monkeypatch):
    """bench.persist_dedup_winner: only live accelerator A/B pairs are
    persisted; CPU or replayed sections never flip the default."""
    import bench

    tuned = str(tmp_path / "tuned.json")
    live = {"e2e": {"ms_per_step": 100.0, "gather_mode": "lanes"},
            "e2e_dedup_hop": {"ms_per_step": 80.0, "gather_mode": "lanes"}}
    replay = {"e2e": {"ms_per_step": 100.0, "source": "cached:tpu",
                      "gather_mode": "lanes"},
              "e2e_dedup_hop": {"ms_per_step": 80.0,
                                "gather_mode": "lanes"}}
    assert bench.persist_dedup_winner(live, "cpu", tuned) is None
    assert bench.persist_dedup_winner(replay, "tpu", tuned) is None
    assert bench.persist_dedup_winner(live, "tpu", tuned) == "hop"
    import json
    assert bench.read_tuned("tpu", tuned)["dedup"] == "hop"
    live["e2e_dedup_hop"]["ms_per_step"] = 150.0
    assert bench.persist_dedup_winner(live, "tpu", tuned) == "none"
    # merge semantics: a later gather-probe write must keep the dedup key
    bench.merge_tuned({"gather_mode": "pwindow:3", "modes_version": 99},
                      "tpu", tuned)
    t = bench.read_tuned("tpu", tuned)
    assert t["dedup"] == "none" and t["gather_mode"] == "pwindow:3"
    # a CPU write must NOT erase the TPU entry (per-backend v2 format)
    bench.merge_tuned({"gather_mode": "lanes"}, "cpu", tuned)
    assert bench.read_tuned("cpu", tuned)["gather_mode"] == "lanes"
    assert bench.read_tuned("tpu", tuned)["dedup"] == "none"
    # a cross-mode A/B pair is refused
    mixed = {"e2e": {"ms_per_step": 100.0, "gather_mode": "pwindow:3"},
             "e2e_dedup_hop": {"ms_per_step": 80.0,
                               "gather_mode": "lanes"}}
    assert bench.persist_dedup_winner(mixed, "tpu", tuned) is None
    # legacy-format caches WITHOUT the gather_mode stamp are refused too:
    # None == None must not pass as "same mode" (missing on either side
    # or both means the pair's comparability is unknown)
    legacy = {"e2e": {"ms_per_step": 100.0},
              "e2e_dedup_hop": {"ms_per_step": 80.0}}
    assert bench.persist_dedup_winner(legacy, "tpu", tuned) is None
    half = {"e2e": {"ms_per_step": 100.0, "gather_mode": "lanes"},
            "e2e_dedup_hop": {"ms_per_step": 80.0}}
    assert bench.persist_dedup_winner(half, "tpu", tuned) is None


def test_uva_auto_dedup_survives_tuned_hop(monkeypatch, small_graph):
    """A tuned/env dedup='hop' must not crash UVA samplers constructed
    with the default dedup (UVA rides the positional pipeline only)."""
    import numpy as np

    from quiver_tpu import GraphSageSampler

    monkeypatch.setenv("QUIVER_TPU_DEDUP", "hop")
    qconfig._config = None
    s = GraphSageSampler(small_graph, [3], mode="UVA",
                         uva_budget=small_graph.edge_count * 2)
    assert s.dedup == "none"
    s.sample(np.arange(8, dtype=np.int32))
    # an explicit hop still surfaces the incompatibility
    with pytest.raises(AssertionError, match="positional"):
        GraphSageSampler(small_graph, [3], mode="UVA", dedup="hop",
                         uva_budget=small_graph.edge_count * 2)
