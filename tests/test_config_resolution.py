"""Resolution precedence for gather_mode / sample_rng.

Explicit kwarg > env (QUIVER_TPU_*) / tuned file > backend default.
Backend default on CPU (the test backend): gather_mode="xla",
sample_rng="key".  The accelerator branch ("lanes"/"hash",
docs/TPU_MEASUREMENTS.md round 2) can't execute here; the precedence
logic it shares is what's under test.
"""

import os

import pytest

import quiver_tpu.config as qconfig
from quiver_tpu.config import resolve_gather_mode, resolve_sample_rng


@pytest.fixture(autouse=True)
def _clean_config():
    """Reset the config singleton, scrub env overrides, and disable the
    tuned-file loader around each test (a locally-written
    .quiver_tpu_tuned.json must not leak into backend-default asserts)."""
    saved = {k: os.environ.pop(k) for k in
             ("QUIVER_TPU_GATHER_MODE", "QUIVER_TPU_SAMPLE_RNG")
             if k in os.environ}
    saved_loader = qconfig._load_tuned
    qconfig._load_tuned = lambda cfg: None
    qconfig._config = None
    yield
    os.environ.update(saved)
    qconfig._load_tuned = saved_loader
    qconfig._config = None


def test_explicit_wins():
    assert resolve_gather_mode("pallas") == "pallas"
    assert resolve_sample_rng("hash") == "hash"


def test_backend_default_cpu():
    assert resolve_gather_mode("auto") == "xla"
    assert resolve_sample_rng("auto") == "key"


def test_env_overrides_auto():
    os.environ["QUIVER_TPU_GATHER_MODE"] = "lanes"
    os.environ["QUIVER_TPU_SAMPLE_RNG"] = "hash"
    qconfig._config = None
    assert resolve_gather_mode("auto") == "lanes"
    assert resolve_sample_rng("auto") == "hash"


def test_explicit_beats_env():
    os.environ["QUIVER_TPU_GATHER_MODE"] = "lanes"
    os.environ["QUIVER_TPU_SAMPLE_RNG"] = "hash"
    qconfig._config = None
    assert resolve_gather_mode("xla") == "xla"
    assert resolve_sample_rng("key") == "key"


def test_invalid_values_raise():
    with pytest.raises(ValueError):
        resolve_gather_mode("fast")
    with pytest.raises(ValueError):
        resolve_sample_rng("Hash")


def test_invalid_env_raises_not_silently_defaults():
    os.environ["QUIVER_TPU_SAMPLE_RNG"] = "keyed"
    qconfig._config = None
    with pytest.raises(ValueError):
        resolve_sample_rng("auto")


def test_sampler_resolves_at_init(small_graph_factory=None):
    import numpy as np

    from quiver_tpu import CSRTopo, GraphSageSampler
    from quiver_tpu.utils.synthetic import synthetic_csr

    indptr, indices = synthetic_csr(500, 4000, 0)
    topo = CSRTopo(indptr=indptr, indices=indices)
    s = GraphSageSampler(topo, [3], gather_mode="auto", sample_rng="auto")
    assert s.gather_mode == "xla" and s.sample_rng == "key"
    b = s.sample(np.arange(8, dtype=np.int32))
    assert int(b.num_nodes) >= 8
