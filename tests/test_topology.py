import numpy as np
import pytest

from quiver_tpu import CSRTopo, coo_to_csr, parse_size
from quiver_tpu.utils.topology import reindex_feature


def test_coo_to_csr_roundtrip():
    src = np.array([0, 0, 1, 2, 2, 2, 4])
    dst = np.array([1, 2, 0, 0, 1, 3, 4])
    indptr, indices, eid = coo_to_csr(src, dst)
    assert indptr.tolist() == [0, 2, 3, 6, 6, 7]
    assert sorted(indices[0:2].tolist()) == [1, 2]
    assert sorted(indices[3:6].tolist()) == [0, 1, 3]
    # eid maps back to original edge positions
    assert (dst[eid] == indices).all()


def test_csr_topo_from_edge_index():
    src = np.array([0, 1, 1, 2])
    dst = np.array([1, 0, 2, 1])
    topo = CSRTopo(edge_index=np.stack([src, dst]))
    assert topo.node_count == 3
    assert topo.edge_count == 4
    assert topo.degree.tolist() == [1, 2, 1]


def test_csr_topo_from_indptr():
    topo = CSRTopo(indptr=np.array([0, 1, 3]), indices=np.array([1, 0, 1]))
    assert topo.node_count == 2
    assert topo.edge_count == 3


def test_parse_size():
    assert parse_size(1024) == 1024
    assert parse_size("1K") == 1024
    assert parse_size("1KB") == 1024
    assert parse_size("1.5M") == int(1.5 * 2**20)
    assert parse_size("2GB") == 2 * 2**30
    with pytest.raises(ValueError):
        parse_size("abc")


def test_reindex_feature_hot_prefix(small_graph):
    n = small_graph.node_count
    feat = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, 4),
                                                            np.float32)
    ratio = 0.2
    new_feat, new_order = reindex_feature(small_graph, feat, ratio)
    hot = int(n * ratio)
    # permutation property
    assert sorted(new_order.tolist()) == list(range(n))
    # row i of new_feat is old row prev_order[i]; new_order[old] = new row
    old_ids = new_feat[:, 0].astype(np.int64)
    assert (new_order[old_ids] == np.arange(n)).all()
    # hot prefix contains the top-degree nodes (as a set)
    deg = small_graph.degree
    top = set(np.argsort(-deg, kind="stable")[:hot].tolist())
    assert set(old_ids[:hot].tolist()) == top


def test_to_device_roundtrip(small_graph):
    indptr, indices = small_graph.to_device()
    n, e = small_graph.node_count, small_graph.edge_count
    # padded to lane multiples for the fast-gather [rows, 128] view
    assert indptr.shape[0] % 128 == 0 and indptr.shape[0] >= n + 1
    assert indices.shape[0] % 128 == 0 and indices.shape[0] >= e
    np.testing.assert_array_equal(
        np.asarray(indices)[:e], small_graph.indices.astype(np.int32)
    )
    np.testing.assert_array_equal(
        np.asarray(indptr)[: n + 1], small_graph.indptr.astype(np.int32)
    )


def test_to_device_cache_is_per_device(small_graph):
    import jax

    devs = jax.devices()
    a0, _ = small_graph.to_device(devs[0])
    b0, _ = small_graph.to_device(devs[0])
    assert a0 is b0                          # same device: cached
    if len(devs) > 1:
        a1, _ = small_graph.to_device(devs[1])
        assert a1 is not a0                  # regression: the old single-slot
        assert list(a1.devices()) == [devs[1]]  # cache served dev0's arrays
        c0, _ = small_graph.to_device(devs[0])
        assert c0 is a0                      # dev1 placement didn't evict dev0


def test_to_device_invalidate_drops_stale_arrays(small_graph):
    stale_indptr, stale_indices = small_graph.to_device()
    v0 = small_graph.version
    # mutate the topology in place (what the stream compactor's swap
    # protects against) and invalidate
    small_graph.indices_ = small_graph.indices_[::-1].copy()
    small_graph.invalidate()
    assert small_graph.version == v0 + 1
    indptr, indices = small_graph.to_device()
    assert indices is not stale_indices
    e = small_graph.edge_count
    np.testing.assert_array_equal(
        np.asarray(indices)[:e], small_graph.indices.astype(np.int32)
    )
