"""Mesh-native sharded serving suite (docs/SHARDING.md).

The tentpole contract under test: N virtual devices serve as ONE
logical replica — row-range-sharded feature store (halo exchange as a
``shard_map`` collective), frontier exchange reusing the overlay
sampler per shard, and the two pins that make it deployable:

  * **bit-identity** — the sharded sample→gather path produces exactly
    the bytes the single-device staged path produces, for every shard
    count in {1, 2, 4, 8};
  * **steady state builds nothing** — after warmup, serving a fixed
    frontier ladder traces zero new executables and restacks zero
    sharded views.

Plus the fleet face of the tier: shard-group membership/routing
(a group is routable only when complete and fully healthy; one dead
member makes the whole logical replica typed-unavailable, never a
partial answer) and per-shard WAL segments with a coherent group
manifest.  Also hosts the ported MULTICHIP dryrun assertions: 8-device
DP training over the row-sharded dist stack with zero overflow, and
all-to-all DistFeature exactness.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quiver_tpu import telemetry
from quiver_tpu.analysis.retrace_guard import count_jit_builds
from quiver_tpu.mesh import (DATA_AXIS, SHARD_AXIS, MeshFeature,
                             MeshSampler, build_mesh, match_partition_rules,
                             mesh_status, require_devices, shard_ranges)
from quiver_tpu.ops.sample import sample_neighbors_overlay
from quiver_tpu.resilience.breaker import reset as breakers_reset

pytestmark = pytest.mark.mesh

N, D = 1000, 16


def counter_value(name, **labels):
    from quiver_tpu.telemetry.registry import metric_key

    return telemetry.snapshot()["counters"].get(metric_key(name, labels), 0)


def gauge_value(name, **labels):
    from quiver_tpu.telemetry.registry import metric_key

    return telemetry.snapshot()["gauges"].get(metric_key(name, labels))


@pytest.fixture(autouse=True)
def _clean_breakers():
    yield
    breakers_reset()


@pytest.fixture
def table(rng):
    return rng.standard_normal((N, D)).astype(np.float32)


def _csr(rng, n=N, avg_deg=8):
    deg = rng.integers(1, avg_deg * 2, n)
    indptr = np.zeros(n + 1, np.int32)
    indptr[1:] = np.cumsum(deg)
    indices = rng.integers(0, n, indptr[-1]).astype(np.int32)
    return indptr, indices


# ------------------------------------------------------------ topology
class TestTopology:
    def test_shard_ranges_cover_exactly(self):
        rps, ranges = shard_ranges(10, 4)
        assert rps == 3
        assert ranges == [(0, 3), (3, 6), (6, 9), (9, 10)]
        # ownership is a shift: every id maps into its range
        for i in range(10):
            s = i // rps
            lo, hi = ranges[s]
            assert lo <= i < hi

    def test_require_devices_names_the_flag(self):
        with pytest.raises(RuntimeError, match="xla_force_host_platform"):
            require_devices(jax.device_count() + 1)

    def test_build_mesh_axes(self):
        mesh = build_mesh(4)
        assert mesh.axis_names == (DATA_AXIS, SHARD_AXIS)
        assert mesh.shape[SHARD_AXIS] == 4
        assert mesh.shape[DATA_AXIS] == 1

    def test_match_partition_rules(self):
        from jax.sharding import PartitionSpec as P

        tree = {"layers_0": {"kernel": np.zeros((2, 2)),
                             "bias": np.zeros(2)}}
        specs = match_partition_rules(
            [("kernel", P(SHARD_AXIS)), ("bias", P())], tree)
        assert specs["layers_0"]["kernel"] == P(SHARD_AXIS)
        assert specs["layers_0"]["bias"] == P()
        with pytest.raises(ValueError, match="no partition rule"):
            match_partition_rules([("kernel", P())], tree)

    def test_mesh_off_by_default(self):
        from quiver_tpu.config import get_config

        assert get_config().mesh_shards == 0
        with pytest.raises(ValueError, match="mesh_shards"):
            MeshFeature(np.zeros((4, 2), np.float32))


# ------------------------------------------------- sharded feature store
class TestMeshFeature:
    @pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
    def test_gather_bit_identical_to_staged(self, rng, table, n_shards):
        """The acceptance pin: sharded gather == single-device staged
        path, bitwise, for every rehearsal shard count."""
        from quiver_tpu.feature import Feature

        staged = Feature(device_cache_size=N, cache_unit="rows") \
            .from_cpu_tensor(table)
        mf = MeshFeature(table, n_shards=n_shards)
        for B in (1, 7, 64, 200):
            ids = rng.integers(0, N, B)
            want = np.asarray(staged[ids])
            got = np.asarray(mf[ids])
            assert got.dtype == want.dtype
            np.testing.assert_array_equal(got, want)
            np.testing.assert_array_equal(got, table[ids])

    def test_gather_int_dtype_sentinel(self, rng):
        """Integer tables use iinfo.min as the pmax identity — exact."""
        t = rng.integers(-2**30, 2**30, (N, 4)).astype(np.int32)
        mf = MeshFeature(t, n_shards=4)
        ids = rng.integers(0, N, 50)
        np.testing.assert_array_equal(np.asarray(mf[ids]), t[ids])

    def test_steady_state_zero_restacks_zero_builds(self, rng, table):
        mf = MeshFeature(table, n_shards=4)
        streams = [rng.integers(0, N, 64) for _ in range(4)]
        for ids in streams:          # warm epoch: faults + builds happen
            mf[ids]
        restacks = mf.restacks
        with count_jit_builds() as c:
            for ids in streams * 2:  # steady state: same ladder again
                np.testing.assert_array_equal(np.asarray(mf[ids]),
                                              table[ids])
        assert c.builds == 0, c.describe()
        assert mf.restacks == restacks

    @pytest.mark.retrace_budget(2)
    def test_budget_marker_pins_warmed_gather(self, rng, table):
        """The marker counts the whole test: one gather collective +
        one page-fault scatter on first touch of the B=64 bucket, then
        NOTHING — repeated serving stays inside the budget."""
        mf = MeshFeature(table, n_shards=2)
        ids = rng.integers(0, N, 64)
        for _ in range(4):
            mf[ids]

    def test_overflow_falls_back_exact(self, rng, table):
        """A pool too small for the batch working set answers exactly
        from the host table and ticks the fallback counter."""
        mf = MeshFeature(table, n_shards=2, page_rows=8, pool_pages=1)
        before = counter_value("feature_page_fallback_total")
        ids = rng.integers(0, N, 128)
        np.testing.assert_array_equal(np.asarray(mf[ids]), table[ids])
        assert counter_value("feature_page_fallback_total") > before
        assert mf.fallbacks >= 1

    def test_warm_executables_idempotent(self, table):
        mf = MeshFeature(table, n_shards=2)
        built = mf.warm_executables()
        assert built > 0
        assert mf.warm_executables() == 0

    def test_halo_counters_move(self, rng, table):
        mf = MeshFeature(table, n_shards=4)
        ids = rng.integers(0, N, 32)
        sent0 = counter_value("mesh_halo_bytes_total", direction="send")
        mf[ids]
        sent1 = counter_value("mesh_halo_bytes_total", direction="send")
        assert sent1 - sent0 == 32 * D * 4 * 3  # B rows to (n-1) shards
        assert counter_value("mesh_halo_bytes_total",
                             direction="recv") > 0


# ------------------------------------------------- frontier exchange
class TestMeshSampler:
    @pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
    def test_sample_bit_identical(self, rng, n_shards):
        indptr, indices = _csr(rng)
        ms = MeshSampler(indptr, indices, n_shards=n_shards)
        tomb = jnp.zeros(len(indices), jnp.int32)
        for trial in range(3):
            seeds = rng.integers(0, N, 32)
            key = jax.random.PRNGKey(trial)
            got = ms.sample(seeds, 8, key)
            ref = sample_neighbors_overlay(
                jnp.asarray(indptr), jnp.asarray(indices), tomb,
                jnp.zeros(N + 1, jnp.int32), jnp.zeros(8, jnp.int32),
                jnp.asarray(seeds, jnp.int32), 8, key,
                gather_mode=ms.gather_mode, sample_rng=ms.sample_rng)
            for f in ("nbrs", "mask", "counts", "eid"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(got, f)),
                    np.asarray(getattr(ref, f)), err_msg=f)

    def test_frontier_gauge_tracks_ownership(self, rng):
        indptr, indices = _csr(rng)
        ms = MeshSampler(indptr, indices, n_shards=4)
        seeds = np.arange(ms.rows_per_shard // 2)  # all owned by shard 0
        ms.sample(seeds, 4, jax.random.PRNGKey(0))
        assert gauge_value("mesh_shard_frontier_rows",
                           shard="0") == len(seeds)
        assert gauge_value("mesh_shard_frontier_rows", shard="1") == 0

    def test_sample_then_gather_pipeline_bit_identical(self, rng, table):
        """The full sharded serving hop: frontier sample + neighbour
        feature gather — bitwise equal to the unsharded pipeline."""
        indptr, indices = _csr(rng)
        ms = MeshSampler(indptr, indices, n_shards=4)
        mf = MeshFeature(table, n_shards=4)
        seeds = rng.integers(0, N, 16)
        key = jax.random.PRNGKey(11)
        out = ms.sample(seeds, 8, key)
        nbrs = np.asarray(out.nbrs)
        mask = np.asarray(out.mask)
        flat = np.where(mask, nbrs, 0).reshape(-1)
        got = np.asarray(mf[flat])
        ref = sample_neighbors_overlay(
            jnp.asarray(indptr), jnp.asarray(indices),
            jnp.zeros(len(indices), jnp.int32),
            jnp.zeros(N + 1, jnp.int32), jnp.zeros(8, jnp.int32),
            jnp.asarray(seeds, jnp.int32), 8, key,
            gather_mode=ms.gather_mode, sample_rng=ms.sample_rng)
        ref_flat = np.where(np.asarray(ref.mask),
                            np.asarray(ref.nbrs), 0).reshape(-1)
        np.testing.assert_array_equal(flat, ref_flat)
        np.testing.assert_array_equal(got, table[ref_flat])

    def test_steady_state_sampler_builds_nothing(self, rng):
        indptr, indices = _csr(rng)
        ms = MeshSampler(indptr, indices, n_shards=4)
        key = jax.random.PRNGKey(0)
        ms.sample(rng.integers(0, N, 32), 8, key)   # warm (B=32, k=8)
        execs = ms.stats()["executables"]
        with count_jit_builds() as c:
            for trial in range(4):
                ms.sample(rng.integers(0, N, 32), 8,
                          jax.random.PRNGKey(trial))
        assert c.builds == 0, c.describe()
        assert ms.stats()["executables"] == execs


# ---------------------------------------- MULTICHIP dryrun assertions
class TestMultichipDryrun:
    """Ported from the driver's MULTICHIP dryrun: the 8-device DP dist
    stack stays exact and overflow-free at dryrun scale."""

    def test_dp_training_8dev_zero_overflow(self):
        from quiver_tpu.dist.e2e import run_dist_training

        out = run_dist_training(n_devices=8, n_nodes=512, avg_deg=8,
                                feat_dim=8, batch_per_dev=8,
                                sizes=[4, 3], steps=2, seed=0)
        assert all(np.isfinite(l) for l in out["losses"])
        assert out["sampler_overflow"].sum() == 0
        assert out["feature_overflow"] == 0

    def test_dist_feature_all_to_all_exact(self, rng):
        from quiver_tpu.dist import DistFeature, PartitionInfo
        from quiver_tpu.utils.mesh import make_mesh

        nhosts = 8
        mesh = make_mesh(("data",), devices=jax.devices()[:nhosts])
        full = rng.normal(size=(256, 8)).astype(np.float32)
        g2h = rng.integers(0, nhosts, 256).astype(np.int32)
        info = PartitionInfo(host=0, hosts=nhosts, global2host=g2h)
        df = DistFeature.from_global_feature(full, mesh, info)
        ids = rng.integers(0, 256, (nhosts, 32)).astype(np.int32)
        out = np.asarray(df.lookup(ids))
        for h in range(nhosts):
            np.testing.assert_allclose(out[h], full[ids[h]], rtol=1e-6)


# --------------------------------------------------- subprocess rehearsal
class TestSubprocessRehearsal:
    def test_mesh_in_isolated_device_count(self, devices_subprocess):
        """The conftest helper boots a child with its OWN virtual device
        count — here a 2-device mesh gathers exactly in a process whose
        device count differs from the suite's 8."""
        code = """
import numpy as np
from quiver_tpu.mesh import MeshFeature
t = np.arange(40, dtype=np.float32).reshape(10, 4)
mf = MeshFeature(t, n_shards=2)
ids = np.array([0, 3, 5, 9, 9, 1])
assert (np.asarray(mf[ids]) == t[ids]).all()
print("MESH_CHILD_OK", mf.n_shards)
"""
        res = devices_subprocess(code, n_devices=2)
        assert res.returncode == 0, res.stderr
        assert "MESH_CHILD_OK 2" in res.stdout


# ------------------------------------------------------ shard groups
class TestShardGroups:
    def _info(self, rid, gid=None, idx=0, count=0, state="serving"):
        import time as _t

        from quiver_tpu.fleet.membership import ReplicaInfo

        detail = {}
        if gid is not None:
            detail = {"shard_group": gid, "shard_index": idx,
                      "shard_count": count}
        return ReplicaInfo(replica_id=rid, state=state,
                           heartbeat=_t.time(), detail=detail)

    def test_grouping_and_completeness(self):
        from quiver_tpu.fleet.membership import (group_complete,
                                                 shard_groups)

        infos = [self._info("b", "g1", 1, 2), self._info("a", "g1", 0, 2),
                 self._info("solo")]
        groups = shard_groups(infos)
        assert list(groups) == ["g1"]
        assert [m.replica_id for m in groups["g1"]] == ["a", "b"]
        assert group_complete(groups["g1"])
        # half-booted, duplicated, or disagreeing groups never route
        assert not group_complete([self._info("a", "g1", 0, 2)])
        assert not group_complete([self._info("a", "g1", 0, 2),
                                   self._info("b", "g1", 0, 2)])
        assert not group_complete([self._info("a", "g1", 0, 2),
                                   self._info("b", "g1", 1, 3)])
        assert not group_complete([])

    def test_router_routes_complete_group_as_unit(self, tmp_path):
        from quiver_tpu.fleet import FleetRouter, MembershipDirectory

        d = MembershipDirectory(str(tmp_path), heartbeat_timeout_s=30.0)
        d.announce(self._info("s0", "g1", 0, 2))
        d.announce(self._info("s1", "g1", 1, 2))
        d.announce(self._info("solo"))
        router = FleetRouter(d, scan_ttl_s=0.0)
        try:
            router.refresh(force=True)
            assert sorted(router.ring.members) == ["group:g1", "solo"]
            assert gauge_value("fleet_shard_group_members",
                               group="g1") == 2
            st = router.status()
            assert st["shard_groups"] == {"g1": ["s0", "s1"]}
        finally:
            router.close()

    def test_incomplete_group_takes_no_traffic(self, tmp_path):
        from quiver_tpu.fleet import FleetRouter, MembershipDirectory
        from quiver_tpu.resilience.errors import NoReplicaAvailable

        d = MembershipDirectory(str(tmp_path), heartbeat_timeout_s=30.0)
        d.announce(self._info("s0", "g1", 0, 2))  # shard 1 never joined
        router = FleetRouter(d, scan_ttl_s=0.0, route_retries=1)
        try:
            router.refresh(force=True)
            assert router.ring.members == ()
            with pytest.raises(NoReplicaAvailable):
                router.request([1], sleep=lambda _s: None)
        finally:
            router.close()

    def test_unhealthy_member_removes_whole_group(self, tmp_path):
        from quiver_tpu.fleet import FleetRouter, MembershipDirectory

        d = MembershipDirectory(str(tmp_path), heartbeat_timeout_s=30.0)
        d.announce(self._info("s0", "g1", 0, 2))
        d.announce(self._info("s1", "g1", 1, 2))
        router = FleetRouter(d, scan_ttl_s=0.0)
        try:
            router.refresh(force=True)
            assert "group:g1" in router.ring.members
            with router._lock:
                router._health_ok["s1"] = False   # non-coordinator dies
            router.refresh(force=True)
            assert router.ring.members == ()
        finally:
            router.close()


# --------------------------------------- shard group end-to-end serving
class TestShardGroupServing:
    def _spawn_member(self, tmp_path, members, rid, idx, service_fn):
        from quiver_tpu.fleet import FleetReplica
        from quiver_tpu.stream import StreamingGraph
        from quiver_tpu.utils.topology import CSRTopo

        def _graph():
            src = np.arange(8, dtype=np.int64)
            return CSRTopo(edge_index=np.stack([src, (src + 1) % 8]))

        rep = FleetReplica(
            rid, fleet_dir=str(tmp_path / "fleet"),
            root=str(tmp_path / f"dur-{rid}"),
            graph_factory=lambda: StreamingGraph(_graph(),
                                                 delta_capacity=64),
            role="leader", heartbeat_s=0.1, service_fn=service_fn,
            shard_group="g1", shard_index=idx, shard_count=2).boot()
        members.append(rep)
        return rep

    def test_group_failover_typed_unavailable(self, tmp_path):
        """The acceptance scenario: a 2-member shard group serves as
        one unit; one member dying yields a typed NoReplicaAvailable —
        answered (with an error), never dropped, never partial."""
        from quiver_tpu.fleet import FleetRouter, MembershipDirectory
        from quiver_tpu.resilience.errors import NoReplicaAvailable

        members = []
        directory = MembershipDirectory(str(tmp_path / "fleet"),
                                        heartbeat_timeout_s=5.0)
        router = None
        try:
            s0 = self._spawn_member(
                tmp_path, members, "s0", 0,
                lambda ids, tenant: {"answered_by": "s0",
                                     "n": len(ids)})
            self._spawn_member(
                tmp_path, members, "s1", 1,
                lambda ids, tenant: {"answered_by": "s1",
                                     "n": len(ids)})
            router = FleetRouter(directory, scan_ttl_s=0.0,
                                 request_timeout_s=2.0, route_retries=1)
            router.refresh(force=True)
            assert router.ring.members == ("group:g1",)
            # requests land on the shard-0 coordinator of the group
            reply = router.request([1, 2, 3])
            assert reply["status"] == "ok"
            assert reply["replica"] == "s0"
            assert reply["answered_by"] == "s0"
            assert counter_value("fleet_router_requests_total",
                                 replica="group:g1", status="ok") >= 1
            # one member dies -> the group leaves the ring -> typed
            # unavailable for every caller; no request is silently lost
            members[1].stop()
            router.refresh(force=True)
            assert router.ring.members == ()
            with pytest.raises(NoReplicaAvailable):
                router.request([1], sleep=lambda _s: None)
            # the surviving member alone must NOT serve group traffic
            assert directory.get("s0") is not None
            assert s0.state == "serving"
        finally:
            if router is not None:
                router.close()
            for rep in reversed(members):
                rep.stop()

    def test_member_announces_shard_detail(self, tmp_path):
        from quiver_tpu.fleet import FleetReplica

        os.makedirs(tmp_path / "fleet", exist_ok=True)
        rep = FleetReplica("m0", fleet_dir=str(tmp_path / "fleet"),
                           root=str(tmp_path / "dur"),
                           shard_group="g7", shard_index=1,
                           shard_count=4)
        info = rep._info()
        assert info.shard_group == "g7"
        assert info.shard_index == 1
        assert info.shard_count == 4
        # unsharded replicas carry none of the keys (pre-mesh records)
        plain = FleetReplica("m1", fleet_dir=str(tmp_path / "fleet"),
                             root=str(tmp_path / "dur"))
        assert plain._info().shard_group is None
        assert "shard_index" not in plain._info().detail


# ------------------------------------------------- per-shard WAL + manifest
class TestShardGroupWAL:
    def test_coherent_replay_stops_at_manifest(self, tmp_path):
        from quiver_tpu.recovery.shardwal import ShardGroupWAL

        w = ShardGroupWAL(str(tmp_path), n_shards=2, group="g1",
                          fsync="off")
        for i in range(4):
            w.append(0, f"a{i}".encode())
        w.append(1, b"b0")
        m = w.publish_manifest()
        assert m.lsns == [3, 0]
        # writes AFTER the group commit point are the un-acked tail
        w.append(0, b"a4")
        w.append(1, b"b1")
        got0 = [p for _lsn, p in w.replay(0)]
        got1 = [p for _lsn, p in w.replay(1)]
        assert got0 == [b"a0", b"a1", b"a2", b"a3"]
        assert got1 == [b"b0"]
        assert w.tail_lsns() == [1, 1]
        w.close()

    def test_no_manifest_replays_nothing(self, tmp_path):
        from quiver_tpu.recovery.shardwal import ShardGroupWAL

        w = ShardGroupWAL(str(tmp_path), n_shards=2, fsync="off")
        w.append(0, b"x")
        assert list(w.replay(0)) == []
        w.close()

    def test_manifest_survives_reopen_and_versions(self, tmp_path):
        from quiver_tpu.recovery.shardwal import (ShardGroupWAL,
                                                  load_manifest)

        w = ShardGroupWAL(str(tmp_path), n_shards=2, fsync="off")
        w.append(0, b"x")
        v1 = w.publish_manifest().version
        w.append(1, b"y")
        v2 = w.publish_manifest().version
        assert v2 == v1 + 1
        w.close()
        # a fresh process resumes versioning past what is on disk
        w2 = ShardGroupWAL(str(tmp_path), n_shards=2, fsync="off")
        assert load_manifest(str(tmp_path)).version == v2
        assert w2.publish_manifest().version == v2 + 1
        got = [p for _lsn, p in w2.replay(1)]
        assert got == [b"y"]
        w2.close()

    def test_garbage_manifest_is_loud(self, tmp_path):
        from quiver_tpu.recovery.errors import RecoveryError
        from quiver_tpu.recovery.shardwal import load_manifest

        path = tmp_path / "group-manifest.json"
        path.write_bytes(b"{torn")
        with pytest.raises(RecoveryError, match="manifest"):
            load_manifest(str(tmp_path))

    def test_truncate_through_manifest(self, tmp_path):
        from quiver_tpu.recovery.shardwal import ShardGroupWAL

        w = ShardGroupWAL(str(tmp_path), n_shards=1, fsync="off",
                          segment_bytes=64)
        for i in range(40):
            w.append(0, b"payload-%d" % i)
        w.publish_manifest()
        assert w.truncate_through_manifest() > 0
        # everything the manifest vouches for past the cut is intact
        lsns = [lsn for lsn, _p in w.replay(0)]
        assert lsns == sorted(lsns)
        assert lsns[-1] == 39
        w.close()


# --------------------------------------------------------- observability
class TestMeshObservability:
    def test_mesh_status_active_document(self, table):
        mf = MeshFeature(table, n_shards=2)
        doc = mesh_status()
        assert doc["active"] is True
        assert doc["n_shards"] == 2
        assert doc["feature"]["rows_per_shard"] == mf.rows_per_shard

    def test_debug_mesh_endpoint(self, table):
        from quiver_tpu.telemetry.export import MetricsServer

        # hold a strong ref: the /debug/mesh registry is a weakref and
        # the instance's internal cycle frees on an arbitrary gc tick
        mf = MeshFeature(table, n_shards=2)
        srv = MetricsServer()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/mesh",
                    timeout=10) as resp:
                doc = json.loads(resp.read())
            assert doc["active"] is True
            assert doc["n_shards"] == 2
            assert doc["feature"]["rows_per_shard"] == mf.rows_per_shard
        finally:
            srv.close()

    def test_gather_seconds_histogram_observes(self, rng, table):
        from quiver_tpu.telemetry.registry import metric_key

        mf = MeshFeature(table, n_shards=2)
        mf[rng.integers(0, N, 16)]
        hists = telemetry.snapshot()["histograms"]
        key = metric_key("mesh_shard_gather_seconds", {})
        assert sum(hists[key]["counts"]) >= 1
