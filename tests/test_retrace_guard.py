"""retrace_guard: the runtime half of the retrace contract.

Covers the counting context manager directly (exact build counts on the
real sampler), the ``@pytest.mark.retrace_budget`` marker in-process on
the passing path, the enforcement failure path as a unit, and — the
acceptance check — a subprocess pytest run where a deliberately
cache-busting test MUST fail with "retrace budget exceeded".
"""

import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import jax
import pytest

from quiver_tpu import GraphSageSampler
from quiver_tpu.analysis.retrace_guard import (
    JitBuildCounter, count_jit_builds, enforce_budget,
)

REPO = Path(__file__).resolve().parents[1]


# ----------------------------------------------------- counting context
def test_counts_one_build_per_distinct_batch_size(small_graph):
    s = GraphSageSampler(small_graph, [4, 3])
    with count_jit_builds() as c:
        for B in [8, 16, 8, 32, 16, 8, 32]:
            s.sample(np.arange(B, dtype=np.int64),
                     key=jax.random.PRNGKey(B))
    assert c.builds == 3
    assert sorted(k for _, k in c.sites) == [8, 16, 32]
    assert all(site == "sampler._build_jit" for site, _ in c.sites)
    # warm cache: a second sweep builds nothing
    with count_jit_builds() as c2:
        for B in [8, 16, 32]:
            s.sample(np.arange(B, dtype=np.int64),
                     key=jax.random.PRNGKey(B))
    assert c2.builds == 0


def test_patches_are_restored_on_exit(small_graph):
    before = GraphSageSampler.__dict__["_build_jit"]
    with count_jit_builds():
        assert GraphSageSampler.__dict__["_build_jit"] is not before
    assert GraphSageSampler.__dict__["_build_jit"] is before


def test_backend_compile_listener_sees_xla_compiles(small_graph):
    s = GraphSageSampler(small_graph, [4, 3])
    with count_jit_builds() as c:
        s.sample(np.arange(64, dtype=np.int64), key=jax.random.PRNGKey(0))
    if not c.backend_available:   # private jax API moved: soft-degrade
        pytest.skip("jax monitoring listener unavailable")
    assert c.builds == 1
    assert c.backend_compiles >= 1


# ----------------------------------------------------- marker: pass path
@pytest.mark.retrace_budget(3)
def test_marker_passes_within_budget(small_graph):
    s = GraphSageSampler(small_graph, [4, 3])
    for B in [8, 16, 8, 32, 16, 8, 32]:   # 3 distinct shapes == budget
        b = s.sample(np.arange(B, dtype=np.int64),
                     key=jax.random.PRNGKey(B))
        assert b.batch_size == B


# ----------------------------------------------------- enforcement unit
def test_enforce_budget_failure_message():
    c = JitBuildCounter()
    for B in (8, 16, 32, 64):
        c.record("sampler._build_jit", B)
    with pytest.raises(pytest.fail.Exception,
                       match=r"retrace budget exceeded: 4 jit build"):
        enforce_budget(c, builds=3, nodeid="test_x")
    enforce_budget(c, builds=4)   # at the budget: no failure

    c.backend_available = True
    c.backend_compiles = 9
    with pytest.raises(pytest.fail.Exception, match="backend compile"):
        enforce_budget(c, builds=None, backend_compiles=2)


def test_enforce_budget_ignores_backend_when_unavailable():
    c = JitBuildCounter()
    c.backend_compiles = 9        # stale garbage, but listener never ran
    assert c.backend_available is False
    enforce_budget(c, builds=None, backend_compiles=0)


# ------------------------------------------- acceptance: cache buster
def test_cache_busting_test_fails_in_subprocess(tmp_path):
    """A test that builds more executables than its budget must FAIL —
    run in a real pytest subprocess with the same conftest wiring the
    suite uses (env staging, then star-import of the plugin)."""
    (tmp_path / "conftest.py").write_text(textwrap.dedent("""
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"

        from quiver_tpu.analysis.retrace_guard import *  # noqa: F401,F403
    """))
    (tmp_path / "test_bust.py").write_text(textwrap.dedent("""
        import numpy as np
        import pytest

        from quiver_tpu import CSRTopo, GraphSageSampler


        @pytest.mark.retrace_budget(1)
        def test_cache_buster():
            rng = np.random.default_rng(0)
            src = rng.integers(0, 60, 400)
            dst = rng.integers(0, 60, 400)
            topo = CSRTopo(edge_index=np.stack([src, dst]))
            s = GraphSageSampler(topo, [3, 2])
            for B in (4, 8, 16):     # 3 distinct shapes, budget is 1
                s.sample(np.arange(B, dtype=np.int32))
    """))
    env = dict(os.environ, PYTHONPATH=str(REPO), JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "test_bust.py", "-q", "-p",
         "no:cacheprovider"],
        capture_output=True, text=True, timeout=600, cwd=str(tmp_path),
        env=env)
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert "retrace budget exceeded" in proc.stdout
    assert "3 jit build(s) > budget 1" in proc.stdout
