"""The repo must lint clean — quiverlint is part of tier-1.

This is the CI gate the baseline workflow exists for: pre-existing,
justified findings live in ``quiverlint.baseline.json``; anything new
fails here.  The injected-violation tests prove the gate actually has
teeth end to end (``python -m`` exit codes, not just library calls).
"""

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from quiver_tpu.analysis import analyze_paths
from quiver_tpu.analysis import baseline as baseline_mod

REPO = Path(__file__).resolve().parents[1]
LINT_TARGETS = ["quiver_tpu", "bench.py"]


def test_repo_lints_clean_against_baseline():
    result = analyze_paths(LINT_TARGETS, root=REPO)
    assert result.errors == []
    baseline = baseline_mod.load(REPO / baseline_mod.DEFAULT_BASELINE_NAME)
    new, _ = baseline_mod.partition(result.findings, baseline)
    assert new == [], "new quiverlint findings:\n" + "\n".join(
        f.format() for f in new)


def test_cli_exits_zero_on_repo():
    proc = subprocess.run(
        [sys.executable, "-m", "quiver_tpu.analysis", *LINT_TARGETS],
        capture_output=True, text=True, timeout=300, cwd=str(REPO))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def _repo_copy_with(tmp_path, relpath, appended):
    """Copy the lint targets into tmp_path and append ``appended`` to
    ``relpath`` — an injected violation in an otherwise-clean tree."""
    shutil.copytree(REPO / "quiver_tpu", tmp_path / "quiver_tpu")
    shutil.copy(REPO / "bench.py", tmp_path / "bench.py")
    shutil.copy(REPO / baseline_mod.DEFAULT_BASELINE_NAME,
                tmp_path / baseline_mod.DEFAULT_BASELINE_NAME)
    target = tmp_path / relpath
    target.write_text(target.read_text() + appended)
    return tmp_path


def test_cli_strict_baseline_clean_on_repo():
    """No stale baseline entries: every accepted finding must still be
    reported (the debt ledger only shrinks)."""
    proc = subprocess.run(
        [sys.executable, "-m", "quiver_tpu.analysis", "--strict-baseline",
         *LINT_TARGETS],
        capture_output=True, text=True, timeout=300, cwd=str(REPO))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_stale_baseline_entry_fails_only_under_strict(tmp_path):
    shutil.copytree(REPO / "quiver_tpu", tmp_path / "quiver_tpu")
    shutil.copy(REPO / "bench.py", tmp_path / "bench.py")
    doc = json.loads(
        (REPO / baseline_mod.DEFAULT_BASELINE_NAME).read_text())
    doc["findings"].append({
        "rule": "QT001", "path": "quiver_tpu/sampler.py", "line": 1,
        "col": 0, "scope": "ghost", "message": "fixed long ago",
        "snippet": "x = jax.device_get(y)"})
    (tmp_path / baseline_mod.DEFAULT_BASELINE_NAME).write_text(
        json.dumps(doc))
    base_cmd = [sys.executable, "-m", "quiver_tpu.analysis", *LINT_TARGETS]
    lax = subprocess.run(base_cmd, capture_output=True, text=True,
                         timeout=300, cwd=str(tmp_path))
    assert lax.returncode == 0, lax.stdout + lax.stderr
    strict = subprocess.run(base_cmd + ["--strict-baseline"],
                            capture_output=True, text=True, timeout=300,
                            cwd=str(tmp_path))
    assert strict.returncode == 1, strict.stdout + strict.stderr
    assert "stale baseline entry" in strict.stdout


@pytest.mark.parametrize("relpath, code, appended", [
    ("quiver_tpu/feature.py", "QT008",
     "\n\ndef _racy_publish(feat: \"Feature\"):\n"
     "    feat.hot = None\n"),
    ("quiver_tpu/serving.py", "QT009",
     "\n\nclass _Inverted:\n"
     "    def __init__(self):\n"
     "        self._qa = threading.Lock()\n"
     "        self._qb = threading.Lock()\n"
     "\n"
     "    def fwd(self):\n"
     "        with self._qa:\n"
     "            with self._qb:\n"
     "                pass\n"
     "\n"
     "    def bwd(self):\n"
     "        with self._qb:\n"
     "            with self._qa:\n"
     "                pass\n"),
    ("quiver_tpu/sampler.py", "QT001",
     "\n\ndef _leaky(x):\n"
     "    import jax\n"
     "    return jax.device_get(x)\n"),
    ("quiver_tpu/sampler.py", "QT002",
     "\n\ndef _retracey(f, xs):\n"
     "    import jax\n"
     "    for x in xs:\n"
     "        x = jax.jit(f)(x)\n"
     "    return x\n"),
    ("quiver_tpu/serving.py", "QT006",
     "\n\ndef _bad_metric(bucket):\n"
     "    telemetry.counter(f\"serving_bucket_{bucket}_total\").inc()\n"),
    ("quiver_tpu/serving.py", "QT007",
     "\n\ndef _doomed_loop(q):\n"
     "    while True:\n"
     "        try:\n"
     "            q.get()\n"
     "        except Exception:\n"
     "            continue\n"),
    ("quiver_tpu/recovery/wal.py", "QT011",
     "\n\ndef _sneaky_sidecar(path):\n"
     "    with open(path, \"w\") as f:\n"
     "        f.write(\"unframed, unchecksummed\")\n"),
    ("quiver_tpu/serving.py", "QT012",
     "\n\ndef _wall_timed(fn):\n"
     "    t0 = time.time()\n"
     "    fn()\n"
     "    return time.time() - t0\n"),
    # v3: the device value crosses a function boundary, so QT001's
    # local tracking can't see it — only the staging dataflow can
    ("quiver_tpu/sampler.py", "QT013",
     "\n\ndef _inj_gather_scores(xs):\n"
     "    return jnp.asarray(xs).sum()\n"
     "\n"
     "def _inj_mean_score(xs):\n"
     "    return float(_inj_gather_scores(xs)) / max(len(xs), 1)\n"),
    # v3: executable cache keyed by a raw batch length — every novel
    # size compiles a new program (no bucket helper, no directive)
    ("quiver_tpu/serving.py", "QT014",
     "\n\nfrom .recovery.registry import program_cache\n"
     "\n"
     "class _InjExecCache:\n"
     "    def __init__(self):\n"
     "        self._fns = program_cache(\"inj\", owner=self)\n"
     "\n"
     "    def get(self, ids):\n"
     "        n = int(ids.shape[0])\n"
     "        if n not in self._fns:\n"
     "            self._fns[n] = object()\n"
     "        return self._fns[n]\n"),
    # v3: float psum in a bit-exactness module (mesh/*) — order of
    # reduction varies with shard layout, breaking the replica contract
    ("quiver_tpu/mesh/sampler.py", "QT015",
     "\n\ndef _inj_combine(x):\n"
     "    import jax\n"
     "    return jax.lax.psum(x, \"shard\")\n"
     "\n"
     "def _inj_allmean(x):\n"
     "    import jax\n"
     "    return jax.pmap(_inj_combine, axis_name=\"shard\")(x)\n"),
])
def test_injected_violation_fails_cli(tmp_path, relpath, code, appended):
    root = _repo_copy_with(tmp_path, relpath, appended)
    proc = subprocess.run(
        [sys.executable, "-m", "quiver_tpu.analysis", *LINT_TARGETS,
         "--format", "json"],
        capture_output=True, text=True, timeout=300, cwd=str(root),
        env=None)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert [f["rule"] for f in doc["findings"]] == [code]
    assert doc["findings"][0]["path"] == relpath
