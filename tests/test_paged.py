"""Paged feature store + ragged page-gather kernel suite (``make paged``).

Correctness bar (docs/FEATURE_CACHE.md): a Feature with the paged store
enabled must return rows BIT-IDENTICAL to the staged three-tier merge
under every residency mix — hot-only, overlay hits, host faults, mixed
traffic, pool overflow fallback, ``feature_order`` translation — while
the executable count collapses from the staged ``(B, bucket)`` grid to
at most two programs per batch size (the ragged gather plus the
page-fault scatter), and page residency survives a checkpoint/restore
cycle including a kill -9 (the ``make crash`` variant).

``feature_paged=off`` (the default) must be a byte-identical no-op:
no ``feature_page_*`` metric keys, no ``("paged", ...)`` executable
keys — PR 9 behavior untouched.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

import quiver_tpu.config as config_mod
from quiver_tpu import Feature, telemetry
from quiver_tpu.analysis.retrace_guard import count_jit_builds
from quiver_tpu.ops.paged import (DEVICE, HOST, OVERLAY, PageTable,
                                  _plan_geometry, default_page_rows)
from quiver_tpu.ops.pallas.page_gather_kernel import page_gather

pytestmark = pytest.mark.paged

REPO = Path(__file__).resolve().parents[1]

# one geometry shared by the feature-level suites: 512 rows, 128 hot,
# page_rows=8 -> 16 hot pages + 48 host pages
N, D, HOT, R = 512, 16, 128, 8
N_HOST_PAGES = (N - HOT) // R


def _counter(name):
    return telemetry.snapshot()["counters"].get(name, 0.0)


def _feats(rng, n=N, d=D):
    return rng.standard_normal((n, d)).astype(np.float32)


def _paged_feature(feats, hot_rows=HOT, **kw):
    f = Feature(device_cache_size=hot_rows,
                cache_unit="rows").from_cpu_tensor(feats)
    kw.setdefault("page_rows", R)
    f.enable_paging(**kw)
    return f


def _cold_ids(rng, size, lo=HOT, hi=N):
    return rng.integers(lo, hi, size=size).astype(np.int64)


# ------------------------------------------------------------- geometry
class TestGeometry:
    def test_transaction_multiple_and_floor(self):
        for row_bytes in (4, 12, 64, 128, 512, 640):
            r = default_page_rows(row_bytes)
            assert (r * row_bytes) % 512 == 0, row_bytes
            assert r * row_bytes >= 4096, row_bytes

    def test_odd_row_width_still_aligns(self):
        # odd byte widths force r up to a multiple of 512 rows — the
        # page stays whole-transaction even for awkward dims
        r = default_page_rows(7)
        assert (r * 7) % 512 == 0 and r * 7 >= 4096

    def test_target_override(self):
        assert default_page_rows(128, target_bytes=512) == 4

    def test_block_plan_is_lane_friendly_and_bounded(self):
        for page_rows, dim in ((8, 16), (32, 128), (256, 1024)):
            block, ppb = _plan_geometry(page_rows, dim, 4)
            assert block % 8 == 0 and 8 <= block <= 128
            assert ppb == block  # worst case: every row its own page


# ------------------------------------------------------------ page table
class TestPageTable:
    def test_partition_and_initial_states(self):
        t = PageTable(n_rows=100, cache_count=20, page_rows=8,
                      pool_pages=4)
        assert t.n_pages == 13 and t.hot_pages == 3
        assert t.n_host_pages == 10 and t.pool_pages == 4
        assert t.n_frames == 7
        assert all(t.state_of(p) == DEVICE for p in range(3))
        assert all(t.state_of(p) == HOST for p in range(3, 13))
        assert t.resident_pages() == 3  # hot pages are pinned resident

    def test_fault_and_invalidate_transitions(self, rng):
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=8)
        t = f.paged.table
        page = (HOT // R) + 2                 # a host-space page
        nid = np.array([page * R + 3], dtype=np.int64)
        assert t.state_of(page) == HOST
        f[nid]                                 # gather faults it in
        assert t.state_of(page) == OVERLAY
        f.invalidate_rows(nid)                 # stream mutation drops it
        assert t.state_of(page) == HOST

    def test_pool_clamped_to_host_pages(self, rng):
        f = _paged_feature(_feats(rng), pool_pages=10_000)
        assert f.paged.table.pool_pages == N_HOST_PAGES


# ------------------------------------------------------------ raw kernel
class TestKernel:
    def test_hand_built_plan_matches_reference(self):
        """Drive ``page_gather`` directly with a hand-built ragged plan
        (two blocks, different distinct-page counts, padded tail)."""
        rng = np.random.default_rng(7)
        F, pr, d, block, ppb = 5, 4, 8, 8, 8
        frames = rng.standard_normal((F, pr, d)).astype(np.float32)
        nb, M, B = 2, 16, 13           # 3 padded rows in block 1
        blk_np = np.array([3, 2], dtype=np.int32)
        blk_pages = np.zeros(nb * ppb, dtype=np.int32)
        blk_pages[0:3] = [0, 2, 4]
        blk_pages[ppb:ppb + 2] = [1, 3]
        row_lp = np.zeros(M, dtype=np.int32)
        row_off = np.zeros(M, dtype=np.int32)
        for i in range(B):
            b = i // block
            row_lp[i] = rng.integers(0, blk_np[b])
            row_off[i] = rng.integers(0, pr)
        out = np.asarray(page_gather(
            jnp.asarray(frames), jnp.asarray(blk_pages),
            jnp.asarray(blk_np), jnp.asarray(row_lp),
            jnp.asarray(row_off), page_rows=pr, block=block, ppb=ppb,
            interpret=True))
        assert out.shape == (M, d)
        for i in range(M):
            src = blk_pages[(i // block) * ppb + row_lp[i]]
            np.testing.assert_array_equal(out[i], frames[src, row_off[i]])


# -------------------------------------------------- bit-identical mixes
class TestPagedEquivalence:
    """Seeded property suite: every residency mix must come back equal
    to the source tensor bit for bit (float32 rows pass through gathers
    and scatters untouched — any mismatch is a planner/kernel bug)."""

    def test_hot_only(self, rng):
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=16)
        for _ in range(4):
            ids = rng.integers(0, HOT, size=64).astype(np.int64)
            np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
        assert f.paged.table.cache.resident == 0  # never touched host

    def test_overlay_hits_serve_without_refaulting(self, rng):
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=16)
        ids = _cold_ids(rng, 64, hi=HOT + 16 * R)  # <= 16 distinct pages
        np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
        faults = _counter("feature_page_faults_total")
        hits = _counter("feature_page_hits_total")
        np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
        assert _counter("feature_page_faults_total") == faults
        assert _counter("feature_page_hits_total") > hits

    def test_host_faults_fresh_pages_every_batch(self, rng):
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=N_HOST_PAGES)
        for i in range(6):                    # disjoint 8-page windows
            lo = HOT + i * 8 * R
            ids = rng.integers(lo, lo + 8 * R, size=48).astype(np.int64)
            faults = _counter("feature_page_faults_total")
            np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
            assert _counter("feature_page_faults_total") > faults

    def test_mixed_traffic_vs_staged_reference(self, rng):
        """The headline property: paged vs the PR-9 staged overlay on
        the SAME stream, compared row for row."""
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=N_HOST_PAGES)
        ref = Feature(device_cache_size=HOT,
                      cache_unit="rows").from_cpu_tensor(feats)
        ref.enable_cold_cache(rows=64, admit_threshold=2)
        for i in range(30):
            B = int(rng.integers(1, 128))
            ids = rng.integers(0, N, size=B).astype(np.int64)
            if i % 3 == 0:                    # duplicates in one batch
                ids[: B // 2 + 1] = ids[0]
            got = np.asarray(f[ids])
            np.testing.assert_array_equal(got, np.asarray(ref[ids]))
            np.testing.assert_array_equal(got, feats[ids])

    def test_boundary_page_straddles_hot_edge(self, rng):
        """cache_count not a page multiple: the boundary DEVICE page is
        padded with REAL host rows, so ids just past the hot edge are
        served from the pinned page, not zeros."""
        feats = _feats(rng)
        f = _paged_feature(feats, hot_rows=HOT + 2, pool_pages=16)
        assert f.cache_count % R != 0          # genuinely straddles
        ids = np.arange(f.cache_count - 4, f.cache_count + 8,
                        dtype=np.int64)
        faults = _counter("feature_page_faults_total")
        np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
        # rows in the boundary page's tail came from DEVICE, only the
        # ids past the page boundary faulted
        assert _counter("feature_page_faults_total") <= faults + 1

    def test_feature_order_translation(self, rng):
        prob = rng.random(N)
        feats = _feats(rng)
        f = Feature(device_cache_size=HOT,
                    cache_unit="rows").from_cpu_tensor(feats, prob=prob)
        f.enable_paging(page_rows=R, pool_pages=N_HOST_PAGES)
        for _ in range(5):
            ids = rng.integers(0, N, size=64).astype(np.int64)
            np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])

    def test_pool_overflow_falls_back_bit_identical(self, rng):
        """A batch whose page working set exceeds the OVERLAY pool must
        fall back to the staged merge — correct, counted, never wrong."""
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=8)
        ids = (HOT + rng.choice(N - HOT, size=96,
                                replace=False)).astype(np.int64)
        before = _counter("feature_page_fallback_total")
        np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
        assert f.paged.fallbacks > 0
        assert _counter("feature_page_fallback_total") > before

    def test_tail_partial_page(self, rng):
        """N not a page multiple: the last HOST page is short; gathering
        its rows must not read past the host tail."""
        feats = _feats(rng, n=N + 3)
        f = Feature(device_cache_size=HOT,
                    cache_unit="rows").from_cpu_tensor(feats)
        f.enable_paging(page_rows=R, pool_pages=16)
        ids = np.arange(N - 2, N + 3, dtype=np.int64)  # spans the tail
        np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])


# ------------------------------------------------- bucket-edge sentinel
class TestBucketEdgeRegression:
    """Satellite: the staged path's padding sentinel.  When the cold
    count lands EXACTLY on a pow2/quarter-octave bucket edge, padded
    lanes must stay out of range of both the staging buffer and the
    output scatter (``_stage``/``_stage_overlay`` carry bounds
    assertions; these streams would trip them if the sentinel ever
    regressed)."""

    EDGES = (15, 16, 17, 31, 32, 33, 63, 64)

    def test_staged_cold_count_on_bucket_edges(self, rng):
        feats = _feats(rng)
        f = Feature(device_cache_size=HOT,
                    cache_unit="rows").from_cpu_tensor(feats)
        for n_cold in self.EDGES:
            n_hot = max(0, 64 - n_cold)
            ids = np.concatenate([
                rng.integers(0, HOT, size=n_hot),
                HOT + rng.choice(N - HOT, size=n_cold, replace=False),
            ]).astype(np.int64)
            rng.shuffle(ids)
            np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])

    def test_whole_batch_cold_equals_bucket(self, rng):
        # B == n_cold == bucket: zero pad lanes, sentinel never built
        feats = _feats(rng)
        f = Feature(device_cache_size=HOT,
                    cache_unit="rows").from_cpu_tensor(feats)
        ids = (HOT + rng.choice(N - HOT, size=64,
                                replace=False)).astype(np.int64)
        np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])

    def test_overlay_hit_and_fresh_counts_on_edges(self, rng):
        feats = _feats(rng)
        f = Feature(device_cache_size=HOT,
                    cache_unit="rows").from_cpu_tensor(feats)
        f.enable_cold_cache(rows=64, admit_threshold=1)
        warm = (HOT + np.arange(32)).astype(np.int64)
        f[warm]                                # admitted on first touch
        for n_hit, n_fresh in ((16, 16), (32, 17), (31, 32), (16, 0)):
            ids = np.concatenate([
                warm[:n_hit],
                HOT + 200 + rng.choice(100, size=n_fresh, replace=False),
            ]).astype(np.int64)
            rng.shuffle(ids)
            np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])


# --------------------------------------------------------- retrace guard
@pytest.fixture
def warmed_paged(rng):
    """A paged feature pre-warmed over a fixed batch list (two epochs:
    every page the stream touches is resident, every executable built)
    — fixture setup runs OUTSIDE the retrace counting window."""
    feats = _feats(rng, n=1024)
    f = Feature(device_cache_size=256,
                cache_unit="rows").from_cpu_tensor(feats)
    f.enable_paging(page_rows=R, pool_pages=(1024 - 256) // R)
    batches = [rng.integers(0, 1024, size=64).astype(np.int64)
               for _ in range(6)]
    for _ in range(2):
        for ids in batches:
            f[ids]
    return f, feats, batches


class TestRetraceBudget:
    def test_steady_state_builds_zero_programs(self, warmed_paged):
        f, feats, batches = warmed_paged
        keys_before = set(f._merge_cache)
        with count_jit_builds() as c:
            for ids in batches:
                np.testing.assert_array_equal(np.asarray(f[ids]),
                                              feats[ids])
        assert c.builds == 0, c.describe()
        assert set(f._merge_cache) == keys_before
        # ONE ragged gather program serves every residency mix at B=64
        assert [k for k in f._merge_cache if k[0] == "paged"] \
            == [("paged", 64)]

    @pytest.mark.retrace_budget(2)
    def test_budget_marker_enforces_steady_state(self, warmed_paged):
        f, _feats_, batches = warmed_paged
        for ids in batches:
            f[ids]

    def test_fewer_executables_than_staged_grid(self, rng):
        """The tentpole's executable-count claim: the staged path keys
        programs on (B, pow2 cold bucket) — a fixed-B stream with
        drifting cold fractions builds one per bucket.  The paged path
        builds ONE gather program for all of them."""
        feats = _feats(rng, n=1024)
        f = Feature(device_cache_size=256,
                    cache_unit="rows").from_cpu_tensor(feats)
        f.enable_paging(page_rows=R, pool_pages=(1024 - 256) // R)
        ref = Feature(device_cache_size=256,
                      cache_unit="rows").from_cpu_tensor(feats)
        for n_cold in (3, 9, 17, 33, 48):      # buckets 16, 32, 64
            ids = np.concatenate([
                rng.integers(0, 256, size=64 - n_cold),
                rng.integers(256, 1024, size=n_cold),
            ]).astype(np.int64)
            np.testing.assert_array_equal(np.asarray(f[ids]),
                                          np.asarray(ref[ids]))
        paged_gathers = [k for k in f._merge_cache if k[0] == "paged"]
        staged_merges = [k for k in ref._merge_cache
                         if isinstance(k[0], int)]
        assert len(paged_gathers) == 1
        assert len(staged_merges) >= 3


# --------------------------------------------------------- off identity
class TestPagedOffIdentity:
    def test_off_is_byte_identical_to_pr9(self, rng):
        """feature_paged=off (default): no paged store, no
        feature_page_* metric keys, no paged executable keys — the
        staged path untouched."""
        telemetry.reset()
        feats = _feats(rng)
        f = Feature(device_cache_size=HOT,
                    cache_unit="rows").from_cpu_tensor(feats)
        f.enable_cold_cache(rows=64, admit_threshold=1)
        assert f.paged is None
        for _ in range(5):
            ids = rng.integers(0, N, size=64).astype(np.int64)
            np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
        snap = telemetry.snapshot()
        keys = list(snap.get("counters", {})) + list(snap.get("gauges", {}))
        assert not any(k.startswith("feature_page_") for k in keys), keys
        assert all(k[0] not in ("paged", "pgfault")
                   for k in f._merge_cache)

    def test_config_on_auto_enables(self, rng):
        cfg = config_mod.get_config()
        saved = {k: getattr(cfg, k) for k in
                 ("feature_paged", "feature_page_rows",
                  "feature_page_pool")}
        config_mod.update(feature_paged="on", feature_page_rows=R,
                          feature_page_pool=16)
        try:
            feats = _feats(rng)
            f = Feature(device_cache_size=HOT,
                        cache_unit="rows").from_cpu_tensor(feats)
            assert f.paged is not None
            assert f.paged.table.page_rows == R
            assert f.paged.table.pool_pages == 16
            ids = rng.integers(0, N, size=64).astype(np.int64)
            np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
        finally:
            config_mod.update(**saved)


# ------------------------------------------------------------- recovery
def _graph_factory():
    from quiver_tpu.stream import StreamingGraph
    from quiver_tpu.utils.topology import CSRTopo

    src = np.arange(64, dtype=np.int64)
    dst = (src + 1) % 64
    return StreamingGraph(CSRTopo(edge_index=np.stack([src, dst])),
                          delta_capacity=512)


@pytest.fixture
def _clean_recovery():
    cfg = config_mod.get_config()
    saved = {k: getattr(cfg, k) for k in
             ("recovery_dir", "recovery_cache_dir",
              "recovery_retrace_budget")}
    yield
    from quiver_tpu.recovery.manager import set_active
    from quiver_tpu.recovery.registry import get_program_registry

    get_program_registry().unseal()
    set_active(None)
    config_mod.update(**saved)


class TestPagedRecovery:
    def _warm(self, rng, f):
        # confined to a 16-page window so the working set fits the pool
        ids = (HOT + rng.choice(16 * R, size=64,
                                replace=False)).astype(np.int64)
        f[ids]
        return ids

    def test_export_restore_round_trip(self, rng):
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=16)
        ids = self._warm(rng, f)
        assert f.paged.table.cache.resident > 0
        state = f.export_coldcache_state()
        assert state is not None and state["kind"] == "paged"
        assert state["page_rows"] == R

        f2 = _paged_feature(feats, pool_pages=16)
        warmed = f2.restore_coldcache_state(state)
        assert warmed == f.paged.table.cache.resident * R
        np.testing.assert_array_equal(f2.paged.table.cache.node_of,
                                      f.paged.table.cache.node_of)
        # restored pages serve real values without re-faulting
        faults = _counter("feature_page_faults_total")
        np.testing.assert_array_equal(np.asarray(f2[ids]), feats[ids])
        assert _counter("feature_page_faults_total") == faults

    def test_paged_snapshot_with_paging_off_degrades(self, rng):
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=16)
        ids = self._warm(rng, f)
        state = f.export_coldcache_state()

        off = Feature(device_cache_size=HOT,
                      cache_unit="rows").from_cpu_tensor(feats)
        off.enable_cold_cache(rows=64, admit_threshold=1)
        assert off.restore_coldcache_state(state) == 0  # cold, not a crash
        np.testing.assert_array_equal(np.asarray(off[ids]), feats[ids])

    def test_staged_snapshot_into_paged_build_starts_cold(self, rng):
        feats = _feats(rng)
        staged = Feature(device_cache_size=HOT,
                         cache_unit="rows").from_cpu_tensor(feats)
        staged.enable_cold_cache(rows=64, admit_threshold=1)
        ids = self._warm(rng, staged)
        state = staged.export_coldcache_state()
        assert state.get("kind") != "paged"

        f2 = _paged_feature(feats, pool_pages=16)
        assert f2.restore_coldcache_state(state) == 0
        np.testing.assert_array_equal(np.asarray(f2[ids]), feats[ids])

    def test_page_geometry_mismatch_refuses(self, rng):
        feats = _feats(rng)
        f = _paged_feature(feats, pool_pages=16)
        self._warm(rng, f)
        state = f.export_coldcache_state()
        f2 = _paged_feature(feats, page_rows=2 * R, pool_pages=16)
        with pytest.raises(ValueError, match="page geometry"):
            f2.restore_coldcache_state(state)

    def test_manager_round_trip_restores_residency(self, tmp_path, rng,
                                                   _clean_recovery):
        from quiver_tpu.recovery.manager import RecoveryManager

        root = str(tmp_path / "r")
        feats = _feats(rng)
        mgr = RecoveryManager(root, graph_factory=_graph_factory)
        mgr.boot()
        f = _paged_feature(feats, pool_pages=16)
        mgr.attach_feature("feat", f)
        ids = self._warm(rng, f)
        resident = f.paged.table.cache.resident
        assert resident > 0
        mgr.checkpoint()
        mgr.close()

        mgr2 = RecoveryManager(root, graph_factory=_graph_factory)
        mgr2.boot()
        f2 = _paged_feature(feats, pool_pages=16)
        warmed = mgr2.attach_feature("feat", f2)
        assert warmed == resident * R
        np.testing.assert_array_equal(f2.paged.table.cache.node_of,
                                      f.paged.table.cache.node_of)
        np.testing.assert_array_equal(np.asarray(f2[ids]), feats[ids])
        mgr2.close()

    def test_manager_mismatched_geometry_starts_cold(self, tmp_path, rng,
                                                     _clean_recovery):
        """Through the manager the ValueError is caught: a re-tuned
        page size boots cold instead of refusing."""
        from quiver_tpu.recovery.manager import RecoveryManager

        root = str(tmp_path / "r")
        feats = _feats(rng)
        mgr = RecoveryManager(root, graph_factory=_graph_factory)
        mgr.boot()
        f = _paged_feature(feats, pool_pages=16)
        mgr.attach_feature("feat", f)
        ids = self._warm(rng, f)
        mgr.checkpoint()
        mgr.close()

        mgr2 = RecoveryManager(root, graph_factory=_graph_factory)
        mgr2.boot()
        f2 = _paged_feature(feats, page_rows=2 * R, pool_pages=16)
        assert mgr2.attach_feature("feat", f2) == 0
        np.testing.assert_array_equal(np.asarray(f2[ids]), feats[ids])
        mgr2.close()


# --------------------------------------------------------- kill -9 crash
def _spawn(code, *argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(REPO), PYTHONUNBUFFERED="1")
    return subprocess.Popen(
        [sys.executable, "-c", code, *map(str, argv)],
        cwd=str(REPO), env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


# The paged-crash child: boot the recovery tier, fault a deterministic
# set of pages, checkpoint, print the resident page set, then spin until
# SIGKILLed — no atexit, no flush beyond the prints.
_PAGED_CHILD = r"""
import json
import sys
import time

import numpy as np

from quiver_tpu.feature import Feature
from quiver_tpu.recovery.manager import RecoveryManager
from quiver_tpu.stream import StreamingGraph
from quiver_tpu.utils.topology import CSRTopo

root, seed = sys.argv[1], int(sys.argv[2])
rng = np.random.default_rng(seed)
feats = rng.standard_normal((512, 16)).astype(np.float32)

def factory():
    src = np.arange(64, dtype=np.int64)
    dst = (src + 1) % 64
    return StreamingGraph(CSRTopo(edge_index=np.stack([src, dst])),
                          delta_capacity=512)

mgr = RecoveryManager(root, graph_factory=factory)
mgr.boot()
f = Feature(device_cache_size=128,
            cache_unit="rows").from_cpu_tensor(feats)
f.enable_paging(page_rows=8, pool_pages=16)
mgr.attach_feature("feat", f)
ids = (128 + rng.choice(128, size=64, replace=False)).astype(np.int64)
f[ids]
mgr.checkpoint()
cache = f.paged.table.cache
resident = sorted(int(p) for p in cache.node_of[cache.node_of >= 0])
print("RESIDENT " + json.dumps(resident), flush=True)
print("READY", flush=True)
while True:
    time.sleep(0.1)
"""


@pytest.mark.crash
def test_kill9_then_recover_restores_page_residency(tmp_path,
                                                    _clean_recovery):
    """``make crash`` variant: a real child checkpoints page residency
    and is SIGKILLed mid-serve; a fresh process must re-warm exactly the
    pages the child reported resident and serve them correctly."""
    from quiver_tpu.recovery.manager import RecoveryManager

    root, seed = str(tmp_path / "r"), 77
    proc = _spawn(_PAGED_CHILD, root, seed)
    resident = None
    try:
        deadline = time.time() + 120
        for line in proc.stdout:
            if line.startswith("RESIDENT "):
                resident = json.loads(line.split(" ", 1)[1])
            if line.strip() == "READY":
                break
            assert time.time() < deadline, "child never reached READY"
        assert resident, (
            "child died before checkpointing: "
            + (proc.stderr.read() or "")[-2000:])
        proc.kill()                            # SIGKILL, no mercy
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # the parent replays the child's exact build (same seed)
    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((512, 16)).astype(np.float32)
    mgr = RecoveryManager(root, graph_factory=_graph_factory)
    mgr.boot()
    f = _paged_feature(feats, pool_pages=16)
    warmed = mgr.attach_feature("feat", f)
    assert warmed == len(resident) * R
    cache = f.paged.table.cache
    got = sorted(int(p) for p in cache.node_of[cache.node_of >= 0])
    assert got == resident
    ids = (HOT + rng.choice(N - HOT, size=64,
                            replace=False)).astype(np.int64)
    np.testing.assert_array_equal(np.asarray(f[ids]), feats[ids])
    mgr.close()


# ------------------------------------------------------------- tooling
def test_paged_module_is_in_the_lint_hot_set():
    """quiverlint must treat ops/paged.py as hot-path code (QT001's
    implicit-device_get rule and friends apply)."""
    import fnmatch

    from quiver_tpu.analysis.core import _DEFAULT_HOT

    assert any(fnmatch.fnmatch("quiver_tpu/ops/paged.py", pat)
               for pat in _DEFAULT_HOT)
