"""Scaled distributed-training evidence (VERDICT next #7): 100K nodes,
reference fanout [15,10,5], full dist stack on the virtual 8-mesh, loss
decreases over 20+ steps, zero silent drops at exact caps."""

import numpy as np
import pytest

from quiver_tpu.dist.e2e import run_dist_training


@pytest.mark.slow
def test_dist_training_100k_loss_decreases():
    out = run_dist_training(
        n_devices=8, n_nodes=100_000, avg_deg=12, feat_dim=16,
        batch_per_dev=32, sizes=[15, 10, 5], steps=24, classes=8,
        lr=3e-3, seed=7,
    )
    losses = out["losses"]
    assert len(losses) == 24
    assert all(np.isfinite(l) for l in losses), losses
    early = float(np.mean(losses[:5]))
    late = float(np.mean(losses[-5:]))
    assert late < early, (early, late, losses)
    # exact caps: nothing silently dropped anywhere in the stack
    assert out["sampler_overflow"].sum() == 0, out["sampler_overflow"]
    assert out["feature_overflow"] == 0


def test_dist_training_quick_smoke():
    """Small config (the dryrun shape) stays healthy — quick variant."""
    out = run_dist_training(n_devices=8, n_nodes=2_000, avg_deg=8,
                            feat_dim=8, batch_per_dev=8, sizes=[5, 4],
                            steps=3, seed=1)
    assert all(np.isfinite(l) for l in out["losses"])
    assert out["sampler_overflow"].sum() == 0
    assert out["feature_overflow"] == 0


def test_dist_training_with_hier_feature():
    """ICI x DCN HierFeature inside a real training loop: loss decreases
    and hot-heavy frontiers keep most feature traffic off the DCN axis."""
    out = run_dist_training(n_devices=8, n_nodes=3_000, avg_deg=10,
                            feat_dim=8, batch_per_dev=8, sizes=[5, 4],
                            steps=6, seed=3, hier=(2, 0.4))
    losses = out["losses"]
    assert all(np.isfinite(l) for l in losses)
    assert np.mean(losses[-2:]) < np.mean(losses[:2])
    assert out["feature_overflow"] == 0
    total_queries = 8 * 8 * (1 + 5 + 5 * 4) * 6  # frontier size x steps
    # degree-ordered hot tier: most queried rows resolve on ICI
    assert out["dcn_crossings"] < 0.45 * total_queries


@pytest.mark.slow
def test_dist_training_1m_nodes_zero_overflow():
    """~1M nodes / 12M edges (VERDICT r4 next #8): bucket capacities and
    int32 shard-offset paths near papers100M reality; exact caps drop
    nothing and the loss still moves."""
    out = run_dist_training(
        n_devices=8, n_nodes=1_000_000, avg_deg=12, feat_dim=16,
        batch_per_dev=32, sizes=[15, 10, 5], steps=6, classes=8,
        lr=3e-3, seed=11,
    )
    losses = out["losses"]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
    assert out["sampler_overflow"].sum() == 0, out["sampler_overflow"]
    assert out["feature_overflow"] == 0
