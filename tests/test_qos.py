"""Multi-tenant QoS suite (``make qos``).

Covers the overload-control tier end to end: token-bucket admission
(typed ``QuotaExceeded`` answers with a retry-after hint), deficit-
weighted round-robin fair lanes, the reversible SLO-driven degradation
ladder, the shared backoff policy and the breaker's single-probe
half-open gate, tenant plumbing through flight records and metric
labels, ambient deadlines into the degraded dist paths, continuous
batching's zero-retrace contract, and the closed-loop burst harness
(``benchmarks/qos_load.py``) acceptance criteria.

Everything is deterministic: scripted clocks for buckets/breakers,
seeded RNGs for jitter, direct ``observe()`` ticks for the ladder, and
a seeded arrival schedule in the harness.
"""

import os
import queue
import threading
import time
import random

import numpy as np
import jax
import pytest

import quiver_tpu.config as config_mod
from quiver_tpu import (
    Feature, GraphSageSampler, InferenceServer, RequestBatcher, telemetry,
)
from quiver_tpu.serving import ServingRequest, _STOP
from quiver_tpu.telemetry import flightrec, metric_key
from quiver_tpu.resilience import (
    Backoff, BoundedLane, ChaosPlan, CircuitBreaker, DeadlineExceeded,
    DegradationLadder, LadderStep, LoadShed, PeerTimeout, QoSController,
    QuotaExceeded, TenantClass, TokenBucket, WeightedFairLane, deadline_scope,
    check_ambient, install_qos, qos_from_config, qos_status, retry_call,
    serving_ladder, shed,
)
from quiver_tpu.resilience import chaos
from quiver_tpu.resilience import qos as qos_mod
from quiver_tpu.resilience.deadline import ambient_deadline
from quiver_tpu.resilience.qos import parse_tenant_spec

pytestmark = pytest.mark.qos

NHOSTS = 8

_CFG_KEYS = (
    "qos_enabled", "qos_tenants", "qos_default_tenant", "qos_ingest_tenant",
    "qos_admit_window_ms", "qos_quantum", "qos_degrade_fanout_frac",
    "qos_breach_ticks", "qos_recover_ticks",
    "serving_deadline_ms", "serving_queue_depth",
    "serving_queue_high_watermark", "serving_queue_low_watermark",
)


@pytest.fixture(autouse=True)
def _clean_qos():
    """Fresh registry/recorder/controller per test; config restored, and
    no chaos plan may leak across tests."""
    cfg = config_mod.get_config()
    saved = {k: getattr(cfg, k) for k in _CFG_KEYS}
    telemetry.set_enabled(True)
    telemetry.reset()
    qos_mod.reset()
    config_mod.update(serving_deadline_ms=0)
    yield
    chaos.uninstall()
    qos_mod.reset()
    config_mod.update(**saved)
    telemetry.set_enabled(True)
    telemetry.reset()


def counter_value(name, **labels):
    return telemetry.snapshot()["counters"].get(metric_key(name, labels), 0)


def gauge_value(name, **labels):
    return telemetry.snapshot()["gauges"].get(metric_key(name, labels))


def _req(ids=(1,), seq=0, priority=0, tenant=None, tenant_class=None,
         deadline=None):
    return ServingRequest(ids=np.asarray(ids, dtype=np.int64), client=0,
                          seq=seq, priority=priority, deadline=deadline,
                          tenant=tenant, tenant_class=tenant_class)


class _Clock:
    """Scripted monotonic clock."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _classes():
    return {
        "gold": TenantClass("gold", rate=100.0, burst=50.0, weight=4.0,
                            priority=3),
        "silver": TenantClass("silver", rate=50.0, burst=20.0, weight=2.0,
                              priority=2),
        "bronze": TenantClass("bronze", rate=20.0, burst=10.0, weight=1.0,
                              priority=1),
        "ingest": TenantClass("ingest", rate=10.0, burst=5.0, weight=1.0,
                              priority=0),
    }


def _controller(clock=time.monotonic):
    return QoSController(classes=_classes(), default="bronze",
                         ingest="ingest", clock=clock)


# ===================================================== token bucket
def test_token_bucket_burst_retry_after_and_refill():
    clk = _Clock()
    tb = TokenBucket(rate=10.0, burst=5.0, clock=clk)
    assert tb.tokens == 5.0
    for _ in range(5):
        assert tb.try_take() == 0.0
    # empty: retry-after is the exact refill time for one token
    assert tb.try_take() == pytest.approx(0.1)
    # partial refill shortens the hint
    clk.t = 0.05
    assert tb.try_take() == pytest.approx(0.05)
    clk.t = 0.1
    assert tb.try_take() == 0.0
    # refill is capped at burst: a long idle period banks at most 5
    clk.t = 1000.0
    for _ in range(5):
        assert tb.try_take() == 0.0
    assert tb.try_take() > 0.0
    # multi-token takes hint proportionally
    tb2 = TokenBucket(rate=4.0, burst=2.0, clock=_Clock())
    assert tb2.try_take(2.0) == 0.0
    assert tb2.try_take(2.0) == pytest.approx(0.5)


# ===================================================== tenant spec
def test_parse_tenant_spec_roundtrip():
    classes = parse_tenant_spec(
        "gold:rate=200,burst=50,weight=8,priority=3; bronze")
    assert classes["gold"] == TenantClass("gold", 200.0, 50.0, 8.0, 3)
    # bare name: all defaults
    assert classes["bronze"].rate == 100.0
    assert classes["bronze"].priority == 0


@pytest.mark.parametrize("spec", [
    "",                       # no classes at all
    "gold:speed=9",           # unknown field
    ":rate=1",                # missing name
    "gold:rate=0",            # quota must be positive
    "gold:burst=-1",
    "gold:rate=abc",          # non-numeric
])
def test_parse_tenant_spec_rejects_malformed(spec):
    with pytest.raises(ValueError):
        parse_tenant_spec(spec)


# ===================================================== controller
def test_resolve_allowlists_and_floor_excludes_ingest():
    ctl = _controller()
    assert ctl.resolve("gold").name == "gold"
    assert ctl.resolve(None).name == "bronze"
    # unknown tenants map to the default class — the metric-label
    # allowlist is the declared class set, never client input
    assert ctl.resolve("mystery").name == "bronze"
    # ingest has the lowest priority but is not a floor candidate
    assert ctl.floor == "bronze"


def test_admit_stamps_class_and_lifts_priority():
    ctl = _controller()
    req = _req(tenant="mystery", priority=0)
    assert ctl.admit(req, None)
    assert req.tenant_class == "bronze"
    assert req.priority == 1  # lifted to the class priority
    high = _req(tenant="gold", priority=9)
    assert ctl.admit(high, None)
    assert high.priority == 9  # never lowered
    assert counter_value("serving_qos_admitted_total", tenant="bronze") == 1
    assert counter_value("serving_qos_admitted_total", tenant="gold") == 1


def test_quota_rejection_answers_quota_exceeded():
    clk = _Clock()
    classes = {
        "gold": TenantClass("gold", rate=10.0, burst=2.0, weight=1.0,
                            priority=1),
        "bronze": TenantClass("bronze", rate=1.0, burst=1.0),
    }
    ctl = QoSController(classes=classes, default="bronze", ingest="none",
                        clock=clk)
    rq = queue.Queue()
    r1, r2, r3 = (_req(tenant="gold", seq=i) for i in (1, 2, 3))
    assert ctl.admit(r1, rq) and ctl.admit(r2, rq)
    assert not ctl.admit(r3, rq)
    req, exc = rq.get_nowait()
    assert req is r3 and isinstance(exc, QuotaExceeded)
    assert exc.tenant == "gold"
    assert exc.retry_after_s == pytest.approx(0.1)
    assert counter_value("serving_qos_rejected_total", tenant="gold") == 1
    assert counter_value("serving_qos_admitted_total", tenant="gold") == 2
    rec = flightrec.get_recorder().get(r3.trace.trace_id)
    assert rec is not None and rec["status"] == "rejected"
    # the hint is honest: waiting it out readmits
    clk.t = 0.1
    assert ctl.admit(_req(tenant="gold", seq=4), rq)


# ===================================================== weighted-fair lane
def test_wfl_drr_drains_by_weight():
    rq = queue.Queue()
    lane = WeightedFairLane("device", {"gold": 4.0, "bronze": 1.0},
                            default_class="bronze", quantum=1,
                            maxsize=64, high=1.0, low=0.5, result_queue=rq)
    for i in range(8):
        lane.put(_req(seq=i, tenant_class="bronze"))
    for i in range(8, 16):
        lane.put(_req(seq=i, tenant_class="gold"))
    assert lane.class_depths() == {"bronze": 8, "gold": 8}
    order = [lane.get_nowait().tenant_class for _ in range(16)]
    # DRR with quantum=1: gold's 4x weight gives it 4 dequeues per
    # bronze dequeue while both classes are backlogged
    assert order == (["bronze"] + ["gold"] * 4) * 2 + ["bronze"] * 6
    assert rq.empty()  # fairness never sheds


def test_wfl_unstamped_requests_ride_default_class():
    lane = WeightedFairLane("device", {"gold": 4.0, "bronze": 1.0},
                            default_class="bronze", maxsize=8,
                            result_queue=queue.Queue())
    lane.put(_req(seq=0))  # no tenant_class stamp
    assert lane.class_depths() == {"bronze": 1}


def test_wfl_control_fence_preserves_arrival_order():
    lane = WeightedFairLane("device", {"gold": 1.0}, default_class="gold",
                            maxsize=2, high=1.0, low=0.5,
                            result_queue=queue.Queue())
    a = _req(seq=0)
    b = _req(seq=1)
    lane.put(a)
    lane.put(_STOP)   # arrives between a and b
    lane.put(b)       # at capacity 2 the control item still went through
    assert lane.get_nowait() is a
    assert lane.get_nowait() is _STOP  # only after every earlier request
    assert lane.get_nowait() is b


def test_wfl_watermark_sheds_lowest_class_under_interleave():
    """Satellite: watermark hysteresis with interleaved multi-tenant
    enqueue — sheds land on the lowest class PRESENT no matter whose
    burst crossed the watermark, and admissions resume below ``low``."""
    rq = queue.Queue()
    lane = WeightedFairLane("device", {"gold": 4.0, "bronze": 1.0},
                            default_class="bronze", maxsize=10,
                            high=0.5, low=0.2, result_queue=rq)
    # interleave the two tenants up to the high watermark (5)
    reqs = []
    for i in range(5):
        cls, pri = (("bronze", 1) if i % 2 == 0 else ("gold", 3))
        r = _req(seq=i, priority=pri, tenant_class=cls)
        reqs.append(r)
        lane.put(r)
    assert not lane.shedding
    # a gold arrival at the watermark displaces the OLDEST bronze
    g5 = _req(seq=5, priority=3, tenant_class="gold")
    lane.put(g5)
    assert lane.shedding
    victim, exc = rq.get_nowait()
    assert victim is reqs[0] and victim.tenant_class == "bronze"
    assert isinstance(exc, LoadShed) and exc.reason == "watermark"
    # a bronze arrival while shedding finds no lower class: it sheds
    b6 = _req(seq=6, priority=1, tenant_class="bronze")
    lane.put(b6)
    shed_req, _ = rq.get_nowait()
    assert shed_req is b6
    # tenant-labelled accounting (bounded by the class allowlist)
    assert counter_value("serving_shed_total", reason="watermark",
                         lane="device", tenant="bronze") == 2
    # hysteresis: draining below low (2) releases shedding
    while lane.qsize() >= 2:
        lane.get_nowait()
    b7 = _req(seq=7, priority=1, tenant_class="bronze")
    lane.put(b7)
    assert not lane.shedding
    assert rq.empty()


# ===================================================== degradation ladder
def test_ladder_hysteresis_and_reversal_order():
    calls = []
    steps = [
        LadderStep("s1", lambda: calls.append("+1"),
                   lambda: calls.append("-1")),
        LadderStep("s2", lambda: calls.append("+2"),
                   lambda: calls.append("-2")),
    ]
    lad = DegradationLadder(steps, breach_ticks=2, recover_ticks=2)
    # alternating windows never flap the ladder
    for _ in range(3):
        lad.observe(True)
        lad.observe(False)
    assert lad.level == 0 and calls == []
    # two consecutive breaches per step-down
    lad.observe(True)
    assert lad.level == 0
    lad.observe(True)
    assert lad.level == 1 and calls == ["+1"]
    lad.observe(True)
    lad.observe(True)
    assert lad.level == 2 and calls == ["+1", "+2"]
    # saturated at the bottom: more breaches apply nothing new
    lad.observe(True)
    lad.observe(True)
    assert lad.level == 2 and calls == ["+1", "+2"]
    assert gauge_value("serving_degradation_level") == 2
    # recovery reverts newest-first, same hysteresis
    lad.observe(False)
    lad.observe(False)
    assert lad.level == 1 and calls[-1] == "-2"
    lad.observe(False)
    lad.observe(False)
    assert lad.level == 0 and calls == ["+1", "+2", "-2", "-1"]
    assert gauge_value("serving_degradation_level") == 0
    for direction, step in (("down", "s1"), ("down", "s2"),
                            ("up", "s2"), ("up", "s1")):
        assert counter_value("serving_qos_ladder_transitions_total",
                             direction=direction, step=step) == 1
    st = lad.status()
    assert st["level"] == 0 and len(st["history"]) == 4
    with pytest.raises(ValueError):
        DegradationLadder(steps, breach_ticks=0)


def test_ladder_attach_filters_objectives():
    class _WD:
        def __init__(self):
            self.listeners = []

        def add_listener(self, fn):
            self.listeners.append(fn)

    lad = DegradationLadder([LadderStep("s", lambda: None, lambda: None)],
                            breach_ticks=1, recover_ticks=1)
    wd = _WD()
    assert lad.attach(wd, objectives=("p99_latency",)) is lad
    (fire,) = wd.listeners
    # a breach on an unwatched objective counts as a healthy tick
    fire([{"objective": "error_ratio", "breaching": True}])
    assert lad.level == 0
    fire([{"objective": "p99_latency", "breaching": True},
          {"objective": "error_ratio", "breaching": False}])
    assert lad.level == 1


def test_serving_ladder_full_reversal():
    class _Sampler:
        fanout_frac = 1.0

        def set_fanout_frac(self, f):
            self.fanout_frac = f

    class _ColdCache:
        admission_paused = False

    clk = _Clock()
    ctl = _controller(clock=clk)
    sampler, cc = _Sampler(), _ColdCache()
    lad = serving_ladder(ctl, sampler=sampler, cold_cache=cc,
                         fanout_frac=0.5, breach_ticks=1, recover_ticks=1)
    assert ctl.ladder is lad
    # walk the full ladder down: fanout -> coldcache -> cpu_floor -> shed
    for _ in range(4):
        lad.observe(True)
    assert lad.level == 4
    assert sampler.fanout_frac == 0.5
    assert cc.admission_paused
    assert ctl.route_floor_to_cpu and ctl.shed_floor
    # at the bottom, the floor class is shed at admission — answered,
    # not dropped — while higher classes still pass
    rq = queue.Queue()
    floor_req = _req(tenant="bronze", seq=0)
    assert not ctl.admit(floor_req, rq)
    req, exc = rq.get_nowait()
    assert req is floor_req
    assert isinstance(exc, LoadShed) and exc.reason == "degraded"
    assert ctl.admit(_req(tenant="gold", seq=1), rq)
    # full reversal: every step reverts, newest first
    for _ in range(4):
        lad.observe(False)
    assert lad.level == 0
    assert sampler.fanout_frac == 1.0
    assert not cc.admission_paused
    assert not ctl.route_floor_to_cpu and not ctl.shed_floor
    assert gauge_value("serving_degradation_level") == 0
    assert ctl.admit(_req(tenant="bronze", seq=2), rq)


# ===================================================== shared backoff
def test_backoff_deterministic_schedule():
    b = Backoff(1.0, cap_s=8.0)
    assert [b.delay(i) for i in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
    # seeded jitter replays identically and stays inside its bounds
    d1 = [Backoff(0.1, cap_s=1.0, jitter=0.5,
                  rng=random.Random(7)).delay(i) for i in range(6)]
    d2 = [Backoff(0.1, cap_s=1.0, jitter=0.5,
                  rng=random.Random(7)).delay(i) for i in range(6)]
    assert d1 == d2
    undithered = [min(0.1 * 2 ** i, 1.0) for i in range(6)]
    assert d1 != undithered  # the jitter actually moved the schedule
    for d, base in zip(d1, undithered):
        assert base * 0.5 <= d <= base * 1.5
    with pytest.raises(ValueError):
        Backoff(-1.0)
    with pytest.raises(ValueError):
        Backoff(1.0, jitter=1.0)
    with pytest.raises(ValueError):
        Backoff(1.0, multiplier=0.5)


def test_retry_call_schedule_and_propagation():
    sleeps, retries = [], []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise PeerTimeout()
        return "ok"

    out = retry_call(flaky, attempts=3, backoff=Backoff(1.0, cap_s=8.0),
                     retry_on=(PeerTimeout,), sleep=sleeps.append,
                     on_retry=lambda a, e: retries.append(a))
    assert out == "ok" and calls["n"] == 3
    assert sleeps == [1.0, 2.0] and retries == [0, 1]

    # a non-retryable exception propagates without a second attempt
    calls["n"] = 0

    def wrong():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry_call(wrong, attempts=5, retry_on=(PeerTimeout,),
                   sleep=sleeps.append)
    assert calls["n"] == 1

    # exhausted attempts surface the last failure; one sleep between two
    sleeps2 = []
    with pytest.raises(PeerTimeout):
        retry_call(lambda: (_ for _ in ()).throw(PeerTimeout()),
                   attempts=2, backoff=Backoff(0.5), retry_on=(PeerTimeout,),
                   sleep=sleeps2.append)
    assert sleeps2 == [0.5]
    with pytest.raises(ValueError):
        retry_call(flaky, attempts=0)


# ===================================================== breaker half-open
def test_breaker_half_open_admits_single_probe():
    clk = _Clock()
    br = CircuitBreaker("qos.halfopen", failure_threshold=1,
                        reset_timeout_s=1.0, half_open_probes=3, clock=clk)
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clk.t = 1.0
    assert br.allow()          # wins the probe slot
    assert br.state == "half_open"
    for _ in range(5):
        assert not br.allow()  # every other caller sees it as closed off
    br.record_success()
    assert br.state == "closed" and br.allow()


def test_breaker_half_open_single_probe_under_concurrency():
    """Regression: half-open used to admit EVERY concurrent caller as a
    probe, stampeding a barely-recovered lane with the burst that
    tripped it.  Exactly one of N racing callers may probe."""
    clk = _Clock()
    br = CircuitBreaker("qos.stampede", failure_threshold=1,
                        reset_timeout_s=1.0, half_open_probes=3, clock=clk)
    br.record_failure()
    clk.t = 1.0
    n = 8
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(n)

    def caller():
        barrier.wait()
        ok = br.allow()
        with lock:
            results.append(ok)

    threads = [threading.Thread(target=caller) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert sum(results) == 1


def test_breaker_reopen_backs_off_probe_schedule():
    clk = _Clock()
    br = CircuitBreaker("qos.backoff", failure_threshold=1,
                        reset_timeout_s=1.0, half_open_probes=1, clock=clk)
    br.record_failure()                     # open at t=0
    clk.t = 1.0
    assert br.allow()
    br.record_failure()                     # probe 1 fails: reopen at 1.0
    clk.t = 1.9
    assert not br.allow()                   # base timeout still 1.0
    clk.t = 2.0
    assert br.allow()
    br.record_failure()                     # probe 2 fails: timeout 2.0
    clk.t = 3.9
    assert not br.allow()
    clk.t = 4.0
    assert br.allow()
    br.record_success()                     # recovery resets the backoff
    assert br.state == "closed"
    br.record_failure()                     # trips again at t=4.0
    clk.t = 5.0
    assert br.allow()                       # back to the base timeout


# ===================================================== tenant plumbing
def test_tenant_rides_trace_into_flight_record():
    req = _req(tenant="gold", seq=0)
    assert req.trace is not None and req.trace.tenant == "gold"
    shed(req, queue.Queue(), "device", "watermark")
    rec = flightrec.get_recorder().get(req.trace.trace_id)
    assert rec is not None and rec["tenant"] == "gold"


def test_disabled_qos_keeps_seed_metric_keys():
    """QoS off: no controller, plain BoundedLanes, and shed/reject
    metric keys byte-identical to the pre-QoS ones (no tenant label)."""
    config_mod.update(qos_enabled=False, serving_queue_depth=4,
                      serving_queue_high_watermark=0.75,
                      serving_queue_low_watermark=0.25)
    qos_mod.reset()
    assert qos_from_config() is None
    rq = queue.Queue()
    rb = RequestBatcher([queue.Queue()], mode="CPU", result_queue=rq)
    assert type(rb.cpu_batched_queue) is BoundedLane
    assert rb._qos is None
    for i in range(6):
        rb._route(_req(ids=(1, 2), seq=i))
    snap = telemetry.snapshot()["counters"]
    shed_keys = [k for k in snap if k.startswith("serving_shed_total")]
    assert shed_keys and all("tenant=" not in k for k in shed_keys)
    assert counter_value("serving_shed_total", reason="watermark",
                         lane="cpu") >= 1
    assert not any(k.startswith("serving_qos_") for k in snap)


def test_enabled_qos_builds_weighted_fair_lanes():
    config_mod.update(serving_queue_depth=8)
    ctl = _controller()
    rb = RequestBatcher([queue.Queue()], mode="Device",
                        result_queue=queue.Queue(), qos=ctl)
    assert isinstance(rb.device_batched_queue, WeightedFairLane)
    req = _req(tenant="gold", seq=0)
    rb._route(req)
    assert req.tenant_class == "gold" and req.priority == 3
    assert rb.device_batched_queue.class_depths() == {"gold": 1}
    assert counter_value("serving_qos_admitted_total", tenant="gold") == 1


def test_route_floor_to_cpu_reroutes_only_floor_class():
    config_mod.update(serving_queue_depth=8)
    ctl = _controller()
    ctl.route_floor_to_cpu = True  # ladder L3 in force
    rb = RequestBatcher([queue.Queue()], mode="Auto",
                        result_queue=queue.Queue(), qos=ctl)
    rb._route(_req(tenant="bronze", seq=0))
    rb._route(_req(tenant="gold", seq=1))
    assert rb.cpu_batched_queue.class_depths() == {"bronze": 1}
    assert rb.device_batched_queue.class_depths() == {"gold": 1}


# ===================================================== ambient deadlines
def test_deadline_scope_nesting_and_noop():
    assert ambient_deadline() is None
    check_ambient("nowhere")  # no scope: one contextvar read, no raise
    with deadline_scope(None):
        assert ambient_deadline() is None
    dl = time.perf_counter() + 5.0
    with deadline_scope(dl):
        assert ambient_deadline() == dl
        check_ambient("live")
        with deadline_scope(dl - 10.0, dl - 11.0):
            with pytest.raises(DeadlineExceeded) as ei:
                check_ambient("inner")
            assert ei.value.lane == "inner"
        assert ambient_deadline() == dl  # outer scope restored
    assert ambient_deadline() is None


@pytest.fixture(scope="module")
def mesh():
    from quiver_tpu.utils.mesh import make_mesh

    assert jax.device_count() == NHOSTS
    return make_mesh(("data",))


def test_ambient_deadline_refuses_degraded_dist_lookup(mesh, rng):
    """Satellite: the serving loop's ambient deadline propagates into
    DistFeature — an expired batch is refused BEFORE the lookup does any
    work (the chaos exchange point is never even reached)."""
    from quiver_tpu.dist import DistFeature, PartitionInfo

    n, d = 128, 4
    full = rng.normal(size=(n, d)).astype(np.float32)
    g2h = rng.integers(0, NHOSTS, n).astype(np.int32)
    info = PartitionInfo(host=0, hosts=NHOSTS, global2host=g2h)
    df = DistFeature.from_global_feature(full, mesh, info)
    ids = rng.integers(0, n, (NHOSTS, 16)).astype(np.int32)
    # a live scope changes nothing
    with deadline_scope(time.perf_counter() + 60.0):
        out = np.asarray(df.lookup(ids))
    assert out.shape == (NHOSTS, 16, d)
    # an expired scope refuses the work up front
    plan = ChaosPlan(seed=1).fail("dist.feature.exchange",
                                  exc=PeerTimeout, times=8)
    with chaos.active(plan):
        with deadline_scope(time.perf_counter() - 0.01):
            with pytest.raises(DeadlineExceeded) as ei:
                df.lookup(ids)
    assert ei.value.lane == "dist_feature"
    assert plan.hits("dist.feature.exchange") == 0


# ===================================================== continuous batching
def test_continuous_batching_steady_state_zero_builds(small_graph, rng):
    """Acceptance: the admit window coalesces late arrivals into the
    in-flight batch without changing executable keying — after warm-up,
    a burst served through continuous batching builds ZERO new
    executables."""
    from quiver_tpu.analysis.retrace_guard import count_jit_builds
    from quiver_tpu.models import GraphSAGE

    config_mod.update(qos_admit_window_ms=2.0)
    ctl = _controller()
    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [3], mode="CPU")
    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    apply_fn = lambda p, x, blocks: model.apply(p, x, blocks)
    dq = queue.Queue()
    server = InferenceServer(sampler, feature, apply_fn, params, dq,
                             max_coalesce=4, qos=ctl)
    assert server._admit_window_s > 0  # continuous batching armed
    server.start()
    try:
        # warm-up: one pass per coalesced-total bucket a 4x8 burst can
        # produce (8, 16, 24->32, 32), each request served alone
        for size in (8, 16, 24, 32):
            dq.put(_req(ids=rng.integers(0, n, size), seq=size,
                        tenant="gold"))
            _, out = server.result_queue.get(timeout=60)
            assert not isinstance(out, Exception), out
        with count_jit_builds() as c:
            for i in range(12):
                dq.put(_req(ids=rng.integers(0, n, 8), seq=100 + i,
                            tenant="gold"))
            for _ in range(12):
                _, out = server.result_queue.get(timeout=60)
                assert not isinstance(out, Exception), out
                assert out.shape == (8, 2)
        assert c.builds == 0, c.describe()
    finally:
        server.stop()


# ===================================================== debug endpoint
def test_qos_status_payload():
    config_mod.update(qos_enabled=False)
    qos_mod.reset()
    assert qos_status() == {"enabled": False, "installed": False}
    ctl = install_qos(_controller())
    serving_ladder(ctl, fanout_frac=0.5, breach_ticks=1, recover_ticks=1)
    st = qos_status()
    assert st["installed"] and st["floor"] == "bronze"
    assert {c["name"] for c in st["classes"]} == {"gold", "silver",
                                                  "bronze", "ingest"}
    assert st["ladder"]["level"] == 0
    assert st["ladder"]["steps"] == ["fanout", "coldcache", "cpu_floor",
                                     "shed_floor"]
    assert "tokens" in st and not st["shed_floor"]


# ===================================================== burst harness
def _load_qos_harness():
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "benchmarks", "qos_load.py")
    spec = importlib.util.spec_from_file_location("qos_load_harness", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_qos_load_harness_acceptance():
    """The closed-loop burst harness meets the overload-control
    acceptance criteria: under a 10x zipfian burst with mid-burst chaos
    faults, no admitted tenant starves, the top class keeps its loss
    far below the floor class's, quota rejections land on the heavy
    hitter only, the ladder engages, and it fully reverses once the
    burst passes."""
    harness = _load_qos_harness()
    rep = harness.run_qos_load(smoke=True, seed=0)

    def loss(entry):
        return (entry["shed"] + entry["rejected"]) / max(entry["offered"], 1)

    burst = {t: rep["tenants"][t]["burst"] for t in harness.STEADY_RPS}
    # no starvation: every admitted tenant completes work mid-burst
    for tenant, e in burst.items():
        assert e["offered"] > 0, tenant
        assert e["ok"] > 0, (tenant, e)
        assert e["ok"] / e["offered"] >= 0.05, (tenant, e)
    # the top class holds: its loss stays small and far below the
    # floor class's (sheds and quota rejections land on bronze first)
    assert burst["gold"]["rejected"] == 0
    assert loss(burst["gold"]) <= 0.3
    assert loss(burst["gold"]) < loss(burst["bronze"])
    # the zipfian heavy hitter is the one the token bucket throttles
    assert burst["bronze"]["rejected"] > 0
    # the ladder engaged under the burst...
    assert rep["peak_level"] >= 2
    # ...and fully reversed afterwards: level 0, fanout and coldcache
    # admission restored
    assert rep["final_level"] == 0
    assert rep["fanout_frac"] == 1.0
    assert not rep["coldcache_paused"]
    assert rep["ladder"]["level"] == 0
