"""Torch interop: a pure-torch PyG-style loop trains on quiver_tpu
samples (the reference-direction 3-line swap)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from quiver_tpu import Feature, GraphSageSampler
from quiver_tpu.interop import TorchSampleLoader, to_torch_adjs


def test_to_torch_adjs_types_and_shrinking_loop(small_graph, rng):
    s = GraphSageSampler(small_graph, [5, 3])
    batch = s.sample(np.arange(16, dtype=np.int64))
    n_id, bs, adjs = to_torch_adjs(batch)
    assert n_id.dtype == torch.int64 and bs == 16
    x = torch.randn(len(n_id), 6)
    for edge_index, e_id, (n_src, n_dst) in adjs:
        assert edge_index.dtype == torch.int64
        assert int(edge_index.max()) < n_src
        # torch-side mean aggregation over the bipartite block
        agg = torch.zeros(n_dst, 6)
        cnt = torch.zeros(n_dst).clamp(min=1)
        agg.index_add_(0, edge_index[1], x[edge_index[0]])
        x = x[:n_dst] + agg
    assert x.shape[0] >= bs


def test_torch_training_loop_learns(small_graph, rng):
    n = small_graph.node_count
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    w_true = rng.normal(size=(8, 3))
    labels = np.argmax(feat @ w_true, axis=1).astype(np.int64)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [4])
    loader = TorchSampleLoader(np.arange(n), sampler, feature,
                               labels=labels, batch_size=64)

    class TorchSAGE(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.lin_self = torch.nn.Linear(8, 3)
            self.lin_nbr = torch.nn.Linear(8, 3, bias=False)

        def forward(self, x, adjs):
            edge_index, _, (n_src, n_dst) = adjs[0]
            agg = torch.zeros(n_dst, x.shape[1])
            deg = torch.zeros(n_dst)
            agg.index_add_(0, edge_index[1], x[edge_index[0]])
            deg.index_add_(0, edge_index[1],
                           torch.ones(edge_index.shape[1]))
            mean = agg / deg.clamp(min=1).unsqueeze(1)
            return self.lin_self(x[:n_dst]) + self.lin_nbr(mean)

    model = TorchSAGE()
    opt = torch.optim.Adam(model.parameters(), lr=5e-2)
    losses = []
    for epoch in range(3):
        for n_id, bs, adjs, x, y in loader:
            opt.zero_grad()
            logits = model(x, adjs)[:bs]
            loss = torch.nn.functional.cross_entropy(logits, y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


class TestDGLBlocks:
    """interop.block_specs / to_dgl_blocks — our samples as DGL MFGs
    (parity direction: reference examples/dgl/ogbn_products_sage_quiver
    pairs its Feature with a DGL block loop)."""

    def _batch(self, small_graph, **kw):
        from quiver_tpu import GraphSageSampler

        s = GraphSageSampler(small_graph, [4, 3], **kw)
        return s.sample(np.arange(16, dtype=np.int64)), small_graph

    def test_block_specs_invariants(self, small_graph):
        from quiver_tpu.interop import block_specs

        batch, topo = self._batch(small_graph, return_eid=True)
        specs = block_specs(batch)
        assert len(specs) == 2
        prev_n_src = None
        for src, dst, eid, n_src, n_dst in specs:
            assert len(src) == len(dst) == len(eid)
            assert n_dst <= n_src  # DGL block invariant
            assert (src < n_src).all() and (src >= 0).all()
            assert (dst < n_dst).all() and (dst >= 0).all()
            # target frontier is a PREFIX of source frontier
            if prev_n_src is not None:
                assert n_src == prev_n_src
            prev_n_src = n_dst
        # outermost first: last spec's dst frontier is the seed batch
        assert specs[-1][4] == 16

    def test_block_specs_edges_match_graph(self, small_graph):
        """Every (src, dst) pair maps to a real edge of the graph."""
        from quiver_tpu.interop import block_specs

        batch, topo = self._batch(small_graph)
        n_id = np.asarray(batch.n_id)
        for src, dst, eid, n_src, n_dst in block_specs(batch):
            for s_, d_ in zip(src[:200], dst[:200]):
                u, v = int(n_id[s_]), int(n_id[d_])
                row = topo.indices[topo.indptr[v]: topo.indptr[v + 1]]
                assert u in row, (u, v)

    def test_to_dgl_blocks_or_skip(self, small_graph):
        pytest.importorskip("dgl")
        from quiver_tpu.interop import to_dgl_blocks

        batch, _ = self._batch(small_graph)
        blocks = to_dgl_blocks(batch)
        assert blocks[0].num_dst_nodes() <= blocks[0].num_src_nodes()

    def test_fallback_sage_learns(self, small_graph):
        """The dgl-free path of examples/dgl_products_sage.py: a torch
        SAGEConv over block_specs trains (loss decreases)."""
        import subprocess
        import sys

        p = subprocess.run(
            [sys.executable, "examples/dgl_products_sage.py", "--cpu",
             "--nodes", "3000", "--steps", "12", "--batch-size", "128"],
            capture_output=True, text=True, timeout=420,
            cwd="/root/repo")
        assert p.returncode == 0, p.stderr[-2000:]
        assert "loss" in p.stdout
