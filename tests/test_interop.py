"""Torch interop: a pure-torch PyG-style loop trains on quiver_tpu
samples (the reference-direction 3-line swap)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from quiver_tpu import Feature, GraphSageSampler
from quiver_tpu.interop import TorchSampleLoader, to_torch_adjs


def test_to_torch_adjs_types_and_shrinking_loop(small_graph, rng):
    s = GraphSageSampler(small_graph, [5, 3])
    batch = s.sample(np.arange(16, dtype=np.int64))
    n_id, bs, adjs = to_torch_adjs(batch)
    assert n_id.dtype == torch.int64 and bs == 16
    x = torch.randn(len(n_id), 6)
    for edge_index, e_id, (n_src, n_dst) in adjs:
        assert edge_index.dtype == torch.int64
        assert int(edge_index.max()) < n_src
        # torch-side mean aggregation over the bipartite block
        agg = torch.zeros(n_dst, 6)
        cnt = torch.zeros(n_dst).clamp(min=1)
        agg.index_add_(0, edge_index[1], x[edge_index[0]])
        x = x[:n_dst] + agg
    assert x.shape[0] >= bs


def test_torch_training_loop_learns(small_graph, rng):
    n = small_graph.node_count
    feat = rng.normal(size=(n, 8)).astype(np.float32)
    w_true = rng.normal(size=(8, 3))
    labels = np.argmax(feat @ w_true, axis=1).astype(np.int64)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [4])
    loader = TorchSampleLoader(np.arange(n), sampler, feature,
                               labels=labels, batch_size=64)

    class TorchSAGE(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.lin_self = torch.nn.Linear(8, 3)
            self.lin_nbr = torch.nn.Linear(8, 3, bias=False)

        def forward(self, x, adjs):
            edge_index, _, (n_src, n_dst) = adjs[0]
            agg = torch.zeros(n_dst, x.shape[1])
            deg = torch.zeros(n_dst)
            agg.index_add_(0, edge_index[1], x[edge_index[0]])
            deg.index_add_(0, edge_index[1],
                           torch.ones(edge_index.shape[1]))
            mean = agg / deg.clamp(min=1).unsqueeze(1)
            return self.lin_self(x[:n_dst]) + self.lin_nbr(mean)

    model = TorchSAGE()
    opt = torch.optim.Adam(model.parameters(), lr=5e-2)
    losses = []
    for epoch in range(3):
        for n_id, bs, adjs, x, y in loader:
            opt.zero_grad()
            logits = model(x, adjs)[:bs]
            loss = torch.nn.functional.cross_entropy(logits, y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
