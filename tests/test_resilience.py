"""Resilience suite: deadlines, load shedding, circuit breaking, lane
failover, and the deterministic chaos harness (``make chaos``).

Every fault here is *scripted* — a seeded :class:`ChaosPlan` or an
injected fake clock — so the suite is deterministic: the same plan over
the same request sequence leaves identical shed / retry / degraded
counters and an identical replay log (asserted explicitly below).
"""

import queue
import threading
import time

import numpy as np
import jax
import pytest

import quiver_tpu.config as config_mod
from quiver_tpu import (
    Feature, GraphSageSampler, InferenceServer, RequestBatcher, telemetry,
)
from quiver_tpu.serving import ServingRequest, _STOP
from quiver_tpu.telemetry import flightrec, metric_key
from quiver_tpu.resilience import (
    BoundedLane, ChaosFault, ChaosPlan, CircuitBreaker, DeadlineExceeded,
    LoadShed, PeerTimeout, breakers_status, join_and_reap,
)
from quiver_tpu.resilience import chaos

pytestmark = pytest.mark.chaos

NHOSTS = 8

_CFG_KEYS = (
    "serving_deadline_ms", "serving_queue_depth",
    "serving_queue_high_watermark", "serving_queue_low_watermark",
    "serving_breaker_failures", "serving_breaker_reset_s",
    "serving_breaker_probes", "flightrec_slow_ms",
)


@pytest.fixture(autouse=True)
def _clean_resilience():
    """Fresh registry/recorder/breakers per test; config restored, and
    no chaos plan may leak across tests."""
    cfg = config_mod.get_config()
    saved = {k: getattr(cfg, k) for k in _CFG_KEYS}
    telemetry.set_enabled(True)
    telemetry.reset()
    yield
    chaos.uninstall()
    config_mod.update(**saved)
    telemetry.set_enabled(True)
    telemetry.reset()


def counter_value(name, **labels):
    return telemetry.snapshot()["counters"].get(metric_key(name, labels), 0)


def _req(ids=(1, 2), seq=0, priority=0, deadline=None):
    return ServingRequest(ids=np.asarray(ids, dtype=np.int64), client=0,
                          seq=seq, priority=priority, deadline=deadline)


# ===================================================== BoundedLane
def test_lane_overflow_sheds_lowest_priority_first():
    rq = queue.Queue()
    lane = BoundedLane("device", maxsize=4, high=1.0, low=0.5,
                       result_queue=rq)
    for i in range(4):
        lane.put(_req(seq=i, priority=1))
    # arrival at capacity with no lower-priority victim: arrival sheds
    lane.put(_req(seq=4, priority=0))
    req, exc = rq.get_nowait()
    assert req.seq == 4 and isinstance(exc, LoadShed)
    assert exc.reason == "overflow"
    # higher-priority arrival displaces the oldest lower-priority one
    lane.put(_req(seq=5, priority=2))
    req, exc = rq.get_nowait()
    assert req.seq == 0 and isinstance(exc, LoadShed)
    assert lane.qsize() == 4
    kept = [lane.get_nowait().seq for _ in range(4)]
    assert kept == [1, 2, 3, 5]
    assert counter_value("serving_shed_total", reason="overflow",
                         lane="device") == 2


def test_lane_watermark_hysteresis():
    rq = queue.Queue()
    lane = BoundedLane("cpu", maxsize=10, high=0.5, low=0.2,
                       result_queue=rq)  # high=5, low=2
    for i in range(5):
        lane.put(_req(seq=i))
    assert not lane.shedding
    lane.put(_req(seq=5))  # depth 5 >= high: engages shedding, sheds
    assert lane.shedding
    req, exc = rq.get_nowait()
    assert req.seq == 5 and exc.reason == "watermark"
    # still above low: sheds persist even though depth < maxsize
    lane.put(_req(seq=6))
    assert rq.get_nowait()[0].seq == 6
    # drain below low releases shedding; admissions resume
    while lane.qsize() > 1:
        lane.get_nowait()
    lane.put(_req(seq=7))
    assert not lane.shedding
    assert lane.get_nowait().seq in (4, 7)
    assert counter_value("serving_shed_total", reason="watermark",
                         lane="cpu") == 2


def test_lane_sheds_expired_request_at_get():
    rq = queue.Queue()
    lane = BoundedLane("device", maxsize=8, result_queue=rq)
    expired = _req(seq=0, deadline=time.perf_counter() - 0.01)
    live = _req(seq=1)
    lane.put(expired)
    lane.put(live)
    got = lane.get_nowait()  # expired one shed on the spot
    assert got.seq == 1
    req, exc = rq.get_nowait()
    assert req.seq == 0 and isinstance(exc, DeadlineExceeded)
    assert counter_value("serving_shed_total", reason="deadline",
                         lane="device") == 1


def test_lane_control_items_always_admitted():
    rq = queue.Queue()
    lane = BoundedLane("device", maxsize=2, high=1.0, low=0.5,
                       result_queue=rq)
    lane.put(_req(seq=0))
    lane.put(_req(seq=1))
    lane.put(_STOP)  # at capacity — the sentinel must still go through
    assert lane.qsize() == 3
    assert rq.empty()


def test_lane_without_result_queue_never_drops():
    lane = BoundedLane("cpu", maxsize=2, high=1.0, low=0.5)
    for i in range(5):  # no way to answer a shed: admit past capacity
        lane.put(_req(seq=i))
    assert [lane.get_nowait().seq for _ in range(5)] == [0, 1, 2, 3, 4]


# ===================================================== CircuitBreaker
def test_breaker_lifecycle_scripted_clock():
    clk = {"t": 0.0}
    br = CircuitBreaker("test.lane", failure_threshold=2,
                        reset_timeout_s=10.0, half_open_probes=1,
                        clock=lambda: clk["t"])
    gauge_key = metric_key("serving_breaker_state", {"lane": "test.lane"})

    assert br.allow() and br.state == "closed"
    br.record_failure()
    assert br.state == "closed"  # below threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()
    assert telemetry.snapshot()["gauges"][gauge_key] == 2

    clk["t"] += 9.9
    assert not br.allow()  # timeout not yet elapsed
    clk["t"] += 0.2
    assert br.allow()  # -> half-open, first probe admitted
    assert br.state == "half_open"
    assert not br.allow()  # probe budget (1) exhausted

    br.record_failure()  # probe failed: back to open, timer restarts
    assert br.state == "open" and not br.allow()
    clk["t"] += 10.1
    assert br.allow() and br.state == "half_open"
    br.record_success()
    assert br.state == "closed" and br.allow()
    assert telemetry.snapshot()["gauges"][gauge_key] == 0

    status = breakers_status()["breakers"]
    mine = [b for b in status if b["lane"] == "test.lane"]
    assert mine and mine[0]["state"] == "closed"
    assert counter_value("serving_breaker_transitions_total",
                         lane="test.lane", to="open") == 2


# ===================================================== chaos harness
def test_chaos_plan_replays_byte_identical():
    def run():
        telemetry.reset()
        plan = (ChaosPlan(seed=42)
                .fail("p.crash", times=2, after=1)
                .fail("p.flaky", exc=ValueError, times=None, rate=0.3))
        crash, flaky = chaos.point("p.crash"), chaos.point("p.flaky")
        outcomes = []
        with chaos.active(plan):
            for _ in range(20):
                for pt in (crash, flaky):
                    try:
                        pt()
                        outcomes.append("ok")
                    except Exception as e:  # noqa: BLE001 — recording
                        outcomes.append(type(e).__name__)
        counters = {
            p: counter_value("chaos_injections_total", point=p)
            for p in ("p.crash", "p.flaky")
        }
        return outcomes, plan.log(), counters

    first, second = run(), run()
    assert first == second  # byte-identical replay
    outcomes, log, counters = first
    assert outcomes.count("ChaosFault") == 2 == counters["p.crash"]
    assert outcomes.count("ValueError") == counters["p.flaky"] > 0
    # hits 1 and 2 of p.crash raise; hit 0 passes
    crash_actions = [a for (n, _, a) in log if n == "p.crash"]
    assert crash_actions[:3] == ["pass", "raise:ChaosFault",
                                 "raise:ChaosFault"]


def test_chaos_point_is_noop_without_plan():
    assert chaos.current_plan() is None
    chaos.point("nowhere.installed")()  # must not raise, tick, or log
    assert counter_value("chaos_injections_total",
                         point="nowhere.installed") == 0


# ===================================================== serving failover
def _serving_stack(small_graph, rng, **server_kw):
    n = small_graph.node_count
    feat = rng.normal(size=(n, 4)).astype(np.float32)
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    sampler = GraphSageSampler(small_graph, [3])
    from quiver_tpu.models import GraphSAGE

    model = GraphSAGE(hidden=8, out_dim=2, num_layers=1, dropout=0.0)
    b0 = sampler.sample(np.arange(8, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        feature[np.asarray(b0.n_id)], b0.layers)
    apply_fn = lambda p, x, blocks: model.apply(p, x, blocks)
    dq = queue.Queue()
    server = InferenceServer(sampler, feature, apply_fn, params, dq,
                             max_coalesce=1, **server_kw)
    return dq, server


def test_device_crash_fails_over_to_cpu_zero_lost(small_graph, rng):
    """An injected device-lane crash completes every in-flight request
    via the CPU sampler lane — none lost, none errored."""
    cpu_sampler = GraphSageSampler(small_graph, [3], mode="CPU")
    dq, server = _serving_stack(small_graph, rng, cpu_sampler=cpu_sampler)
    server.start()
    plan = ChaosPlan(seed=7).fail("serving.device_lane", times=3)
    n_req = 6
    try:
        with chaos.active(plan):
            for i in range(n_req):
                dq.put(_req(ids=rng.integers(0, small_graph.node_count, 5),
                            seq=i))
            got = {}
            for _ in range(n_req):
                req, out = server.result_queue.get(timeout=60)
                got[req.seq] = out
    finally:
        server.stop()
    assert sorted(got) == list(range(n_req))
    for seq, out in got.items():
        assert not isinstance(out, Exception), (seq, out)
        assert out.shape == (5, 2)
    assert plan.hits("serving.device_lane") == n_req
    assert counter_value("serving_failover_total",
                         direction="device_to_cpu") == 3
    assert counter_value("chaos_injections_total",
                         point="serving.device_lane") == 3


def test_device_crash_without_route_answers_errors(small_graph, rng):
    """No cpu_sampler wired: the crash is answered as a typed error (the
    pre-failover contract) — still nothing lost or wedged."""
    dq, server = _serving_stack(small_graph, rng)  # no cpu_sampler
    server.start()
    plan = ChaosPlan(seed=7).fail("serving.device_lane", times=1)
    try:
        with chaos.active(plan):
            dq.put(_req(ids=np.array([1, 2, 3]), seq=0))
            dq.put(_req(ids=np.array([4, 5]), seq=1))
            r = {}
            for _ in range(2):
                req, out = server.result_queue.get(timeout=60)
                r[req.seq] = out
    finally:
        server.stop()
    assert isinstance(r[0], ChaosFault)
    assert r[1].shape == (2, 2)


def test_breaker_open_reroutes_without_touching_device(small_graph, rng):
    """With the device breaker held open every device-lane request takes
    the CPU failover route — the device pass never runs."""
    cpu_sampler = GraphSageSampler(small_graph, [3], mode="CPU")
    dq, server = _serving_stack(small_graph, rng, cpu_sampler=cpu_sampler)
    # trip the breaker before any traffic
    for _ in range(server._breakers["device"].failure_threshold):
        server._breakers["device"].record_failure()
    assert server._breakers["device"].state == "open"
    server.start()
    try:
        dq.put(_req(ids=np.array([1, 2, 3]), seq=0))
        req, out = server.result_queue.get(timeout=60)
    finally:
        server.stop()
    assert not isinstance(out, Exception), out
    assert out.shape == (3, 2)
    assert counter_value("serving_failover_total",
                         direction="device_to_cpu") == 1


# ===================================================== deadlines e2e
def test_deadline_shed_ticks_metric_and_retains_record():
    config_mod.update(serving_deadline_ms=5.0)
    telemetry.reset()
    rq = queue.Queue()
    lane = BoundedLane("device", maxsize=8, result_queue=rq)
    req = _req(seq=0)  # picks up the 5ms budget from config
    assert req.deadline is not None
    lane.put(req)
    time.sleep(0.02)  # let it expire on the queue
    with pytest.raises(queue.Empty):
        lane.get_nowait()
    shed_req, exc = rq.get_nowait()
    assert shed_req is req and isinstance(exc, DeadlineExceeded)
    assert exc.elapsed_ms >= exc.budget_ms
    assert counter_value("serving_shed_total", reason="deadline",
                         lane="device") == 1
    rec = flightrec.get_recorder().get(req.trace.trace_id)
    assert rec is not None
    assert rec["status"] == "shed" and rec["reason"] == "shed"
    assert any(e["name"] == "shed" for e in rec["events"])


def test_batcher_sheds_expired_at_route():
    config_mod.update(serving_deadline_ms=1.0)
    telemetry.reset()
    stream, rq = queue.Queue(), queue.Queue()
    rb = RequestBatcher([stream], mode="CPU", result_queue=rq)
    req = _req(seq=0)
    time.sleep(0.01)
    rb.start()
    try:
        stream.put(req)
        shed_req, exc = rq.get(timeout=10)
    finally:
        assert rb.stop() == []
    assert shed_req is req and isinstance(exc, DeadlineExceeded)
    assert counter_value("serving_shed_total", reason="deadline",
                         lane="batcher") == 1


# ===================================================== batcher rejects
def test_malformed_payload_rejected_thread_survives():
    stream, rq = queue.Queue(), queue.Queue()
    rb = RequestBatcher([stream], mode="CPU", result_queue=rq)
    rb.start()
    try:
        stream.put(3.5)  # scalar payload: not coercible to an ids batch
        good = _req(ids=np.array([1, 2]), seq=1)
        stream.put(good)
        routed = rb.cpu_batched_queue.get(timeout=10)
        assert routed is good  # the stream thread survived the reject
        deadline = time.time() + 5
        while (counter_value("serving_rejected_total") < 1
               and time.time() < deadline):
            time.sleep(0.01)
        assert counter_value("serving_rejected_total") == 1
    finally:
        assert rb.stop() == []  # no leaked threads
    summaries = flightrec.get_recorder().summaries()
    assert any(s["status"] == "rejected" for s in summaries)


# ===================================================== shutdown reaping
def test_join_and_reap_reports_wedged_thread():
    gate = threading.Event()
    t = threading.Thread(target=gate.wait, daemon=True)
    t.start()
    leaked = join_and_reap([t], timeout=0.05, component="unittest")
    assert leaked == [t]
    assert counter_value("serving_thread_leak_total",
                         component="unittest") == 1
    gate.set()
    t.join(timeout=5)


def test_prefetcher_stop_with_wedged_consumer():
    from quiver_tpu.parallel.prefetch import Prefetcher

    p = Prefetcher(range(100), lambda i: i, depth=2)
    it = iter(p)
    assert next(it) == 0
    # the consumer wedges here: it never drains again, so the worker is
    # parked on the full bounded queue.  stop() must still unwind it.
    time.sleep(0.05)
    p.stop()
    deadline = time.time() + 5
    while p._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    assert not p._thread.is_alive()
    it.close()
    assert counter_value("serving_thread_leak_total",
                         component="prefetcher") == 0


# ===================================================== dist degradation
@pytest.fixture(scope="module")
def mesh():
    from quiver_tpu.utils.mesh import make_mesh

    assert jax.device_count() == NHOSTS
    return make_mesh(("data",))


def test_dist_feature_degrades_to_local_rows(mesh, rng):
    from quiver_tpu.dist import DistFeature, PartitionInfo

    n, d = 256, 8
    full = rng.normal(size=(n, d)).astype(np.float32)
    g2h = rng.integers(0, NHOSTS, n).astype(np.int32)
    rep = np.arange(0, 16)  # hottest rows replicated everywhere
    info = PartitionInfo(host=0, hosts=NHOSTS, global2host=g2h,
                         replicate=rep)
    df = DistFeature.from_global_feature(full, mesh, info)
    ids = rng.integers(0, n, (NHOSTS, 32)).astype(np.int32)

    plan = ChaosPlan(seed=3).fail("dist.feature.exchange",
                                  exc=PeerTimeout, times=1)
    with chaos.active(plan):
        out = np.asarray(df.lookup(ids))
    assert df.last_degraded
    mask = df.last_degraded_mask
    # exactly the locally answerable rows: owned by the row's host or
    # replicated everywhere (no overlay is enabled in this fixture)
    expected = (info.replicate_mask[ids]
                | (info.global2host[ids]
                   == np.arange(NHOSTS)[:, None]))
    np.testing.assert_array_equal(mask, expected)
    np.testing.assert_allclose(out[mask], full[ids[mask]], rtol=1e-6)
    assert (out[~mask] == 0).all()
    assert counter_value("dist_feature_degraded_total") == 1

    # the next call (fault cleared) is whole again
    out2 = np.asarray(df.lookup(ids))
    assert not df.last_degraded
    for h in range(NHOSTS):
        np.testing.assert_allclose(out2[h], full[ids[h]], rtol=1e-6)


def test_dist_sampler_retries_exchange_once(small_graph, mesh):
    from quiver_tpu.dist.sampler import DistGraphSampler

    s = DistGraphSampler(small_graph, mesh, sizes=[3])
    seeds = np.random.default_rng(0).integers(
        0, small_graph.node_count, (NHOSTS, 8))
    plan = ChaosPlan(seed=5).fail("dist.sampler.exchange",
                                  exc=PeerTimeout, times=1)
    with chaos.active(plan):
        n_id, n_mask, num, blocks = s.sample(seeds, key=7)
    np.testing.assert_array_equal(np.asarray(n_id)[:, :8], seeds)
    assert counter_value("dist_sampler_retries_total") == 1

    # two consecutive faults exhaust the single retry and surface
    plan2 = ChaosPlan(seed=5).fail("dist.sampler.exchange",
                                   exc=PeerTimeout, times=2)
    with chaos.active(plan2), pytest.raises(PeerTimeout):
        s.sample(seeds, key=7)


# ===================================================== steady-state cost
@pytest.mark.retrace_budget(0)
def test_disabled_checks_add_no_jit_builds():
    """QUIVER_TELEMETRY=off + no chaos plan + no deadline: the whole
    resilience surface — injection points, deadline checks, bounded
    lanes, breaker gates — builds zero jit executables and never touches
    jax (the retrace-budget guard enforces the zero)."""
    telemetry.set_enabled(False)
    try:
        lane = BoundedLane("device", maxsize=16, result_queue=queue.Queue())
        pt = chaos.point("serving.device_lane")
        br = CircuitBreaker("cost.lane", failure_threshold=3)
        for i in range(64):
            pt()  # disabled: one module-global read
            r = _req(seq=i)
            assert r.deadline is None and r.trace is None
            lane.put(r)
            assert br.allow()
            assert lane.get_nowait().seq == i
    finally:
        telemetry.set_enabled(True)
