"""Reindex property tests (parity: tests/cpp/test_reindex.cu — relabel is a
bijection, seeds occupy the frontier prefix, local ids resolve back to the
original global neighbor ids)."""

import numpy as np
import jax
import jax.numpy as jnp

from quiver_tpu.ops.sample import sample_neighbors
from quiver_tpu.ops.reindex import reindex


def _check(seeds, nbrs, mask, r, seed_mask=None):
    B = len(seeds)
    n_id = np.asarray(r.n_id)
    n_mask = np.asarray(r.n_id_mask)
    local = np.asarray(r.local_nbrs)
    num = int(r.num_nodes)
    # seeds occupy their slots
    if seed_mask is None:
        np.testing.assert_array_equal(n_id[:B], seeds)
        assert n_mask[:B].all()
    # valid frontier entries unique
    valid = n_id[n_mask]
    assert num == n_mask.sum()
    assert len(set(valid.tolist())) == len(valid)
    # local ids resolve to the original global ids
    m = np.asarray(mask)
    nb = np.asarray(nbrs)
    for b in range(B):
        for j in range(nb.shape[1]):
            if m[b, j]:
                assert n_id[local[b, j]] == nb[b, j]
                assert n_mask[local[b, j]]
    # every valid frontier node beyond the seeds appears as a neighbor
    seen = set(nb[m].tolist()) | set(np.asarray(seeds)[
        np.ones(B, bool) if seed_mask is None else np.asarray(seed_mask)
    ].tolist())
    assert set(valid.tolist()) <= seen


def test_reindex_bijection(small_graph):
    indptr, indices = small_graph.to_device()
    seeds = np.array([3, 1, 4, 1, 5], dtype=np.int32)  # note: dup seed "1"
    # dedup of seeds themselves is the caller's business in the reference
    # too; use unique seeds for the contract test
    seeds = np.array([3, 1, 4, 15, 5], dtype=np.int32)
    out = sample_neighbors(indptr, indices, jnp.asarray(seeds), 4,
                           jax.random.PRNGKey(0))
    r = reindex(jnp.asarray(seeds), out.nbrs, out.mask)
    _check(seeds, out.nbrs, out.mask, r)


def test_reindex_with_masked_seeds(small_graph):
    indptr, indices = small_graph.to_device()
    seeds = np.array([3, 1, 4, 15, 5, 0, 0, 0], dtype=np.int32)
    sm = np.array([1, 1, 1, 1, 1, 0, 0, 0], dtype=bool)
    out = sample_neighbors(indptr, indices, jnp.asarray(seeds), 3,
                           jax.random.PRNGKey(3),
                           seed_mask=jnp.asarray(sm))
    r = reindex(jnp.asarray(seeds), out.nbrs, out.mask,
                seed_mask=jnp.asarray(sm))
    n_mask = np.asarray(r.n_id_mask)
    assert (n_mask[:8] == sm).all()
    _check(seeds, out.nbrs, out.mask, r, seed_mask=sm)


def test_reindex_no_duplicate_between_seed_and_rest(small_graph):
    """A neighbor that IS a seed must map to the seed's slot, not a new one."""
    indptr, indices = small_graph.to_device()
    # find an edge u -> v, then seed with both u and v
    u = int(np.argmax(small_graph.degree))
    v = int(small_graph.indices[small_graph.indptr[u]])
    seeds = np.array([u, v], dtype=np.int32)
    out = sample_neighbors(indptr, indices, jnp.asarray(seeds), 64,
                           jax.random.PRNGKey(0))
    r = reindex(jnp.asarray(seeds), out.nbrs, out.mask)
    nb = np.asarray(out.nbrs)
    m = np.asarray(out.mask)
    local = np.asarray(r.local_nbrs)
    pos = np.nonzero((nb[0] == v) & m[0])[0]
    assert len(pos) >= 1
    assert local[0, pos[0]] == 1  # v's seed slot
