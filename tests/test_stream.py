"""quiver_tpu.stream suite (docs/STREAMING.md).

Correctness bar, in order of importance:

* **Equivalence** — a StreamingGraph with zero pending deltas must
  sample BIT-IDENTICAL to the frozen-CSR path on the same key, and a
  post-compaction graph must sample bit-identical to a fresh frozen
  sampler built on the folded CSR.  The overlay is an implementation
  detail; it must never show through in the sample distribution.
* **Deletion** — a tombstoned edge never appears in any sample, before
  or after compaction.
* **Time windows** — ``time_window=(lo, hi)`` excludes edges outside
  ``lo <= ts < hi``, and changing the window re-uses the executable.
* **Steady-state ingestion** holds the retrace budget: mutations within
  one delta bucket never mint a new executable.
* **E2E** — concurrent ingest + sampling with a mid-stream compaction
  under a ``stream.compact`` chaos fault: every submitted update is
  answered, sampled versions are monotone and catch up to acked
  admission versions, and deleted edges stay gone throughout.
"""

import queue
import threading
import time

import numpy as np
import jax
import pytest

import quiver_tpu.config as config_mod
from quiver_tpu import Feature, GraphSageSampler, telemetry
from quiver_tpu.resilience import chaos
from quiver_tpu.stream import (
    Compactor, DeltaStore, EdgeUpdate, IngestLane, StreamingGraph, compact,
)
from quiver_tpu.telemetry import flightrec, metric_key
from quiver_tpu.utils.rng import make_key
from quiver_tpu.utils.topology import CSRTopo

pytestmark = pytest.mark.stream


@pytest.fixture(autouse=True)
def _clean_stream():
    telemetry.set_enabled(True)
    telemetry.reset()
    flightrec.reset()
    yield
    chaos.uninstall()
    flightrec.reset()
    telemetry.set_enabled(True)
    telemetry.reset()


def counter_value(name, **labels):
    return telemetry.snapshot()["counters"].get(metric_key(name, labels), 0)


def _random_edges(rng, n=500, e=2000):
    return np.stack([rng.integers(0, n, size=e),
                     rng.integers(0, n, size=e)])


def _star_topo(n_nodes=200, fanout=9):
    """node 0 -> 1..fanout, plus a self-loop pinning node_count."""
    src = np.append(np.zeros(fanout, np.int64), n_nodes - 1)
    dst = np.append(np.arange(1, fanout + 1), n_nodes - 1)
    return CSRTopo(edge_index=np.stack([src, dst]))


def _sampled_neighbors(batch):
    """Set of neighbor node ids drawn for the (single-seed) batch."""
    mask = np.asarray(batch.layers[0].mask)[0]
    return set(int(x) for x in np.asarray(batch.n_id)[1:][mask])


def _assert_batches_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.n_id), np.asarray(b.n_id))
    np.testing.assert_array_equal(np.asarray(a.n_id_mask),
                                  np.asarray(b.n_id_mask))
    assert len(a.layers) == len(b.layers)
    for la, lb in zip(a.layers, b.layers):
        np.testing.assert_array_equal(np.asarray(la.nbr_local),
                                      np.asarray(lb.nbr_local))
        np.testing.assert_array_equal(np.asarray(la.mask),
                                      np.asarray(lb.mask))


# ================================================== DeltaStore (unit)
class TestDeltaStore:
    def test_append_order_and_live_edges(self):
        d = DeltaStore(capacity=8)
        d.add([1, 2], [3, 4])
        d.add(5, 6)
        src, dst, ts = d.live_edges()
        np.testing.assert_array_equal(src, [1, 2, 5])
        np.testing.assert_array_equal(dst, [3, 4, 6])
        assert ts is None and d.live == 3

    def test_kill_marks_last_live_match(self):
        d = DeltaStore(capacity=8)
        d.add([1, 1, 1], [2, 2, 3])
        assert d.kill(1, 2)
        src, dst, _ = d.live_edges()
        np.testing.assert_array_equal(src, [1, 1])
        np.testing.assert_array_equal(dst, [2, 3])
        assert not d.kill(9, 9)            # no match: report, don't raise

    def test_capacity_backpressure(self):
        d = DeltaStore(capacity=2)
        d.add([1, 2], [3, 4])
        with pytest.raises(BufferError):
            d.add(5, 6)
        d.clear()
        d.add(5, 6)                        # clear() frees the buffer
        assert d.live == 1

    def test_timestamps_required_when_declared(self):
        d = DeltaStore(capacity=4, has_ts=True)
        with pytest.raises(ValueError):
            d.add(1, 2)
        d.add(1, 2, ts=7)
        _, _, ts = d.live_edges()
        np.testing.assert_array_equal(ts, [7])


# ============================================= equivalence (tentpole)
def test_zero_delta_bitwise_equivalence():
    rng = np.random.default_rng(0)
    ei = _random_edges(rng)
    topo = CSRTopo(edge_index=ei)
    g = StreamingGraph(CSRTopo(edge_index=ei))
    try:
        stream = GraphSageSampler(g, sizes=[5, 3], gather_mode="xla",
                                  sample_rng="hash")
        frozen = GraphSageSampler(topo, sizes=[5, 3], dedup="none",
                                  gather_mode="xla", sample_rng="hash")
        seeds = rng.integers(0, topo.node_count, size=16)
        for s in range(3):
            bs = stream.sample(seeds, key=make_key(s))
            bf = frozen.sample(seeds, key=make_key(s))
            _assert_batches_equal(bs, bf)
            assert bs.version == 0
    finally:
        g.close()


def test_post_compaction_bitwise_equivalence():
    rng = np.random.default_rng(1)
    g = StreamingGraph(CSRTopo(edge_index=_random_edges(rng)))
    try:
        sampler = GraphSageSampler(g, sizes=[5, 3], gather_mode="xla",
                                   sample_rng="hash")
        n = g.node_count
        g.add_edges(rng.integers(0, n, 40), rng.integers(0, n, 40))
        # tombstone one real base edge
        u = int(np.argmax(g.base.degree))
        v = int(g.base.indices[g.base.indptr[u]])
        g.remove_edges([u], [v])
        stats = compact(g)
        assert stats["dropped"] == 1 and stats["folded"] == 40
        assert g.pending_deltas == 0 and g.tombstone_count == 0
        fresh = GraphSageSampler(g.base, sizes=[5, 3], dedup="none",
                                 gather_mode="xla", sample_rng="hash")
        seeds = rng.integers(0, n, size=16)
        for s in range(3):
            _assert_batches_equal(sampler.sample(seeds, key=make_key(s)),
                                  fresh.sample(seeds, key=make_key(s)))
    finally:
        g.close()


def test_tombstoned_edge_never_sampled():
    g = StreamingGraph(_star_topo())
    try:
        s = GraphSageSampler(g, sizes=[4], gather_mode="xla",
                             sample_rng="hash")
        g.remove_edges([0], [5])
        seen = set()
        for i in range(50):
            seen |= _sampled_neighbors(s.sample([0], key=make_key(i)))
        assert 5 not in seen
        assert seen <= set(range(1, 10))
        compact(g)
        for i in range(50):
            seen |= _sampled_neighbors(s.sample([0], key=make_key(i)))
        assert 5 not in seen
    finally:
        g.close()


def test_delta_edges_join_the_frontier():
    g = StreamingGraph(_star_topo())
    try:
        s = GraphSageSampler(g, sizes=[4], gather_mode="xla",
                             sample_rng="hash")
        g.add_edges([0, 0], [100, 101])
        seen = set()
        for i in range(80):
            seen |= _sampled_neighbors(s.sample([0], key=make_key(i)))
        assert {100, 101} <= seen
    finally:
        g.close()


def test_rejects_node_additions_and_frozen_mutation():
    g = StreamingGraph(_star_topo(n_nodes=10))
    try:
        with pytest.raises(ValueError):
            g.add_edges([0], [10])          # node 10 doesn't exist
        with pytest.raises(ValueError):
            g.add_edges([-1], [0])
    finally:
        g.close()
    frozen = GraphSageSampler(_star_topo(), sizes=[4], dedup="none")
    with pytest.raises(ValueError):
        frozen.sample([0], key=make_key(0), time_window=(0, 5))


# ================================================== temporal sampling
def test_time_window_filters_edges():
    topo = _star_topo()
    ts = np.zeros(topo.edge_count, np.int64)
    # star edges are contiguous in CSR row 0; stamp ts = dst id
    row = topo.indices[topo.indptr[0]:topo.indptr[1]]
    ts[topo.indptr[0]:topo.indptr[1]] = row
    g = StreamingGraph(topo, edge_ts=ts)
    try:
        s = GraphSageSampler(g, sizes=[9], gather_mode="xla",
                             sample_rng="hash")
        b = s.sample([0], key=make_key(1), time_window=(3, 7))
        assert _sampled_neighbors(b) <= {3, 4, 5, 6}
        # widen the window: same executable, full frontier reachable
        seen = set()
        for i in range(40):
            seen |= _sampled_neighbors(
                s.sample([0], key=make_key(i), time_window=(1, 10)))
        assert seen == set(range(1, 10))
        assert len(s._jitted) == 1          # windows are traced operands
    finally:
        g.close()


def test_time_window_applies_to_delta_edges():
    topo = _star_topo()
    g = StreamingGraph(topo, edge_ts=np.full(topo.edge_count, 5,
                                             np.int64))
    try:
        s = GraphSageSampler(g, sizes=[9], gather_mode="xla",
                             sample_rng="hash")
        g.add_edges([0, 0], [50, 60], ts=[2, 8])
        seen = set()
        for i in range(60):
            seen |= _sampled_neighbors(
                s.sample([0], key=make_key(i), time_window=(4, 9)))
        assert 60 in seen and 50 not in seen and seen >= {1, 2, 3}
    finally:
        g.close()


def test_windowed_requires_timestamps():
    g = StreamingGraph(_star_topo())
    try:
        s = GraphSageSampler(g, sizes=[4])
        with pytest.raises(ValueError):
            s.sample([0], key=make_key(0), time_window=(0, 5))
    finally:
        g.close()


# ============================================== invalidation plumbing
def test_mutations_invalidate_attached_feature_rows():
    rng = np.random.default_rng(3)
    topo = _star_topo(n_nodes=64)
    feats = rng.standard_normal((64, 8)).astype(np.float32)
    f = Feature(device_cache_size=16, cache_unit="rows").from_cpu_tensor(
        feats)
    f.enable_cold_cache(rows=16, admit_threshold=2)
    g = StreamingGraph(topo)
    try:
        g.attach_feature(f)
        cold = 40                           # beyond the 16-row hot prefix
        for _ in range(2):                  # second touch admits
            f[np.array([cold])].block_until_ready()
        assert f.cold_cache.probe(np.array([cold - 16]))[0].all()
        g.add_edges([cold], [1])            # mutation touches row `cold`
        hit, _ = f.cold_cache.probe(np.array([cold - 16]))
        assert not hit.any()                # miss after invalidation
        for _ in range(2):
            f[np.array([cold])].block_until_ready()
        assert f.cold_cache.probe(np.array([cold - 16]))[0].all()
        assert counter_value("coldcache_invalidated_rows_total") >= 1
    finally:
        g.close()


def test_graph_version_stamped_on_traces():
    g = StreamingGraph(_star_topo())
    try:
        assert flightrec.graph_version() == 0
        g.add_edges([0], [11])
        t = flightrec.new_trace()
        assert t.graph_version == 1
        assert t.to_record()["graph_version"] == 1
    finally:
        g.close()
    assert flightrec.graph_version() is None   # provider unregistered


# =================================================== retrace budgets
@pytest.mark.retrace_budget(1)
def test_steady_state_ingestion_holds_retrace_budget():
    g = StreamingGraph(_star_topo(), delta_capacity=256)
    try:
        s = GraphSageSampler(g, sizes=[4], gather_mode="xla",
                             sample_rng="hash")
        seeds = np.zeros(8, np.int64)
        s.sample(seeds, key=make_key(0))    # the one budgeted build
        for i in range(20):                 # stays inside one delta bucket
            g.add_edges([0], [20 + i])
            s.sample(seeds, key=make_key(i))
    finally:
        g.close()


# ============================================ ingestion lane + chaos
def test_ingest_lane_applies_and_acks():
    g = StreamingGraph(_star_topo(), delta_capacity=64)
    lane = IngestLane(g, depth=32).start()
    try:
        ups = [lane.submit(0, 10 + i) for i in range(8)]
        acks = [lane.results.get(timeout=5) for _ in range(8)]
        assert all(isinstance(o, tuple) and o[0] == "ok" for _, o in acks)
        assert g.pending_deltas == 8
        assert all(u.admitted_version >= 0 for u in ups)
        assert counter_value("stream_edges_applied_total", op="add") == 8
    finally:
        lane.stop()
        g.close()


def test_ingest_backpressure_compacts_inline():
    g = StreamingGraph(_star_topo(), delta_capacity=8)
    lane = IngestLane(g, depth=64).start()
    try:
        for i in range(20):                 # 2.5x the delta capacity
            lane.submit(0, 10 + i)
        acks = [lane.results.get(timeout=10) for _ in range(20)]
        assert all(isinstance(o, tuple) and o[0] == "ok" for _, o in acks)
        assert counter_value("stream_compactions_total") >= 1
    finally:
        lane.stop()
        g.close()


def test_ingest_chaos_fault_is_answered_not_dropped():
    g = StreamingGraph(_star_topo(), delta_capacity=64)
    lane = IngestLane(g, depth=32).start()
    plan = chaos.ChaosPlan(seed=7).fail("stream.ingest", times=1)
    try:
        with chaos.active(plan):
            for i in range(4):
                lane.submit(0, 10 + i)
            acks = [lane.results.get(timeout=5) for _ in range(4)]
        faults = [o for _, o in acks if isinstance(o, BaseException)]
        oks = [o for _, o in acks if isinstance(o, tuple)]
        assert len(faults) == 1 and len(oks) == 3
        assert counter_value("stream_ingest_errors_total") == 1
        assert g.pending_deltas == 3        # the faulted update not applied
    finally:
        lane.stop()
        g.close()


def test_compactor_retries_after_chaos_fault():
    g = StreamingGraph(_star_topo(), delta_capacity=64)
    g.add_edges([0, 0], [30, 31])
    plan = chaos.ChaosPlan(seed=7).fail("stream.compact", times=1)
    comp = Compactor(g, interval_s=0.05, watermark=1.0, poll_s=0.01)
    try:
        with chaos.active(plan):
            comp.start()
            deadline = time.time() + 10
            while g.pending_deltas and time.time() < deadline:
                time.sleep(0.02)
        assert g.pending_deltas == 0        # second attempt folded
        assert counter_value("stream_compact_errors_total") == 1
        assert counter_value("stream_compactions_total") == 1
    finally:
        comp.stop()
        g.close()


# ================================================ acceptance e2e
@pytest.mark.retrace_budget(2)
def test_e2e_concurrent_ingest_sample_compact_under_chaos():
    """Concurrent ingest + sampling with a mid-stream compaction whose
    first attempt takes a scripted ``stream.compact`` fault: every
    update is answered, sampled graph versions are monotone and reach
    every acked admission version, and a deleted edge never reappears —
    all inside a 2-build retrace budget."""
    rng = np.random.default_rng(42)
    g = StreamingGraph(CSRTopo(edge_index=_random_edges(rng, n=300,
                                                        e=1800)),
                       delta_capacity=128)
    # tombstone one base edge up front; it must stay gone throughout
    dead_u = int(np.argmax(g.base.degree))
    dead_v = int(g.base.indices[g.base.indptr[dead_u]])
    g.remove_edges([dead_u], [dead_v])

    sampler = GraphSageSampler(g, sizes=[6], gather_mode="xla",
                               sample_rng="hash")
    lane = IngestLane(g, depth=64).start()
    comp = Compactor(g, interval_s=0.15, watermark=0.5, poll_s=0.01)
    plan = chaos.ChaosPlan(seed=11).fail("stream.compact", times=1)

    n_updates = 60
    versions, dead_seen, errors = [], [], []
    stop_sampling = threading.Event()

    def sample_loop():
        i = 0
        try:
            while not stop_sampling.is_set():
                b = sampler.sample(np.full(8, dead_u, np.int64),
                                   key=make_key(i))
                versions.append(b.version)
                mask = np.asarray(b.layers[0].mask)
                nbrs = np.asarray(b.n_id)[np.asarray(b.layers[0].nbr_local)]
                if dead_v in set(nbrs[mask].tolist()):
                    dead_seen.append(i)
                i += 1
        except BaseException as e:          # surface, don't hang the join
            errors.append(e)

    t = threading.Thread(target=sample_loop, daemon=True)
    with chaos.active(plan):
        comp.start()
        t.start()
        submitted = []
        for i in range(n_updates):
            u = int(rng.integers(0, g.node_count))
            v = int(rng.integers(0, g.node_count))
            submitted.append(lane.submit(u, v))
            time.sleep(0.002)
        acks = [lane.results.get(timeout=10) for _ in range(n_updates)]
        acked = max(o[2] for _, o in acks if isinstance(o, tuple))
        # keep sampling until a compaction lands AND the sampler has
        # observed a snapshot at least as new as the last acked update
        deadline = time.time() + 30
        while time.time() < deadline and (
                counter_value("stream_compactions_total") < 1
                or not versions or versions[-1] < acked):
            time.sleep(0.02)
    stop_sampling.set()
    t.join(timeout=10)
    lane.stop()
    comp.stop()
    try:
        assert not errors, errors
        # no dropped requests: every update answered, and answered ok
        assert len(acks) == n_updates
        assert all(isinstance(o, tuple) and o[0] == "ok" for _, o in acks)
        # sampled versions are monotone non-decreasing...
        assert versions == sorted(versions)
        # ...and sampling caught up past every acked admission version
        assert max(versions) >= acked >= max(
            u.admitted_version for u in submitted)
        # the deleted edge never reappeared, pre- or post-compaction
        assert dead_seen == []
        # the chaos fault fired AND a later compaction succeeded
        assert counter_value("stream_compact_errors_total") == 1
        assert counter_value("stream_compactions_total") >= 1
        assert plan.hits("stream.compact") >= 2
    finally:
        g.close()


# ================================================ telemetry contract
def test_stream_metrics_ledger():
    g = StreamingGraph(_star_topo(), delta_capacity=64)
    try:
        g.add_edges([0, 0], [20, 21])
        g.remove_edges([0], [1])
        snap = telemetry.snapshot()
        assert counter_value("stream_edges_applied_total", op="add") == 2
        assert counter_value("stream_tombstones_total") == 1
        assert snap["gauges"][metric_key("stream_overlay_bytes", {})] > 0
        compact(g)
        snap = telemetry.snapshot()
        assert counter_value("stream_compactions_total") == 1
        assert snap["gauges"][metric_key("stream_overlay_bytes", {})] == 0
        hkey = metric_key("stream_compact_pause_seconds", {})
        assert sum(snap["histograms"][hkey]["counts"]) == 1
    finally:
        g.close()


# ===================================== concurrency-fix regressions
def test_reentrant_listener_registration_does_not_deadlock():
    """_notify snapshots the listener list and calls back OUTSIDE _lock;
    a listener that registers another listener (or mutates the graph's
    listener set any other way) must therefore not self-deadlock.  Run
    the mutation in a worker so a regression fails the join timeout
    instead of hanging the suite."""
    g = StreamingGraph(_star_topo(), delta_capacity=64)
    try:
        late_rows = []

        def reentrant(rows):
            g.register_invalidation(late_rows.append)

        g.register_invalidation(reentrant)
        t = threading.Thread(target=lambda: g.add_edges([0], [50]),
                             daemon=True)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), \
            "notification deadlocked on re-entrant register_invalidation"
        # the late listener is live for the NEXT mutation
        g.add_edges([0], [51])
        assert late_rows and 51 in np.asarray(late_rows[-1])
    finally:
        g.close()


def test_ingest_and_compactor_threads_are_reaped():
    """stop() must run the threads down through join_and_reap: nothing
    left alive, and the leak counter untouched."""
    g = StreamingGraph(_star_topo(), delta_capacity=64)
    lane = IngestLane(g, depth=8).start()
    comp = Compactor(g, interval_s=30.0)
    comp.start()
    try:
        lane.submit(0, 42)
        lane.results.get(timeout=5)
    finally:
        lane.stop()
        comp.stop()
        g.close()
    assert not comp.is_alive()
    assert not any(th.name.startswith(("stream-ingest", "stream-compact"))
                   for th in threading.enumerate() if th.is_alive())
    assert counter_value("serving_thread_leak_total",
                         component="stream.ingest") == 0
    assert counter_value("serving_thread_leak_total",
                         component="stream.compactor") == 0
