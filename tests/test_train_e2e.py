"""End-to-end training: sampler → feature → model → optimizer.

The "minimum end-to-end slice" of SURVEY.md §7: loss must decrease on a
learnable synthetic task (labels = community id, features correlated with
community), single-device and data-parallel over the 8-device mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

pytestmark = pytest.mark.slow

from quiver_tpu import CSRTopo, Feature, GraphSageSampler
from quiver_tpu.models import GraphSAGE
from quiver_tpu.parallel import TrainState, make_train_step
from quiver_tpu.utils.mesh import make_mesh


N_COMM = 4


@pytest.fixture(scope="module")
def community_graph():
    """Synthetic SBM-ish graph: 4 communities, intra-heavy edges, features
    = community one-hot + noise. Learnable by 2-layer SAGE."""
    rng = np.random.default_rng(0)
    n = 400
    comm = rng.integers(0, N_COMM, n)
    src, dst = [], []
    for v in range(n):
        same = np.nonzero(comm == comm[v])[0]
        other = np.nonzero(comm != comm[v])[0]
        src.extend([v] * 8)
        dst.extend(rng.choice(same, 6).tolist())
        dst.extend(rng.choice(other, 2).tolist())
    topo = CSRTopo(edge_index=np.stack([np.array(src), np.array(dst)]))
    feat = np.eye(N_COMM, dtype=np.float32)[comm]
    feat = feat + rng.normal(0, 0.3, feat.shape).astype(np.float32)
    return topo, feat, comm


def _run_training(topo, feat, comm, mesh=None, steps=30):
    sampler = GraphSageSampler(topo, [5, 5])
    feature = Feature(device_cache_size="1G").from_cpu_tensor(feat)
    model = GraphSAGE(hidden=32, out_dim=N_COMM, num_layers=2, dropout=0.0)
    tx = optax.adam(1e-2)
    B = 32
    rng = np.random.default_rng(1)

    def sample_one(key):
        seeds = rng.integers(0, topo.node_count, B)
        batch = sampler.sample(seeds, key=key)
        x = feature[np.asarray(batch.n_id)]
        labels = jnp.asarray(comm[seeds])
        mask = jnp.ones((B,), bool)
        return batch, x, labels, mask

    b0, x0, l0, m0 = sample_one(jax.random.PRNGKey(0))
    params = model.init(jax.random.PRNGKey(42), x0, b0.layers)
    state = TrainState.create(params, tx)

    ndev = int(mesh.shape["data"]) if mesh is not None else None
    step = make_train_step(
        lambda p, x, blocks, train=False, rngs=None: model.apply(
            p, x, blocks, train=train, rngs=rngs
        ),
        tx, mesh=mesh,
    )

    losses = []
    for i in range(steps):
        if mesh is None:
            batch, x, labels, mask = sample_one(jax.random.PRNGKey(i))
            state, loss = step(state, x, batch.layers, labels, mask,
                               jax.random.PRNGKey(100 + i))
        else:
            parts = [sample_one(jax.random.PRNGKey(i * ndev + r))
                     for r in range(ndev)]
            xs = jnp.stack([p[1] for p in parts])
            blocks = jax.tree_util.tree_map(
                lambda *leaves: jnp.stack(leaves),
                *[p[0].layers for p in parts],
            )
            labels = jnp.stack([p[2] for p in parts])
            masks = jnp.stack([p[3] for p in parts])
            state, loss = step(state, xs, blocks, labels, masks,
                               jax.random.PRNGKey(100 + i))
        losses.append(float(loss))
    return losses


def test_loss_decreases_single_device(community_graph):
    topo, feat, comm = community_graph
    losses = _run_training(topo, feat, comm, mesh=None)
    assert losses[-1] < losses[0] * 0.7, losses[::5]
    assert losses[-1] < 1.0


def test_loss_decreases_data_parallel(community_graph):
    topo, feat, comm = community_graph
    mesh = make_mesh(("data",))
    losses = _run_training(topo, feat, comm, mesh=mesh, steps=20)
    assert losses[-1] < losses[0] * 0.75, losses[::4]
