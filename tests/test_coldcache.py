"""Cold-row overlay cache tests (docs/FEATURE_CACHE.md).

Correctness bar: a Feature with the overlay enabled must return rows
BIT-IDENTICAL to the uncached path under every traffic shape — zipf
skew, wraparound eviction, admission churn, ``feature_order``
translation, pure-cold configs — while `feature_h2d_bytes_total` drops
and the merge/admit executables stay within a fixed build budget.
"""

import numpy as np
import jax
import pytest

from quiver_tpu import Feature, telemetry
from quiver_tpu.ops.coldcache import ColdRowCache
from quiver_tpu.analysis.retrace_guard import count_jit_builds


def _counter(name):
    return telemetry.snapshot()["counters"].get(name, 0.0)


def _budgeted_pair(feats, hot_rows):
    f = Feature(device_cache_size=hot_rows,
                cache_unit="rows").from_cpu_tensor(feats)
    ref = Feature(device_cache_size=hot_rows,
                  cache_unit="rows").from_cpu_tensor(feats)
    return f, ref


def _zipf_ids(rng, s, size, n):
    r = rng.zipf(s, size=size)
    return np.minimum(r - 1, n - 1).astype(np.int64)


# ---------------------------------------------------------------- unit
class TestColdRowCache:
    def test_second_touch_admission(self):
        c = ColdRowCache(capacity=8, n_rows=100, admit_threshold=2)
        ids = np.array([3, 7], dtype=np.int64)
        hit, _ = c.probe(ids)
        assert not hit.any()
        slots, _ = c.admit(ids[~hit])
        assert (slots == -1).all()          # first touch: not admitted
        hit, _ = c.probe(ids)
        assert not hit.any()
        slots, _ = c.admit(ids[~hit])
        assert (slots >= 0).all()           # second touch: admitted
        hit, got = c.probe(ids)
        assert hit.all()
        assert np.array_equal(np.sort(got), np.sort(slots))

    def test_duplicates_in_one_batch_count_as_touches(self):
        c = ColdRowCache(capacity=4, n_rows=10, admit_threshold=2)
        ids = np.array([5, 5], dtype=np.int64)  # twice in one batch
        hit, _ = c.probe(ids)
        slots, _ = c.admit(ids[~hit])
        assert (slots >= 0).all() and slots[0] == slots[1]

    def test_eviction_protects_same_batch_free_slots(self):
        # regression: one admit() both consumes the last free slots and
        # evicts — the sweep must not hand a just-assigned slot out twice
        c = ColdRowCache(capacity=4, n_rows=64, admit_threshold=1)
        c.probe(np.arange(2, dtype=np.int64))
        c.admit(np.arange(2, dtype=np.int64))        # slots 0,1 used
        batch = np.arange(10, 14, dtype=np.int64)    # 2 free + 2 evictions
        c.probe(batch)
        slots, n_evicted = c.admit(batch)
        assert (slots >= 0).all()
        assert len(np.unique(slots)) == len(slots), slots
        assert n_evicted == 2

    @pytest.mark.parametrize("policy", ["clock", "minfreq"])
    def test_eviction_keeps_slot_map_consistent(self, policy, rng):
        c = ColdRowCache(capacity=8, n_rows=200, policy=policy,
                         admit_threshold=1)
        for _ in range(50):
            ids = rng.integers(0, 200, size=12).astype(np.int64)
            hit, slots = c.probe(ids)
            assert np.array_equal(c.node_of[slots[hit]], ids[hit])
            c.admit(ids[~hit])
            res = c.node_of[c.node_of >= 0]
            assert len(np.unique(res)) == len(res)   # no id twice
            live = np.nonzero(c.slot_of >= 0)[0]
            assert np.array_equal(
                np.sort(c.node_of[c.slot_of[live]]), np.sort(live))
        assert c.resident == 8
        assert c.stats()["evictions"] > 0

    def test_clock_second_chance(self):
        c = ColdRowCache(capacity=4, n_rows=50, admit_threshold=1)
        first = np.arange(4, dtype=np.int64)
        c.probe(first)
        c.admit(first)
        c.probe(first[:2])                  # rows 0,1 get their ref bit
        nxt = np.array([10, 11], dtype=np.int64)
        c.probe(nxt)
        c.admit(nxt)                        # must evict the unreferenced 2,3
        hit, _ = c.probe(first)
        assert hit[0] and hit[1] and not hit[2] and not hit[3]

    def test_validation(self):
        with pytest.raises(ValueError):
            ColdRowCache(0, 10)
        with pytest.raises(ValueError):
            ColdRowCache(4, 10, policy="lru")
        with pytest.raises(ValueError):
            ColdRowCache(4, 10, admit_threshold=0)

    def test_stats_shape(self):
        c = ColdRowCache(4, 10)
        s = c.stats()
        assert s["capacity"] == 4 and s["resident"] == 0
        assert s["hit_rate"] == 0.0 and s["policy"] == "clock"
        assert "ColdRowCache" in repr(c)


# -------------------------------------------------------- equivalence
@pytest.mark.parametrize("policy", ["clock", "minfreq"])
def test_overlay_equivalence_zipf(policy, rng):
    feats = rng.normal(size=(500, 8)).astype(np.float32)
    f, ref = _budgeted_pair(feats, 100)
    f.enable_cold_cache(rows=48, policy=policy, admit_threshold=2)
    for step in range(80):
        idx = _zipf_ids(rng, 1.3, 37, 500)
        got, want = np.asarray(f[idx]), np.asarray(ref[idx])
        assert np.array_equal(got, want), (policy, step)
    st = f.cold_cache.stats()
    assert st["hits"] > 0 and st["evictions"] > 0  # churn was exercised


def test_overlay_equivalence_wraparound_eviction(rng):
    # capacity far below the working set: the hand wraps continuously
    feats = rng.normal(size=(300, 5)).astype(np.float32)
    f, ref = _budgeted_pair(feats, 50)
    f.enable_cold_cache(rows=16, admit_threshold=1)
    for step in range(100):
        idx = rng.integers(0, 300, size=23).astype(np.int64)
        assert np.array_equal(np.asarray(f[idx]),
                              np.asarray(ref[idx])), step
    assert f.cold_cache.stats()["evictions"] > 100


def test_overlay_equivalence_feature_order(rng):
    # prob ordering permutes rows; overlay ids live in the TRANSLATED
    # cold space — values must still resolve to the original rows
    feats = rng.normal(size=(400, 6)).astype(np.float32)
    prob = rng.random(400)
    f = Feature(device_cache_size=80,
                cache_unit="rows").from_cpu_tensor(feats, prob=prob)
    f.enable_cold_cache(rows=48, admit_threshold=1)
    for step in range(60):
        idx = rng.integers(0, 400, size=29).astype(np.int64)
        assert np.array_equal(np.asarray(f[idx]), feats[idx]), step
    assert f.cold_cache.stats()["hits"] > 0


def test_overlay_equivalence_pure_cold(rng):
    # cache_count == 0: no hot prefix at all, overlay over everything
    feats = rng.normal(size=(300, 7)).astype(np.float32)
    f, ref = _budgeted_pair(feats, 0)
    assert f.cache_count == 0
    f.enable_cold_cache(rows=32, admit_threshold=1)
    for step in range(80):
        idx = rng.integers(0, 200, size=21).astype(np.int64)
        assert np.array_equal(np.asarray(f[idx]),
                              np.asarray(ref[idx])), step
    assert f.cold_cache.stats()["hits"] > 0


def test_overlay_with_prefetch_worker(rng):
    # prefetch worker stages (and warms the overlay) ahead of consumption
    feats = rng.normal(size=(400, 8)).astype(np.float32)
    f, ref = _budgeted_pair(feats, 80)
    f.enable_cold_cache(rows=64, admit_threshold=1)
    streams = [_zipf_ids(rng, 1.4, 33, 400) for _ in range(40)]
    f.prefetch(streams[0])
    for i, idx in enumerate(streams):
        if i + 1 < len(streams):
            f.prefetch(streams[i + 1])
        assert np.array_equal(np.asarray(f[idx]),
                              np.asarray(ref[idx])), i
    assert f.cold_cache.stats()["hits"] > 0


def test_enable_cold_cache_noop_when_fully_hot(rng):
    feats = rng.normal(size=(50, 4)).astype(np.float32)
    f = Feature(device_cache_size="1G").from_cpu_tensor(feats)
    f.enable_cold_cache(rows=16)
    assert f.cold_cache is None  # nothing to overlay


def test_config_size_enables_at_build(rng):
    feats = rng.normal(size=(200, 4)).astype(np.float32)
    f = Feature(device_cache_size=40, cache_unit="rows",
                cold_cache_size=32).from_cpu_tensor(feats)
    assert f.cold_cache is not None and f.cold_cache.capacity == 32
    off = Feature(device_cache_size=40, cache_unit="rows",
                  cold_cache_size="off").from_cpu_tensor(feats)
    assert off.cold_cache is None


# ----------------------------------------------------------- telemetry
@pytest.mark.telemetry
def test_overlay_counters_and_h2d_reduction(rng):
    """Acceptance: >= 3x less H2D traffic under zipf-skewed repeats."""
    feats = rng.normal(size=(600, 16)).astype(np.float32)
    f, ref = _budgeted_pair(feats, 100)
    f.enable_cold_cache(rows=256, admit_threshold=1)
    streams = [_zipf_ids(rng, 1.1, 64, 600) for _ in range(100)]

    before = _counter("feature_h2d_bytes_total")
    for idx in streams:
        ref[idx]
    bytes_off = _counter("feature_h2d_bytes_total") - before

    before = _counter("feature_h2d_bytes_total")
    hit0 = _counter("feature_coldcache_rows_total{result=hit}")
    miss0 = _counter("feature_coldcache_rows_total{result=miss}")
    ev0 = _counter("feature_coldcache_evictions_total")
    for idx in streams:
        f[idx]
    bytes_on = _counter("feature_h2d_bytes_total") - before
    hits = _counter("feature_coldcache_rows_total{result=hit}") - hit0
    misses = _counter("feature_coldcache_rows_total{result=miss}") - miss0

    assert bytes_off >= 3 * bytes_on, (bytes_off, bytes_on)
    assert hits > 0 and misses > 0
    assert hits + misses > 0
    assert hits / (hits + misses) == pytest.approx(
        f.cold_cache.stats()["hit_rate"], abs=1e-9)
    # evictions counter only moves when the cache actually evicted
    ev = _counter("feature_coldcache_evictions_total") - ev0
    assert ev == f.cold_cache.stats()["evictions"]


@pytest.mark.telemetry
def test_rows_total_tiers_unchanged_by_overlay(rng):
    # the hot/cold tier split is about HBM-prefix vs host-id space and
    # must not change when the overlay absorbs the transfer
    feats = rng.normal(size=(300, 4)).astype(np.float32)
    f, _ = _budgeted_pair(feats, 60)
    f.enable_cold_cache(rows=64, admit_threshold=1)
    idx = rng.integers(0, 300, size=40).astype(np.int64)
    n_cold = int((idx >= 60).sum())
    h0 = _counter("feature_rows_total{tier=hot}")
    c0 = _counter("feature_rows_total{tier=cold}")
    f[idx]
    f[idx]  # second pass: mostly overlay hits, same tier counts
    assert _counter("feature_rows_total{tier=hot}") - h0 \
        == 2 * (40 - n_cold)
    assert _counter("feature_rows_total{tier=cold}") - c0 == 2 * n_cold


# -------------------------------------------------------- retrace cost
@pytest.mark.retrace_budget(24)
def test_overlay_retrace_budget(rng):
    """50 mixed batches stay within a fixed executable budget, and a
    steady-state replay builds NOTHING new (the latency-cliff bar)."""
    feats = np.asarray(rng.normal(size=(500, 8)), dtype=np.float32)
    f = Feature(device_cache_size=100,
                cache_unit="rows").from_cpu_tensor(feats)
    # capacity >= the recurring set + first-touch admission: after one
    # warm pass every recurring cold row is resident, so replays have a
    # stable hit/miss split (deterministic bucket keys)
    f.enable_cold_cache(rows=400, admit_threshold=1)
    streams = [_zipf_ids(rng, 1.2, 64, 500) for _ in range(50)]
    for idx in streams:
        f[idx]
    for idx in streams:          # warm pass 2: admission has converged
        f[idx]
    with count_jit_builds() as c:
        for idx in streams:      # steady state: zero fresh executables
            f[idx]
    assert c.builds == 0, c.describe()


# ---------------------------------------------------------------- dist
def test_dist_overlay_equivalence(rng):
    from jax.sharding import Mesh
    from quiver_tpu.dist.feature import PartitionInfo, DistFeature

    N, D, H = 400, 6, 4
    feats = rng.normal(size=(N, D)).astype(np.float32)
    g2h = rng.integers(0, H, size=N)
    rep = rng.choice(N, size=10, replace=False)
    info = PartitionInfo(host=1, hosts=H, global2host=g2h, replicate=rep)
    mesh = Mesh(np.array(jax.devices()[:H]), ("data",))
    df = DistFeature.from_global_feature(feats, mesh, info)
    ref = DistFeature.from_global_feature(feats, mesh, info)
    df.enable_cold_cache(rows=64, admit_threshold=1)
    for step in range(40):
        ids = _zipf_ids(rng, 1.4, (H, 33), N).astype(np.int32)
        got = np.asarray(df.lookup(ids))
        assert np.array_equal(got, np.asarray(ref.lookup(ids))), step
        assert np.array_equal(got[0], feats[ids[0]]), step
    st = df.cold_cache.stats()
    assert st["hits"] > 0 and st["evictions"] > 0


# ------------------------------------------------- invalidation (stream)
def test_invalidate_rows_miss_then_readmit():
    c = ColdRowCache(capacity=8, n_rows=100, admit_threshold=2)
    ids = np.array([3, 7], dtype=np.int64)
    for _ in range(2):
        hit, _ = c.probe(ids)
        c.admit(ids[~hit])
    assert c.probe(ids)[0].all()
    assert c.invalidate_rows(np.array([3])) == 1
    hit, _ = c.probe(ids)                   # this is touch 1 post-reset
    assert not hit[0] and hit[1]            # only the mutated row dropped
    # admission evidence was reset: one touch isn't enough...
    slots, _ = c.admit(np.array([3]))
    assert (slots == -1).all()
    # ...second touch re-admits, into a serviceable slot
    c.probe(np.array([3]))
    slots, _ = c.admit(np.array([3]))
    assert (slots >= 0).all()
    assert c.probe(np.array([3]))[0].all()


def test_invalidate_rows_ignores_nonresident_and_out_of_range():
    c = ColdRowCache(capacity=4, n_rows=10, admit_threshold=1)
    assert c.invalidate_rows(np.array([-5, 3, 42])) == 0
    assert c.invalidate_rows(np.array([], dtype=np.int64)) == 0


def test_dist_overlay_invalidate_rows(rng):
    from jax.sharding import Mesh
    from quiver_tpu.dist.feature import PartitionInfo, DistFeature

    N, D, H = 400, 6, 4
    feats = rng.normal(size=(N, D)).astype(np.float32)
    g2h = rng.integers(0, H, size=N)
    rep = rng.choice(N, size=10, replace=False)
    info = PartitionInfo(host=1, hosts=H, global2host=g2h, replicate=rep)
    mesh = Mesh(np.array(jax.devices()[:H]), ("data",))
    df = DistFeature.from_global_feature(feats, mesh, info)
    df.enable_cold_cache(rows=64, admit_threshold=1)
    # a remote, non-replicated row: the overlay's bread and butter
    remote = int(np.where((g2h != 1)
                          & ~np.isin(np.arange(N), rep))[0][0])
    ids = np.full((H, 8), remote, dtype=np.int32)
    for _ in range(2):
        df.lookup(ids)
    assert df.cold_cache.probe(np.array([remote]))[0].all()
    assert df.invalidate_rows([remote]) == 1
    assert not df.cold_cache.probe(np.array([remote]))[0].any()
    got = np.asarray(df.lookup(ids))        # correct rows served post-drop
    assert np.array_equal(got[1], feats[ids[1]])
