"""Distributed feature exchange over an 8-device virtual mesh — the
simulated multi-host coverage the reference lacked (SURVEY.md §4)."""

import numpy as np
import pytest

import jax

from quiver_tpu.dist import DistFeature, PartitionInfo, TpuComm
from quiver_tpu.utils.mesh import make_mesh


NHOSTS = 8


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == NHOSTS
    return make_mesh(("data",))


def test_allreduce(mesh):
    comm = TpuComm(mesh, "data")
    x = np.arange(NHOSTS * 4, dtype=np.float32).reshape(NHOSTS, 4)
    out = np.asarray(comm.allreduce(x))
    np.testing.assert_allclose(out, x.sum(axis=0))


def test_all_to_all(mesh):
    comm = TpuComm(mesh, "data")
    # x[i, j] = payload i sends to j
    x = np.arange(NHOSTS * NHOSTS, dtype=np.int32).reshape(NHOSTS, NHOSTS, 1)
    out = np.asarray(comm.all_to_all(x))
    np.testing.assert_array_equal(out[:, :, 0], x[:, :, 0].T)


def test_partition_info_dispatch():
    n = 100
    g2h = np.arange(n) % 4
    info = PartitionInfo(host=1, hosts=4, global2host=g2h)
    ids = np.array([0, 1, 2, 3, 4, 5, 6, 7])
    out_ids, out_pos = info.dispatch(ids)
    for h in range(4):
        assert (g2h[out_ids[h]] == h).all()
    got = np.concatenate(out_ids)
    assert sorted(got.tolist()) == sorted(ids.tolist())


def test_dist_feature_exchange(mesh, rng):
    n, d = 256, 8
    full = rng.normal(size=(n, d)).astype(np.float32)
    g2h = rng.integers(0, NHOSTS, n).astype(np.int32)
    info = PartitionInfo(host=0, hosts=NHOSTS, global2host=g2h)
    df = DistFeature.from_global_feature(full, mesh, info)
    B = 32
    ids = rng.integers(0, n, (NHOSTS, B)).astype(np.int32)
    out = np.asarray(df.lookup(ids))
    for h in range(NHOSTS):
        np.testing.assert_allclose(out[h], full[ids[h]], rtol=1e-6)


def test_dist_feature_with_replication(mesh, rng):
    n, d = 128, 4
    full = rng.normal(size=(n, d)).astype(np.float32)
    g2h = rng.integers(0, NHOSTS, n).astype(np.int32)
    rep = np.array([0, 5, 17, 99])
    info = PartitionInfo(host=0, hosts=NHOSTS, global2host=g2h,
                         replicate=rep)
    df = DistFeature.from_global_feature(full, mesh, info)
    ids = np.tile(rep[None], (NHOSTS, 8)).astype(np.int32)
    out = np.asarray(df.lookup(ids))
    for h in range(NHOSTS):
        np.testing.assert_allclose(out[h], full[ids[h]], rtol=1e-6)


def test_dist_feature_skewed_load(mesh, rng):
    """All requests target one owner — worst-case bucket pressure."""
    n, d = 64, 4
    full = rng.normal(size=(n, d)).astype(np.float32)
    g2h = np.zeros(n, dtype=np.int32)  # everything owned by host 0
    info = PartitionInfo(host=0, hosts=NHOSTS, global2host=g2h)
    df = DistFeature.from_global_feature(full, mesh, info)
    B = 16
    ids = rng.integers(0, n, (NHOSTS, B)).astype(np.int32)
    out = np.asarray(df.lookup(ids))
    for h in range(NHOSTS):
        np.testing.assert_allclose(out[h], full[ids[h]], rtol=1e-6)


def test_dist_feature_parity_getitem(mesh, rng):
    n, d = 64, 4
    full = rng.normal(size=(n, d)).astype(np.float32)
    g2h = rng.integers(0, NHOSTS, n).astype(np.int32)
    info = PartitionInfo(host=2, hosts=NHOSTS, global2host=g2h)
    df = DistFeature.from_global_feature(full, mesh, info)
    ids = rng.integers(0, n, 16)
    out = np.asarray(df[ids])
    np.testing.assert_allclose(out, full[ids], rtol=1e-6)


def test_partition_to_distfeature_roundtrip(mesh, tmp_path, rng):
    """quiver_partition_feature book -> PartitionInfo -> DistFeature lookup
    equals the original features (tooling + runtime coherence)."""
    from quiver_tpu import quiver_partition_feature

    n, d = 160, 4
    feature = rng.normal(size=(n, d)).astype(np.float32)
    probs = [rng.uniform(0, 1, n) for _ in range(NHOSTS)]
    _, _, book = quiver_partition_feature(feature, probs, str(tmp_path))
    info = PartitionInfo.from_partition_book(book)
    assert info.hosts == NHOSTS
    df = DistFeature.from_global_feature(feature, mesh, info)
    ids = rng.integers(0, n, (NHOSTS, 16)).astype(np.int32)
    out = np.asarray(df.lookup(ids))
    for h in range(NHOSTS):
        np.testing.assert_allclose(out[h], feature[ids[h]], rtol=1e-6)


def test_hybrid_mesh_degenerate():
    from quiver_tpu.dist import make_hybrid_mesh

    mesh = make_hybrid_mesh()
    assert mesh.axis_names == ("dcn", "ici")
    assert int(np.prod(list(mesh.shape.values()))) == NHOSTS


def test_ring_feature_lookup(mesh, rng):
    from quiver_tpu.dist import RingFeature

    n, d = 100, 8  # NOT a multiple of 8 devices -> exercises padding
    full = rng.normal(size=(n, d)).astype(np.float32)
    rf = RingFeature(full, mesh)
    ids = rng.integers(0, n, (NHOSTS, 24)).astype(np.int32)
    out = np.asarray(rf.lookup(ids))
    for h in range(NHOSTS):
        np.testing.assert_allclose(out[h], full[ids[h]], rtol=1e-6)
