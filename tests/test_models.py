"""Model forward-pass tests over sampled dense blocks."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu import GraphSageSampler
from quiver_tpu.models import GraphSAGE, GAT, SAGEConv


@pytest.fixture
def sampled(small_graph):
    s = GraphSageSampler(small_graph, [4, 3])
    seeds = np.arange(16, dtype=np.int64)
    return s.sample(seeds, key=jax.random.PRNGKey(0))


def test_sage_forward(sampled, rng):
    x = jnp.asarray(rng.normal(size=(sampled.n_id.shape[0], 12)),
                    jnp.float32)
    model = GraphSAGE(hidden=32, out_dim=5, num_layers=2)
    params = model.init(jax.random.PRNGKey(0), x, sampled.layers)
    out = model.apply(params, x, sampled.layers)
    assert out.shape == (16, 5)
    assert np.isfinite(np.asarray(out)).all()


def test_gat_forward(sampled, rng):
    x = jnp.asarray(rng.normal(size=(sampled.n_id.shape[0], 12)),
                    jnp.float32)
    model = GAT(hidden=8, out_dim=5, num_layers=2, heads=2)
    params = model.init(jax.random.PRNGKey(0), x, sampled.layers)
    out = model.apply(params, x, sampled.layers)
    assert out.shape == (16, 5)
    assert np.isfinite(np.asarray(out)).all()


def test_sageconv_mean_matches_manual(small_graph, rng):
    """SAGEConv aggregation equals a hand-computed masked mean."""
    s = GraphSageSampler(small_graph, [4])
    seeds = np.arange(8, dtype=np.int64)
    b = s.sample(seeds, key=jax.random.PRNGKey(1))
    blk = b.layers[0]
    x = jnp.asarray(rng.normal(size=(b.n_id.shape[0], 6)), jnp.float32)
    conv = SAGEConv(7)
    params = conv.init(jax.random.PRNGKey(0), x, blk)
    out = np.asarray(conv.apply(params, x, blk))

    w_self = np.asarray(params["params"]["lin_self"]["kernel"])
    b_self = np.asarray(params["params"]["lin_self"]["bias"])
    w_nbr = np.asarray(params["params"]["lin_nbr"]["kernel"])
    xs = np.asarray(x)
    local = np.asarray(blk.nbr_local)
    m = np.asarray(blk.mask)
    for i in range(8):
        nb = xs[local[i][m[i]]]
        mean = nb.mean(axis=0) if len(nb) else np.zeros(6)
        ref = xs[i] @ w_self + b_self + mean @ w_nbr
        np.testing.assert_allclose(out[i], ref, rtol=1e-4, atol=1e-5)


def test_masked_padding_does_not_leak(small_graph, rng):
    """Changing features of masked (padding) frontier rows must not change
    the model output for valid targets."""
    s = GraphSageSampler(small_graph, [4, 3])
    seeds = np.arange(8, dtype=np.int64)
    b = s.sample(seeds, key=jax.random.PRNGKey(2))
    P = b.n_id.shape[0]
    x1 = rng.normal(size=(P, 6)).astype(np.float32)
    x2 = x1.copy()
    pad = ~np.asarray(b.n_id_mask)
    x2[pad] = 1e6  # poison padding rows
    model = GraphSAGE(hidden=16, out_dim=3, num_layers=2)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x1), b.layers)
    o1 = np.asarray(model.apply(params, jnp.asarray(x1), b.layers))
    o2 = np.asarray(model.apply(params, jnp.asarray(x2), b.layers))
    np.testing.assert_allclose(o1[:8], o2[:8], rtol=1e-5)


def test_gcn_forward_and_trains(small_graph, rng):
    import optax

    from quiver_tpu.models import GCN

    s = GraphSageSampler(small_graph, [4, 3])
    seeds = np.arange(16, dtype=np.int64)
    b = s.sample(seeds, key=jax.random.PRNGKey(4))
    x = jnp.asarray(rng.normal(size=(b.n_id.shape[0], 12)), jnp.float32)
    model = GCN(hidden=16, out_dim=5, num_layers=2, dropout=0.0)
    params = model.init(jax.random.PRNGKey(0), x, b.layers)
    out = model.apply(params, x, b.layers)
    assert out.shape == (16, 5)
    labels = jnp.asarray(rng.integers(0, 5, 16))
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    def loss_fn(p):
        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply(p, x, b.layers), labels
        ).mean()

    l0 = float(loss_fn(params))
    for _ in range(5):
        g = jax.grad(loss_fn)(params)
        upd, opt = tx.update(g, opt, params)
        params = optax.apply_updates(params, upd)
    assert float(loss_fn(params)) < l0


def test_full_graph_inference_matches_manual(small_graph, rng):
    """Exact inference equals brute-force numpy layer computation."""
    from quiver_tpu.models.sage import full_graph_inference
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu import GraphSageSampler

    n = small_graph.node_count
    x0 = rng.normal(size=(n, 6)).astype(np.float32)
    model = GraphSAGE(hidden=8, out_dim=3, num_layers=2, dropout=0.0)
    s = GraphSageSampler(small_graph, [3, 3])
    b = s.sample(np.arange(4, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(x0)[b.n_id], b.layers)

    indptr, indices = small_graph.indptr, small_graph.indices
    out = np.asarray(full_graph_inference(
        params, jnp.asarray(x0), indptr, indices, 2, edge_chunk=500
    ))

    # numpy brute force
    p = params["params"]
    h = x0
    for i in range(2):
        ws, bs = np.asarray(p[f"conv{i}"]["lin_self"]["kernel"]), \
            np.asarray(p[f"conv{i}"]["lin_self"]["bias"])
        wn = np.asarray(p[f"conv{i}"]["lin_nbr"]["kernel"])
        mean = np.zeros_like(h)
        for v in range(n):
            row = indices[indptr[v]: indptr[v + 1]]
            if len(row):
                mean[v] = h[row].mean(axis=0)
        h = h @ ws + bs + mean @ wn
        if i != 1:
            h = np.maximum(h, 0)
    np.testing.assert_allclose(out, h, rtol=2e-4, atol=2e-5)


def test_gatconv_matches_manual(small_graph, rng):
    """GATConv (1 head) equals a hand-computed masked-softmax attention
    with the self-loop term a_src·Wx_i + a_tgt·Wx_i."""
    from quiver_tpu.models import GATConv

    s = GraphSageSampler(small_graph, [3])
    seeds = np.arange(6, dtype=np.int64)
    b = s.sample(seeds, key=jax.random.PRNGKey(8))
    blk = b.layers[0]
    x = jnp.asarray(rng.normal(size=(b.n_id.shape[0], 5)), jnp.float32)
    conv = GATConv(4, heads=1, concat=True)
    params = conv.init(jax.random.PRNGKey(0), x, blk)
    out = np.asarray(conv.apply(params, x, blk))

    w = np.asarray(params["params"]["lin"]["kernel"])      # [5, 4]
    a_s = np.asarray(params["params"]["att_src"])[0]       # [4]
    a_t = np.asarray(params["params"]["att_tgt"])[0]       # [4]
    xs = np.asarray(x)
    local = np.asarray(blk.nbr_local)
    m = np.asarray(blk.mask)

    def leaky(v):
        return np.where(v > 0, v, 0.2 * v)

    for i in range(6):
        wi = xs[i] @ w
        nbr_ids = local[i][m[i]]
        wn = xs[nbr_ids] @ w if len(nbr_ids) else np.zeros((0, 4))
        e = [leaky(wn[j] @ a_s + wi @ a_t) for j in range(len(nbr_ids))]
        e.append(leaky(wi @ a_s + wi @ a_t))  # self loop
        e = np.array(e)
        al = np.exp(e - e.max())
        al = al / al.sum()
        vals = np.concatenate([wn, wi[None]], axis=0)
        ref = (al[:, None] * vals).sum(axis=0)
        np.testing.assert_allclose(out[i], ref, rtol=1e-4, atol=1e-5)


def test_gcnconv_matches_manual(small_graph, rng):
    """GCNConv equals the hand-computed sampled-degree normalization."""
    from quiver_tpu.models import GCNConv

    s = GraphSageSampler(small_graph, [3])
    seeds = np.arange(6, dtype=np.int64)
    b = s.sample(seeds, key=jax.random.PRNGKey(9))
    blk = b.layers[0]
    x = jnp.asarray(rng.normal(size=(b.n_id.shape[0], 5)), jnp.float32)
    conv = GCNConv(4)
    params = conv.init(jax.random.PRNGKey(0), x, blk)
    out = np.asarray(conv.apply(params, x, blk))

    w = np.asarray(params["params"]["lin"]["kernel"])
    bias = np.asarray(params["params"]["lin"]["bias"])
    xs = np.asarray(x)
    local, m = np.asarray(blk.nbr_local), np.asarray(blk.mask)
    for i in range(6):
        wi = xs[i] @ w + bias
        wn = xs[local[i][m[i]]] @ w + bias
        norm = 1.0 / np.sqrt(m[i].sum() + 1.0)
        ref = (wn.sum(axis=0) * norm + wi) * norm
        np.testing.assert_allclose(out[i], ref, rtol=1e-4, atol=1e-5)


def test_full_graph_inference_gcn_matches_numpy(small_graph, rng):
    """Exact GCN inference == brute-force symmetric-norm computation."""
    from quiver_tpu.models.inference import full_graph_inference
    from quiver_tpu.models import GCN
    from quiver_tpu import GraphSageSampler

    n = small_graph.node_count
    x0 = rng.normal(size=(n, 5)).astype(np.float32)
    model = GCN(hidden=7, out_dim=3, num_layers=2, dropout=0.0)
    s = GraphSageSampler(small_graph, [3, 3])
    b = s.sample(np.arange(4, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(x0)[b.n_id], b.layers)
    indptr, indices = small_graph.indptr, small_graph.indices
    out = np.asarray(full_graph_inference(
        model, params, jnp.asarray(x0), indptr, indices, edge_chunk=333
    ))

    p = params["params"]
    deg = (indptr[1:] - indptr[:-1]).astype(np.float64)
    norm = 1.0 / np.sqrt(deg + 1.0)
    h = x0.astype(np.float64)
    for i in range(2):
        k = np.asarray(p[f"gcn{i}"]["lin"]["kernel"], np.float64)
        bias = np.asarray(p[f"gcn{i}"]["lin"]["bias"], np.float64)
        w = h @ k + bias
        acc = np.zeros_like(w)
        for v in range(n):
            for u in indices[indptr[v]:indptr[v + 1]]:
                acc[v] += w[u] * norm[u]
        h = (acc + w * norm[:, None]) * norm[:, None]
        if i != 1:
            h = np.maximum(h, 0)
    np.testing.assert_allclose(out, h, rtol=2e-4, atol=2e-5)


def test_full_graph_inference_gat_matches_full_fanout_blocks(small_graph,
                                                             rng):
    """With fanout >= max degree the sampled GAT forward sees every
    neighbor, so it must equal the exact layer-wise path."""
    from quiver_tpu.models.inference import full_graph_inference
    from quiver_tpu.models import GAT
    from quiver_tpu import GraphSageSampler

    n = small_graph.node_count
    kmax = int(small_graph.degree.max())
    x0 = rng.normal(size=(n, 4)).astype(np.float32)
    model = GAT(hidden=6, out_dim=3, num_layers=1, heads=1, dropout=0.0)
    s = GraphSageSampler(small_graph, [kmax], dedup="hop")
    seeds = np.arange(n, dtype=np.int64)
    b = s.sample(seeds)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.asarray(x0)[b.n_id], b.layers)

    x_in = jnp.asarray(x0)[b.n_id]
    sampled = np.asarray(model.apply(params, x_in, b.layers))[:n]
    exact = np.asarray(full_graph_inference(
        model, params, jnp.asarray(x0), small_graph.indptr,
        small_graph.indices, edge_chunk=200
    ))
    np.testing.assert_allclose(sampled, exact, rtol=2e-4, atol=2e-5)


def test_bfloat16_models_train(small_graph, rng):
    """dtype=bfloat16 models: finite outputs, loss decreases, params
    stay float32 (mixed precision, the MXU recipe)."""
    import optax
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu import GraphSageSampler

    n = small_graph.node_count
    x0 = jnp.asarray(rng.normal(size=(n, 8)).astype(np.float32))
    model = GraphSAGE(hidden=16, out_dim=4, num_layers=2, dropout=0.0,
                      dtype=jnp.bfloat16)
    s = GraphSageSampler(small_graph, [4, 3])
    b = s.sample(np.arange(16, dtype=np.int64))
    params = model.init(jax.random.PRNGKey(0), x0[b.n_id], b.layers)
    leaves = jax.tree_util.tree_leaves(params)
    assert all(l.dtype == jnp.float32 for l in leaves)
    out = model.apply(params, x0[b.n_id], b.layers)
    assert out.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())

    tx = optax.adam(1e-2)
    opt = tx.init(params)
    labels = jnp.asarray(rng.integers(0, 4, 16))

    def loss_fn(p):
        logits = model.apply(p, x0[b.n_id], b.layers).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:16], labels).mean()

    l0 = float(loss_fn(params))
    for _ in range(8):
        g = jax.grad(loss_fn)(params)
        upd, opt = tx.update(g, opt, params)
        params = optax.apply_updates(params, upd)
    assert float(loss_fn(params)) < l0
