"""Edge-featured model end to end: sampler eid -> edge-feature gather ->
edge-featured GraphSAGE training.  Closes the reference's ``Adj.e_id`` loop
(``sage_sampler.py:143`` forwards edge ids so user code can look up edge
attributes); here the lookup runs under the model's jit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from quiver_tpu import CSRTopo, GraphSageSampler
from quiver_tpu.models import GraphSAGE


@pytest.fixture
def graph():
    rng = np.random.default_rng(3)
    n, e = 400, 3000
    dst = np.sort(rng.integers(0, n, e))
    src = rng.integers(0, n, e)
    topo = CSRTopo(edge_index=np.stack([src, dst]))
    return topo


def test_edge_features_reach_model(graph):
    E = graph.edge_count
    efeat = np.random.default_rng(0).normal(size=(E, 4)).astype(np.float32)
    x = np.random.default_rng(1).normal(size=(graph.node_count, 8)
                                        ).astype(np.float32)
    s = GraphSageSampler(graph, [5, 3], return_eid=True)
    b = s.sample(np.arange(16, dtype=np.int32), key=jax.random.PRNGKey(0))
    assert all(blk.eid is not None for blk in b.layers)

    model = GraphSAGE(hidden=16, out_dim=3, num_layers=2, dropout=0.0)
    xb = jnp.asarray(x)[b.n_id]
    params = model.init(jax.random.PRNGKey(1), xb, b.layers,
                        edge_feat_table=jnp.asarray(efeat))
    out = model.apply(params, xb, b.layers,
                      edge_feat_table=jnp.asarray(efeat))
    assert out.shape == (16, 3)
    assert np.isfinite(np.asarray(out)).all()

    # edge features actually flow: zeroing the table changes the output
    out0 = model.apply(params, xb, b.layers,
                       edge_feat_table=jnp.zeros_like(efeat))
    assert not np.allclose(np.asarray(out), np.asarray(out0))


def test_edge_model_trains(graph):
    E = graph.edge_count
    rng = np.random.default_rng(7)
    efeat = jnp.asarray(rng.normal(size=(E, 4)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(graph.node_count, 8)
                               ).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 3, 16))

    s = GraphSageSampler(graph, [5, 3], return_eid=True)
    model = GraphSAGE(hidden=16, out_dim=3, num_layers=2, dropout=0.0)
    b0 = s.sample(np.arange(16, dtype=np.int32), key=jax.random.PRNGKey(0))
    params = model.init(jax.random.PRNGKey(1), x[b0.n_id], b0.layers,
                        edge_feat_table=efeat)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, n_id, blocks):
        def loss_fn(p):
            logits = model.apply(p, x[n_id], blocks,
                                 edge_feat_table=efeat)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        up, opt = tx.update(g, opt)
        return optax.apply_updates(params, up), opt, loss

    losses = []
    for i in range(12):
        b = s.sample(np.arange(16, dtype=np.int32),
                     key=jax.random.PRNGKey(10 + i))
        params, opt, loss = step(params, opt, b.n_id, b.layers)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
