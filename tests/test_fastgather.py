"""Fast gather paths: lane-select element gather + Pallas row gather
(interpret mode on CPU; real-TPU timing lives in benchmarks/)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu.ops.fastgather import element_gather, prepare_table


def test_element_gather_matches_take(rng):
    table = jnp.asarray(rng.integers(0, 1000, 1000, dtype=np.int32))
    t2d = prepare_table(table)
    idx = jnp.asarray(rng.integers(0, 1000, 513, dtype=np.int32))
    out = element_gather(t2d, idx)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[np.asarray(idx)])


def test_element_gather_2d_idx(rng):
    table = jnp.asarray(rng.normal(size=300).astype(np.float32))
    t2d = prepare_table(table)
    idx = jnp.asarray(rng.integers(0, 300, (7, 9), dtype=np.int32))
    out = element_gather(t2d, idx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(table)[np.asarray(idx)], rtol=1e-7
    )


def test_pallas_gather_rows_interpret(rng):
    from quiver_tpu.ops.pallas.gather_kernel import gather_rows

    table = jnp.asarray(rng.normal(size=(500, 32)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, 500, 512, dtype=np.int32))
    out = gather_rows(table, idx, block=128, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(table)[np.asarray(idx)], rtol=1e-7
    )


def test_pallas_lane_select_interpret(rng):
    from quiver_tpu.ops.pallas.element_gather_kernel import lane_select, BLK

    rows = jnp.asarray(rng.integers(0, 100, (BLK * 2, 128), dtype=np.int32))
    lanes = jnp.asarray(rng.integers(0, 128, BLK * 2, dtype=np.int32))
    out = lane_select(rows, lanes, interpret=True)
    expect = np.asarray(rows)[np.arange(BLK * 2), np.asarray(lanes)]
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_pallas_element_gather_interpret(rng):
    """Fused row-DMA gather kernel == ground truth (interpret mode)."""
    from quiver_tpu.ops.pallas.sample_gather_kernel import (
        pallas_element_gather)

    table = jnp.asarray(rng.normal(size=(256 * 128,)).astype(np.float32))
    t2d = table.reshape(-1, 128)
    # unaligned count exercises the pad+slice path; 2-D idx the reshape
    idx = rng.integers(0, 256 * 128, (37, 11)).astype(np.int32)
    out = pallas_element_gather(t2d, jnp.asarray(idx), interpret=True)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[idx])


def test_pallas_gather_mode_in_sampler(small_graph, rng):
    """gather_mode='pallas' flows through sample_neighbors (interpret on
    CPU is implicit via pallas interpret fallback? no — force interpret by
    calling the op's gather directly)."""
    from quiver_tpu.ops.pallas.sample_gather_kernel import (
        pallas_element_gather)

    indptr, _ = small_graph.to_device()
    m = indptr.shape[0] // 128 * 128
    idx = jnp.asarray(rng.integers(0, m, 64).astype(np.int32))
    got = pallas_element_gather(indptr[:m].reshape(-1, 128), idx,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(indptr)[np.asarray(idx)])


class TestPallasWindowSample:
    """Fused window-sampling kernel (PRNG + window DMA + select in one
    pallas_call): bitwise equality with the XLA hash path on every route
    (fitting windows, compacted fallback, wholesale classic)."""

    def _xla_reference(self, table, start, deg, key, k):
        from quiver_tpu.ops.sample import (_hash_uniform,
                                           _stratified_positions)

        u = _hash_uniform(key, (len(start), k))
        pos = np.asarray(_stratified_positions(
            jnp.asarray(u), jnp.asarray(deg), k))
        return np.asarray(table)[
            np.clip(np.asarray(start)[:, None] + pos, 0, len(table) - 1)]

    def _mk_csr(self, rng, B, max_deg, U):
        deg = rng.integers(0, max_deg, B).astype(np.int32)
        total = int(deg.sum())
        pad = (-total) % 128 or 128
        table = rng.integers(0, 1 << 30, total + pad).astype(np.int32)
        start = np.concatenate([[0], np.cumsum(deg)[:-1]]).astype(np.int32)
        return table, start, deg

    @pytest.mark.parametrize("U,k,B", [
        (3, 15, 64), (3, 10, 257),  # products fanout + multi-program grid
        pytest.param(2, 5, 64, marks=pytest.mark.slow),
        pytest.param(1, 8, 64, marks=pytest.mark.slow),
    ])
    def test_fitting_windows_match_xla(self, rng, U, k, B):
        from quiver_tpu.ops.pallas.window_sample_kernel import (
            pallas_window_sample)

        # all windows fit U rows by construction (deg < 128)
        table, start, deg = self._mk_csr(rng, B, 120, U)
        key = jax.random.PRNGKey(7)
        got = np.asarray(pallas_window_sample(
            jnp.asarray(table).reshape(-1, 128), jnp.asarray(start),
            jnp.asarray(deg), key, k, U=U, interpret=True))
        want = self._xla_reference(table, start, deg, key, k)
        np.testing.assert_array_equal(got, want)

    def test_nonfitting_seeds_route_through_fallback(self, rng):
        from quiver_tpu.ops.pallas.window_sample_kernel import (
            pallas_window_sample)

        U, k, B = 2, 7, 96
        deg = np.where(rng.random(B) < 0.3,
                       rng.integers(U * 128 + 1, 2000, B),
                       rng.integers(0, 100, B)).astype(np.int32)
        total = int(deg.sum())
        table = rng.integers(0, 1 << 30,
                             total + ((-total) % 128 or 128)).astype(np.int32)
        start = np.concatenate([[0], np.cumsum(deg)[:-1]]).astype(np.int32)
        key = jax.random.PRNGKey(3)
        got = np.asarray(pallas_window_sample(
            jnp.asarray(table).reshape(-1, 128), jnp.asarray(start),
            jnp.asarray(deg), key, k, U=U, fallback_frac=0.5,
            interpret=True))
        want = self._xla_reference(table, start, deg, key, k)
        np.testing.assert_array_equal(got, want)

    def test_wholesale_classic_on_cap_overflow(self, rng):
        from quiver_tpu.ops.pallas.window_sample_kernel import (
            pallas_window_sample)

        U, k, B = 1, 6, 64
        deg = rng.integers(200, 1500, B).astype(np.int32)  # nothing fits
        total = int(deg.sum())
        table = rng.integers(0, 1 << 30,
                             total + ((-total) % 128 or 128)).astype(np.int32)
        start = np.concatenate([[0], np.cumsum(deg)[:-1]]).astype(np.int32)
        key = jax.random.PRNGKey(11)
        got = np.asarray(pallas_window_sample(
            jnp.asarray(table).reshape(-1, 128), jnp.asarray(start),
            jnp.asarray(deg), key, k, U=U, fallback_frac=0.02,
            interpret=True))
        want = self._xla_reference(table, start, deg, key, k)
        np.testing.assert_array_equal(got, want)

    def test_window_at_table_end_and_zero_deg(self, rng):
        from quiver_tpu.ops.pallas.window_sample_kernel import (
            pallas_window_sample)

        # windows deliberately in the LAST rows of the table (r0 clipping)
        U, k = 3, 4
        table = rng.integers(0, 1 << 30, 512).astype(np.int32)  # 4 rows
        start = np.array([500, 470, 0, 0], np.int32)
        deg = np.array([12, 42, 0, 0], np.int32)
        key = jax.random.PRNGKey(1)
        got = np.asarray(pallas_window_sample(
            jnp.asarray(table).reshape(-1, 128), jnp.asarray(start),
            jnp.asarray(deg), key, k, U=U, interpret=True))
        want = self._xla_reference(table, start, deg, key, k)
        # zero-degree rows return garbage by contract; compare valid rows
        np.testing.assert_array_equal(got[:2], want[:2])
