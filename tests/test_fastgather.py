"""Fast gather paths: lane-select element gather + Pallas row gather
(interpret mode on CPU; real-TPU timing lives in benchmarks/)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu.ops.fastgather import element_gather, prepare_table


def test_element_gather_matches_take(rng):
    table = jnp.asarray(rng.integers(0, 1000, 1000, dtype=np.int32))
    t2d = prepare_table(table)
    idx = jnp.asarray(rng.integers(0, 1000, 513, dtype=np.int32))
    out = element_gather(t2d, idx)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[np.asarray(idx)])


def test_element_gather_2d_idx(rng):
    table = jnp.asarray(rng.normal(size=300).astype(np.float32))
    t2d = prepare_table(table)
    idx = jnp.asarray(rng.integers(0, 300, (7, 9), dtype=np.int32))
    out = element_gather(t2d, idx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(table)[np.asarray(idx)], rtol=1e-7
    )


def test_pallas_gather_rows_interpret(rng):
    from quiver_tpu.ops.pallas.gather_kernel import gather_rows

    table = jnp.asarray(rng.normal(size=(500, 32)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, 500, 512, dtype=np.int32))
    out = gather_rows(table, idx, block=128, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(table)[np.asarray(idx)], rtol=1e-7
    )


def test_pallas_lane_select_interpret(rng):
    from quiver_tpu.ops.pallas.element_gather_kernel import lane_select, BLK

    rows = jnp.asarray(rng.integers(0, 100, (BLK * 2, 128), dtype=np.int32))
    lanes = jnp.asarray(rng.integers(0, 128, BLK * 2, dtype=np.int32))
    out = lane_select(rows, lanes, interpret=True)
    expect = np.asarray(rows)[np.arange(BLK * 2), np.asarray(lanes)]
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_pallas_element_gather_interpret(rng):
    """Fused row-DMA gather kernel == ground truth (interpret mode)."""
    from quiver_tpu.ops.pallas.sample_gather_kernel import (
        pallas_element_gather)

    table = jnp.asarray(rng.normal(size=(256 * 128,)).astype(np.float32))
    t2d = table.reshape(-1, 128)
    # unaligned count exercises the pad+slice path; 2-D idx the reshape
    idx = rng.integers(0, 256 * 128, (37, 11)).astype(np.int32)
    out = pallas_element_gather(t2d, jnp.asarray(idx), interpret=True)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[idx])


def test_pallas_gather_mode_in_sampler(small_graph, rng):
    """gather_mode='pallas' flows through sample_neighbors (interpret on
    CPU is implicit via pallas interpret fallback? no — force interpret by
    calling the op's gather directly)."""
    from quiver_tpu.ops.pallas.sample_gather_kernel import (
        pallas_element_gather)

    indptr, _ = small_graph.to_device()
    m = indptr.shape[0] // 128 * 128
    idx = jnp.asarray(rng.integers(0, m, 64).astype(np.int32))
    got = pallas_element_gather(indptr[:m].reshape(-1, 128), idx,
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(indptr)[np.asarray(idx)])
