"""Fast gather paths: lane-select element gather + Pallas row gather
(interpret mode on CPU; real-TPU timing lives in benchmarks/)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from quiver_tpu.ops.fastgather import element_gather, prepare_table


def test_element_gather_matches_take(rng):
    table = jnp.asarray(rng.integers(0, 1000, 1000, dtype=np.int32))
    t2d = prepare_table(table)
    idx = jnp.asarray(rng.integers(0, 1000, 513, dtype=np.int32))
    out = element_gather(t2d, idx)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(table)[np.asarray(idx)])


def test_element_gather_2d_idx(rng):
    table = jnp.asarray(rng.normal(size=300).astype(np.float32))
    t2d = prepare_table(table)
    idx = jnp.asarray(rng.integers(0, 300, (7, 9), dtype=np.int32))
    out = element_gather(t2d, idx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(table)[np.asarray(idx)], rtol=1e-7
    )


def test_pallas_gather_rows_interpret(rng):
    from quiver_tpu.ops.pallas.gather_kernel import gather_rows

    table = jnp.asarray(rng.normal(size=(500, 32)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, 500, 512, dtype=np.int32))
    out = gather_rows(table, idx, block=128, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(table)[np.asarray(idx)], rtol=1e-7
    )


def test_pallas_lane_select_interpret(rng):
    from quiver_tpu.ops.pallas.element_gather_kernel import lane_select, BLK

    rows = jnp.asarray(rng.integers(0, 100, (BLK * 2, 128), dtype=np.int32))
    lanes = jnp.asarray(rng.integers(0, 128, BLK * 2, dtype=np.int32))
    out = lane_select(rows, lanes, interpret=True)
    expect = np.asarray(rows)[np.arange(BLK * 2), np.asarray(lanes)]
    np.testing.assert_array_equal(np.asarray(out), expect)
