"""UVA-mode (single-chip big-graph tier) tests: hot/cold split correctness
(VERDICT missing #2)."""

import numpy as np
import jax
import pytest

from quiver_tpu import GraphSageSampler
from quiver_tpu.uva import UVAGraph


def _check_valid(topo, batch):
    """Every sampled edge is a true edge; counts = min(deg, k) per hop."""
    n_id = np.asarray(batch.n_id)
    for blk in batch.layers:
        local = np.asarray(blk.nbr_local)
        m = np.asarray(blk.mask)
        t = local.shape[0]
        for v in range(min(t, 64)):
            vid = n_id[v]
            row = set(topo.indices[
                topo.indptr[vid]: topo.indptr[vid + 1]].tolist())
            for j in range(local.shape[1]):
                if m[v, j]:
                    assert int(n_id[local[v, j]]) in row


def test_uva_split_budget(power_graph):
    g = UVAGraph(power_graph, budget=power_graph.edge_count * 4 // 3)
    st = g.stats()
    assert 0 < st["hot_edges"] < power_graph.edge_count
    assert st["hot_edges"] + st["cold_edges"] == power_graph.edge_count
    assert st["hbm_bytes"] <= power_graph.edge_count * 4 // 3
    # hot rows are the high-degree ones
    deg = power_graph.degree
    if st["hot_rows"] < power_graph.node_count:
        assert deg[g.is_hot].min() >= np.sort(deg[~g.is_hot])[-1] - 1


def test_uva_sampling_correct_partial_budget(power_graph):
    s = GraphSageSampler(power_graph, [5, 4], mode="UVA",
                         uva_budget=power_graph.edge_count * 4 // 3)
    assert s.mode == "UVA" and s._uva is None  # lazy
    b = s.sample(np.arange(32, dtype=np.int64), key=jax.random.PRNGKey(0))
    assert s._uva.stats()["cold_edges"] > 0
    _check_valid(power_graph, b)
    # counts contract on both tiers
    blk = b.layers[-1]  # innermost hop: targets are the seeds
    m = np.asarray(blk.mask)
    deg = power_graph.degree
    for v in range(32):
        assert m[v].sum() == min(deg[v], 5)


def test_uva_budget_zero_all_cold(small_graph):
    s = GraphSageSampler(small_graph, [4], mode="UVA", uva_budget=0)
    b = s.sample(np.arange(16, dtype=np.int64), key=jax.random.PRNGKey(1))
    assert s._uva.stats()["hot_edges"] == 0
    _check_valid(small_graph, b)


def test_uva_no_budget_is_tpu_mode(small_graph):
    s = GraphSageSampler(small_graph, [4], mode="UVA")
    assert s.mode == "TPU"  # degenerate: everything fits


def test_uva_rejects_dedup_and_weights(small_graph):
    with pytest.raises(AssertionError):
        GraphSageSampler(small_graph, [4], mode="UVA", uva_budget=10,
                         dedup="hop")


def test_uva_pinned_key_replays_both_tiers(power_graph):
    s = GraphSageSampler(power_graph, [5, 4], mode="UVA",
                         uva_budget=power_graph.edge_count * 4 // 3)
    k = jax.random.PRNGKey(9)
    b1 = s.sample(np.arange(24, dtype=np.int64), key=k)
    b2 = s.sample(np.arange(24, dtype=np.int64), key=k)
    np.testing.assert_array_equal(np.asarray(b1.n_id), np.asarray(b2.n_id))
    for l1, l2 in zip(b1.layers, b2.layers):
        np.testing.assert_array_equal(np.asarray(l1.mask),
                                      np.asarray(l2.mask))


def test_uva_lanes_gather_covers_tail_nodes():
    """Regression: the lanes gather truncates tables to a 128 multiple
    and clips indices — an unpadded [n+1] indptr returned a WRONG row's
    pointers for the last (n+1) % 128 node ids.  Sample exactly those
    tail nodes with gather_mode='lanes' on an all-hot UVA graph and
    verify every edge against the CSR."""
    rng = np.random.default_rng(7)
    n = 300  # n+1 = 301: 45 tail ids past the 256 truncation boundary
    deg = rng.integers(1, 6, n)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=indptr[1:])
    indices = rng.integers(0, n, indptr[-1])
    from quiver_tpu import CSRTopo

    topo = CSRTopo(indptr=indptr, indices=indices)
    s = GraphSageSampler(topo, [4], mode="UVA",
                         uva_budget=topo.edge_count * 4,  # all hot
                         gather_mode="lanes")
    tail = np.arange(256, n, dtype=np.int64)  # ids the clip used to eat
    b = s.sample(tail, key=jax.random.PRNGKey(2))
    assert s._uva.stats()["cold_edges"] == 0
    _check_valid(topo, b)
    # also: counts must equal min(deg, k) — wrong pointers under-sample
    counts = np.asarray(b.layers[-1].mask).sum(axis=1)
    np.testing.assert_array_equal(counts, np.minimum(deg[tail], 4))


def test_uva_overlap_ab_bit_identical(small_graph):
    """overlap=False (serialized A/B baseline) must produce bit-identical
    samples to the overlapped path under the same key, and the timings
    dict must accumulate the cold tier's host wall."""
    from quiver_tpu.utils.rng import make_key

    budget = small_graph.edge_count * 4 // 3  # 1/3 hot
    t = {}
    s1 = GraphSageSampler(small_graph, [4, 3], mode="UVA",
                          uva_budget=budget, uva_timings=t)
    s2 = GraphSageSampler(small_graph, [4, 3], mode="UVA",
                          uva_budget=budget, uva_overlap=False)
    seeds = np.arange(32, dtype=np.int32)
    b1 = s1.sample(seeds, key=make_key(5))
    b2 = s2.sample(seeds, key=make_key(5))
    np.testing.assert_array_equal(np.asarray(b1.n_id), np.asarray(b2.n_id))
    np.testing.assert_array_equal(np.asarray(b1.n_id_mask),
                                  np.asarray(b2.n_id_mask))
    assert t.get("host_s", 0) > 0  # cold tier ran and was timed
