"""Clean twin of ``psum_seeded``: the payload crosses replicas through
the order-insensitive pmax-sentinel combine, and the only ``psum`` is a
provably-integer count — both bit-exact under any shard layout.
"""

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

AXIS = "shard"


def _combine(x, mask):
    payload = jax.lax.pmax(x, AXIS)
    count = jax.lax.psum(mask.astype(jnp.int32), AXIS)
    return payload, count


def gather_all(x, mask, devices):
    mesh = Mesh(devices, (AXIS,))
    with mesh:
        return jax.pmap(_combine, axis_name=AXIS)(x, mask)
