"""Clean twin of ``keys_seeded``: the same insertion shape, with the
key routed through a pow2 bucket — cardinality is log of the largest
frontier, and QT014 proves it from the helper name.
"""

from quiver_tpu.recovery.registry import program_cache


def _pow2_bucket(n):
    b = 1
    while b < n:
        b *= 2
    return b


class Gather:
    def __init__(self):
        self._fns = program_cache("fixture_gather", owner=self)

    def run(self, ids):
        b = _pow2_bucket(int(ids.shape[0]))
        if b not in self._fns:
            self._fns[b] = object()
        return self._fns[b]
