"""Interprocedural host sync QT001's per-file view cannot see.

``_scores`` returns a device array; ``mean_score`` coerces it with
``float()`` one call away.  Nothing on the caller's line mentions jnp,
so the lexical rule stays quiet — the staging dataflow carries the
DEVICE class through the return edge and QT013 flags the cast.
"""

import jax.numpy as jnp


def _scores(xs):
    return jnp.asarray(xs).sum()


def mean_score(xs):
    return float(_scores(xs)) / max(len(xs), 1)
