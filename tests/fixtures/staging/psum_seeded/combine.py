"""Float ``psum`` inside a ``pmap`` body: cross-replica float addition
is reduction-order-sensitive, so the result depends on the shard
layout — a bit-exactness-contract violation QT015 flags when this
module matches ``bitexact_modules``.
"""

import jax
from jax.sharding import Mesh

AXIS = "shard"


def _combine(x):
    return jax.lax.psum(x, AXIS)


def gather_all(x, devices):
    mesh = Mesh(devices, (AXIS,))
    with mesh:
        return jax.pmap(_combine, axis_name=AXIS)(x)
