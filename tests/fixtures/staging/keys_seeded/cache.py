"""Executable cache keyed on raw runtime data: every distinct frontier
length compiles (and retains) a fresh program — QT014's job is to prove
the key bounded, and here it cannot be.
"""

from quiver_tpu.recovery.registry import program_cache


class Gather:
    def __init__(self):
        self._fns = program_cache("fixture_gather", owner=self)

    def run(self, ids):
        n = int(ids.shape[0])
        if n not in self._fns:
            self._fns[n] = object()
        return self._fns[n]
