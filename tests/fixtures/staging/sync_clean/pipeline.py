"""Clean twin of ``sync_seeded``: the same cast-through-helper shape,
but the sync is an *intentional* epoch boundary and says so — the
waiver suppresses QT013 and registers with the staleness audit.
``count_of`` shows the genuinely-host path: a helper returning host
data may be cast freely.
"""

import jax.numpy as jnp


def _scores(xs):
    return jnp.asarray(xs).sum()


def _sizes(xs):
    return [len(x) for x in xs]


def mean_score(xs):
    # quiverlint: sync-ok[epoch boundary: one readback per epoch]
    return float(_scores(xs)) / max(len(xs), 1)


def count_of(xs):
    return int(sum(_sizes(xs)))
