"""Seeded QT009 true positives — see ../README.md."""
