"""Two QT009 shapes: an A→B / B→A cycle between two public entry
points, and a plain-Lock self-deadlock reached interprocedurally (the
callee's must-hold entry set carries the lock into a second acquire).
"""

import threading


class Pair:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def forward(self):
        with self.a:
            with self.b:
                pass

    def backward(self):
        with self.b:
            with self.a:
                pass


class Reenter:
    def __init__(self):
        self.lock = threading.Lock()

    def outer(self):
        with self.lock:
            self._inner()

    def _inner(self):
        with self.lock:  # entry_must carries `lock`: self-deadlock
            pass
