"""Seeded QT008 true positives — see ../README.md."""
