"""Contract violations the per-file lexical rule (QT003) cannot see.

``rebuild`` writes ``store.rows`` through a non-self reference without
``Store._lock`` (cross-object past the `_guarded_by` contract), and
``tick`` calls the requires-lock ``Segment.flush`` without holding the
named lock: both are QT008's whole-program job.
"""

import threading


class Store:
    _guarded_by = {"rows": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self.rows = []

    def put(self, row):
        with self._lock:
            self.rows.append(row)


def rebuild(store: "Store"):
    store.rows = []  # cross-object write, lock not held


class Segment:
    """Externally synchronized, like the real delta segment: callers
    must hold ``Store._lock`` (no ``_guarded_by`` of its own)."""

    def __init__(self):
        self.count = 0

    # quiverlint: requires-lock[Store._lock]
    def flush(self):
        self.count = 0


def tick(seg: "Segment"):
    seg.flush()  # requires-lock callee, lock not held
