"""Undeclared shared attribute written from two thread roots, no lock.

``Pipeline.progress`` is written by the worker thread (`_loop`) and by
the main root (`reset`) with no common lock and no `_guarded_by` entry:
QT008's undeclared-attribute check must flag it.
"""

import threading

from quiver_tpu.resilience.shutdown import join_and_reap


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self.progress = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            self.progress += 1  # worker write, unguarded

    def reset(self):
        self.progress = 0  # main write, unguarded

    def stop(self):
        self._stop.set()
        join_and_reap([self._thread], 1.0, component="fixture.pipeline")
