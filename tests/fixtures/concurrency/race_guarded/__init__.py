"""True-negative twins of race_seeded — see ../README.md."""
