"""race_seeded/pipeline.py with the lock actually taken.

``progress`` is declared in ``_guarded_by`` and every write (worker and
main root alike) holds ``_lock`` — QT008 and QT003 must both stay quiet.
"""

import threading

from quiver_tpu.resilience.shutdown import join_and_reap


class Pipeline:
    _guarded_by = {"progress": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self.progress = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            with self._lock:
                self.progress += 1

    def reset(self):
        with self._lock:
            self.progress = 0

    def stop(self):
        self._stop.set()
        join_and_reap([self._thread], 1.0, component="fixture.pipeline")
