"""race_seeded/store.py with every contract honored.

The cross-object write holds the declared lock, and the requires-lock
callee is invoked under it — clean under QT008.
"""

import threading


class Store:
    _guarded_by = {"rows": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self.rows = []

    def put(self, row):
        with self._lock:
            self.rows.append(row)


def rebuild(store: "Store"):
    with store._lock:
        store.rows = []


class Segment:
    """Externally synchronized: callers hold ``Store._lock``."""

    def __init__(self):
        self.count = 0

    # quiverlint: requires-lock[Store._lock]
    def flush(self):
        self.count = 0


def tick(store: "Store", seg: "Segment"):
    with store._lock:
        seg.flush()
