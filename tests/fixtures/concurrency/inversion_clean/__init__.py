"""True-negative twins of inversion_seeded — see ../README.md."""
