"""inversion_seeded/pair.py, ordered: every path takes a before b, and
the re-entrant path uses an RLock (re-entry is its contract) — QT009
must stay quiet on both.
"""

import threading


class Pair:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()

    def forward(self):
        with self.a:
            with self.b:
                pass

    def also_forward(self):
        with self.a:
            with self.b:
                pass


class Reenter:
    def __init__(self):
        self.lock = threading.RLock()

    def outer(self):
        with self.lock:
            self._inner()

    def _inner(self):
        with self.lock:
            pass
