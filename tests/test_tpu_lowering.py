"""AOT Mosaic-lowering checks: every Pallas kernel must lower for the
REAL TPU platform, validated on the CPU host via ``jax.export``.

Interpret-mode tests prove semantics; they skip the Mosaic lowering pass
entirely, which is where TPU layout/cast restrictions bite (this caught
a real uint32->f32 cast the window kernel shipped with — an error that
would otherwise have burned a hardware window to discover).
"""

import jax
import jax.export  # not re-exported by `import jax` on every version
import jax.numpy as jnp
import numpy as np
import pytest


def _export_ok(f, *args):
    try:
        jax.export.export(jax.jit(f), platforms=["tpu"])(*args)
    except Exception as e:  # noqa: BLE001 — re-raised unless version-gated
        if "Reductions over integers not implemented" in str(e):
            pytest.skip("Mosaic backend in this jax build lacks integer "
                        "reductions; kernel lowers on newer jax")
        raise


@pytest.mark.parametrize("B,k,U", [(1024, 15, 3), (300, 5, 2), (64, 8, 1)])
def test_window_sample_kernel_lowers_for_tpu(B, k, U):
    from quiver_tpu.ops.pallas.window_sample_kernel import (
        pallas_window_sample)

    table = jnp.zeros((4096, 128), jnp.int32)
    start = jnp.zeros((B,), jnp.int32)
    deg = jnp.ones((B,), jnp.int32)
    key = jax.random.PRNGKey(0)
    _export_ok(lambda t, s, d, kk: pallas_window_sample(t, s, d, kk, k,
                                                        U=U),
               table, start, deg, key)


def test_element_gather_kernel_lowers_for_tpu():
    from quiver_tpu.ops.pallas.sample_gather_kernel import (
        pallas_element_gather)

    table = jnp.zeros((512, 128), jnp.float32)
    idx = jnp.zeros((4096,), jnp.int32)
    _export_ok(lambda t, i: pallas_element_gather(t, i), table, idx)


def test_row_gather_kernel_lowers_for_tpu():
    from quiver_tpu.ops.pallas.gather_kernel import gather_rows

    table = jnp.zeros((500, 128), jnp.float32)
    idx = jnp.zeros((512,), jnp.int32)
    _export_ok(lambda t, i: gather_rows(t, i, block=128), table, idx)


def test_lane_select_kernel_lowers_for_tpu():
    from quiver_tpu.ops.pallas.element_gather_kernel import lane_select, BLK

    rows = jnp.zeros((BLK * 2, 128), jnp.int32)
    lanes = jnp.zeros((BLK * 2,), jnp.int32)
    _export_ok(lambda r, l: lane_select(r, l), rows, lanes)
