"""quiverlint v2 whole-program concurrency tests (QT008/QT009/QT010).

Two layers:

* model + rule unit tests over tmp_path fixtures, through the real
  ``analyze_paths`` / ``build_program`` entry points (same idiom as
  ``test_quiverlint_rules.py``);
* end-to-end CLI gates over the on-disk packages in
  ``tests/fixtures/concurrency/`` — seeded bugs must exit 1 with exactly
  the expected rule, clean twins must exit 0.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from quiver_tpu.analysis import LintConfig, analyze_paths
from quiver_tpu.analysis.concurrency import (
    build_program,
    canonical_lock_edges,
)
from quiver_tpu.analysis.concurrency.program import MAIN_ROOT
from quiver_tpu.analysis.core import load_contexts

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "concurrency"

# referencing join_and_reap satisfies QT010 so unrelated fixtures stay
# single-rule; the fixtures never execute, imports are never resolved
REAP = "from quiver_tpu.resilience.shutdown import join_and_reap\n"


def run_lint(tmp_path, source, name="mod.py", prelude=""):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(prelude + textwrap.dedent(source))
    result = analyze_paths([str(p)], config=LintConfig(), root=tmp_path)
    assert result.errors == [], result.errors  # fixture must parse
    return result


def prog_of(tmp_path, source, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return build_program(load_contexts([str(p)], root=tmp_path))


def codes(result):
    return sorted(f.rule for f in result.findings)


# ------------------------------------------------------ call graph/roots
class TestProgramModel:
    def test_thread_root_discovery_and_reachability(self, tmp_path):
        prog = prog_of(tmp_path, """
            import threading

            class Worker:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self._step()

                def _step(self):
                    pass

            def entry():
                pass
        """)
        run_roots = prog.roots_of["mod:Worker._run"]
        assert run_roots and MAIN_ROOT not in run_roots
        # reachability: the root flows through the call edge into _step
        assert prog.roots_of["mod:Worker._step"] == run_roots
        # public module function seeds the synthetic main root
        assert MAIN_ROOT in prog.roots_of["mod:entry"]

    def test_must_lock_entry_set_is_intersection(self, tmp_path):
        prog = prog_of(tmp_path, """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def locked(self):
                    with self._lock:
                        self._work()

                def also_locked(self):
                    with self._lock:
                        self._work()

                def unlocked(self):
                    self._work()

                def _work(self):
                    pass

                def only_locked(self):
                    with self._lock:
                        self._deep()

                def _deep(self):
                    pass
            """)
        # _work: one caller holds nothing -> intersection is empty
        assert prog.entry_must["mod:C._work"] == frozenset()
        # _deep: private, every caller chain holds the lock
        deep = prog.entry_must["mod:C._deep"]
        assert {(l.owner, l.attr) for l in deep} == {("mod:C", "_lock")}

    def test_canonical_lock_edges_vocabulary(self, tmp_path):
        p = tmp_path / "mod.py"
        p.write_text(textwrap.dedent("""
            import threading

            class C:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()

                def nest(self):
                    with self.a:
                        with self.b:
                            pass
        """))
        edges = canonical_lock_edges(load_contexts([str(p)], root=tmp_path))
        assert ("C.a", "C.b") in edges
        assert ("C.b", "C.a") not in edges


# ------------------------------------------------------------ QT008
class TestDataRace:
    def test_undeclared_two_root_write_flagged(self, tmp_path):
        r = run_lint(tmp_path, prelude=REAP, source="""
            import threading

            class P:
                def __init__(self):
                    self.n = 0
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    self.n += 1

                def bump(self):
                    self.n = 0

                def stop(self):
                    join_and_reap([self._t], 1.0, component="t")
        """)
        assert codes(r) == ["QT008"]
        assert "2 thread roots" in r.findings[0].message

    def test_common_lock_on_every_write_is_clean(self, tmp_path):
        r = run_lint(tmp_path, prelude=REAP, source="""
            import threading

            class P:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    with self._lock:
                        self.n += 1

                def bump(self):
                    with self._lock:
                        self.n = 0

                def stop(self):
                    join_and_reap([self._t], 1.0, component="t")
        """)
        assert r.findings == []

    def test_single_root_attr_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            class P:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    self.n += 1
        """)
        assert r.findings == []

    def test_cross_object_declared_write_needs_lock(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class Store:
                _guarded_by = {"rows": "_lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

            def racy(store: "Store"):
                store.rows = []

            def fine(store: "Store"):
                with store._lock:
                    store.rows = []
        """)
        assert codes(r) == ["QT008"]
        assert r.findings[0].scope == "racy"
        assert "_guarded_by" in r.findings[0].message

    def test_interprocedural_must_lock_guards_callee_write(self, tmp_path):
        # _apply only ever runs under the lock: its write is guarded by
        # the propagated entry set, not lexically
        r = run_lint(tmp_path, """
            import threading

            class Store:
                _guarded_by = {"rows": "_lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

            def _apply(store: "Store"):
                store.rows = []

            def outer(store: "Store"):
                with store._lock:
                    _apply(store)
        """)
        assert r.findings == []

    def test_requires_lock_directive_trusts_body_checks_callers(
            self, tmp_path):
        src = """
            import threading

            class Store:
                _guarded_by = {"rows": "_lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

            class Segment:
                def __init__(self):
                    self.count = 0

                # quiverlint: requires-lock[Store._lock]
                def flush(self):
                    self.count = 0

            def good(store: "Store", seg: "Segment"):
                with store._lock:
                    seg.flush()
        """
        assert run_lint(tmp_path, src).findings == []
        r = run_lint(tmp_path, textwrap.dedent(src) + textwrap.dedent("""
            def bad(seg: "Segment"):
                seg.flush()
        """))
        assert codes(r) == ["QT008"]
        assert "requires-lock" in r.findings[0].message
        assert r.findings[0].scope == "bad"

    def test_fresh_local_prepublication_write_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class Store:
                _guarded_by = {"rows": "_lock"}

                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

            def make():
                s = Store()
                s.rows = [1]  # not yet published: no lock needed
                return s
        """)
        assert r.findings == []

    def test_sync_primitive_attr_is_exempt(self, tmp_path):
        r = run_lint(tmp_path, prelude=REAP, source="""
            import threading

            class W:
                def __init__(self):
                    self._stop = threading.Event()
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    while not self._stop.is_set():
                        pass

                def reset(self):
                    self._stop.clear()

                def stop(self):
                    join_and_reap([self._t], 1.0, component="t")
        """)
        assert r.findings == []

    def test_qos_ladder_level_needs_common_lock(self, tmp_path):
        # qos-shaped fixture: the degradation level is written from the
        # watchdog listener thread AND reset from the main root; without
        # a shared lock that is exactly the race QT008 exists to catch
        r = run_lint(tmp_path, prelude=REAP,
                     name="quiver_tpu/resilience/qos_fixture.py", source="""
            import threading

            class Ladder:
                def __init__(self):
                    self.level = 0
                    self._t = threading.Thread(target=self._watch)

                def _watch(self):
                    self.level += 1

                def reset(self):
                    self.level = 0

                def stop(self):
                    join_and_reap([self._t], 1.0, component="t")
        """)
        assert codes(r) == ["QT008"]
        assert r.findings[0].message.count("level")

    def test_qos_ladder_level_under_lock_is_clean(self, tmp_path):
        # the shipped idiom: tick decisions under _lock, effects outside
        r = run_lint(tmp_path, prelude=REAP,
                     name="quiver_tpu/resilience/qos_fixture.py", source="""
            import threading

            class Ladder:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.level = 0
                    self._t = threading.Thread(target=self._watch)

                def _watch(self):
                    with self._lock:
                        self.level += 1

                def reset(self):
                    with self._lock:
                        self.level = 0

                def stop(self):
                    join_and_reap([self._t], 1.0, component="t")
        """)
        assert r.findings == []

    def test_suppression_comment_silences_qt008(self, tmp_path):
        r = run_lint(tmp_path, prelude=REAP, source="""
            import threading

            class P:
                def __init__(self):
                    self.n = 0
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    # quiverlint: ignore[QT008] -- test fixture
                    self.n += 1

                def bump(self):
                    self.n = 0

                def stop(self):
                    join_and_reap([self._t], 1.0, component="t")
        """)
        assert r.findings == []
        assert [f.rule for f in r.suppressed] == ["QT008"]


# ------------------------------------------------------------ QT009
class TestLockOrder:
    def test_ab_ba_cycle_flagged(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class Pair:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()

                def fwd(self):
                    with self.a:
                        with self.b:
                            pass

                def bwd(self):
                    with self.b:
                        with self.a:
                            pass
        """)
        assert codes(r) == ["QT009"]
        assert "inversion" in r.findings[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class Pair:
                def __init__(self):
                    self.a = threading.Lock()
                    self.b = threading.Lock()

                def fwd(self):
                    with self.a:
                        with self.b:
                            pass

                def fwd2(self):
                    with self.a:
                        with self.b:
                            pass
        """)
        assert r.findings == []

    def test_plain_lock_reacquire_via_callee_flagged(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class R:
                def __init__(self):
                    self.lock = threading.Lock()

                def outer(self):
                    with self.lock:
                        self._inner()

                def _inner(self):
                    with self.lock:
                        pass
        """)
        assert codes(r) == ["QT009"]
        assert "self-deadlock" in r.findings[0].message

    def test_rlock_reentry_is_clean(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class R:
                def __init__(self):
                    self.lock = threading.RLock()

                def outer(self):
                    with self.lock:
                        self._inner()

                def _inner(self):
                    with self.lock:
                        pass
        """)
        assert r.findings == []


# ------------------------------------------------------------ QT010
class TestThreadReap:
    def test_unreaped_thread_root_flagged(self, tmp_path):
        r = run_lint(tmp_path, """
            import threading

            class W:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass
        """)
        assert codes(r) == ["QT010"]

    def test_join_and_reap_reference_satisfies(self, tmp_path):
        r = run_lint(tmp_path, prelude=REAP, source="""
            import threading

            class W:
                def __init__(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()

                def _run(self):
                    pass

                def stop(self):
                    join_and_reap([self._t], 1.0, component="t")
        """)
        assert r.findings == []

    def test_submit_on_borrowed_pool_not_flagged(self, tmp_path):
        # the pool is a parameter: the caller owns its lifecycle, so
        # there is nothing for this scope to reap (QT003 regression
        # fixtures rely on this staying quiet)
        r = run_lint(tmp_path, """
            class S:
                def schedule(self, pool, k):
                    pool.submit(lambda: k)
        """)
        assert r.findings == []

    def test_submit_on_owned_pool_still_flagged(self, tmp_path):
        r = run_lint(tmp_path, """
            from concurrent.futures import ThreadPoolExecutor

            class S:
                def __init__(self):
                    self._pool = ThreadPoolExecutor(2)

                def schedule(self, k):
                    self._pool.submit(lambda: k)
        """)
        assert codes(r) == ["QT010"]


# --------------------------------------------------- fixture package e2e
def _cli_json(target):
    proc = subprocess.run(
        [sys.executable, "-m", "quiver_tpu.analysis", str(target),
         "--format", "json"],
        capture_output=True, text=True, timeout=300, cwd=str(REPO))
    return proc.returncode, json.loads(proc.stdout)


@pytest.mark.parametrize("pkg, rules", [
    ("race_seeded", {"QT008"}),
    ("inversion_seeded", {"QT009"}),
])
def test_seeded_fixture_fails_cli(pkg, rules):
    rc, doc = _cli_json(FIXTURES / pkg)
    assert rc == 1
    assert {f["rule"] for f in doc["findings"]} == rules


@pytest.mark.parametrize("pkg", ["race_guarded", "inversion_clean"])
def test_clean_fixture_passes_cli(pkg):
    rc, doc = _cli_json(FIXTURES / pkg)
    assert rc == 0, doc
    assert doc["findings"] == []
