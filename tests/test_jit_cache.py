"""Executable-cache behavior: alternating batch sizes must not recompile
(VERDICT weak #3 — serving's bucketed shapes collide with a cache of 1)."""

import numpy as np
import jax
import pytest

from quiver_tpu import GraphSageSampler


def test_sampler_cache_keeps_all_batch_sizes(small_graph):
    s = GraphSageSampler(small_graph, [4, 3])
    builds = []
    orig = s._build_jit

    def counting_build(B):
        builds.append(B)
        return orig(B)

    s._build_jit = counting_build
    for B in [8, 16, 8, 32, 16, 8, 32, 16]:
        b = s.sample(np.arange(B, dtype=np.int64),
                     key=jax.random.PRNGKey(B))
        assert b.batch_size == B
    # one build per distinct size, regardless of interleaving
    assert sorted(builds) == [8, 16, 32]
    assert sorted(s._jitted) == [8, 16, 32]


def test_loader_does_not_mutate_caller_train_idx(small_graph):
    from quiver_tpu.loader import SeedLoader

    class _IdFeature:
        def __getitem__(self, ids):
            return np.zeros((len(ids), 2), np.float32)

    s = GraphSageSampler(small_graph, [3])
    train_idx = np.arange(40, dtype=np.int64)
    snapshot = train_idx.copy()
    loader = SeedLoader(train_idx, s, _IdFeature(), batch_size=16,
                        shuffle=True, prefetch=0)
    for _ in loader:
        pass
    # epoch shuffling must not leak into the caller's array
    np.testing.assert_array_equal(train_idx, snapshot)
    # but the loader itself did shuffle its own copy
    assert not np.array_equal(loader.train_idx, snapshot)
