"""Smoke-run every example at tiny scale — keeps examples working as the
library evolves (the reference's examples rotted; SURVEY §4)."""

import runpy
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

EXAMPLES = {
    "examples/reddit_sage.py": [
        "--synthetic-nodes", "2000", "--epochs", "1",
        "--batch-size", "128", "--cache", "5M",
    ],
    "examples/graph_sage_unsup.py": [
        "--nodes", "1500", "--steps", "6", "--batch-size", "64",
    ],
    "examples/papers100M_dist.py": [
        "--nodes", "3000", "--edges", "30000", "--steps", "2",
        "--batch-size", "8", "--dim", "8",
    ],
    "examples/mag240m_rgat.py": [
        "--papers", "800", "--authors", "400", "--institutions", "50",
        "--steps", "3", "--batch-size", "16",
    ],
    "examples/preprocess_partition.py": [
        "--nodes", "2000", "--edges", "20000", "--hosts", "4",
        "--out", "/tmp/qt_part_test",
    ],
    "examples/serving_reddit.py": [
        "--nodes", "1500", "--edges", "15000", "--clients", "2",
        "--requests-per-client", "4",
    ],
    # (examples/dgl_products_sage.py is smoke-run by
    # tests/test_interop.py::TestDGLBlocks::test_fallback_sage_learns)
    "examples/ogbn_products_sage.py": [
        "--force-synthetic", "--synthetic-nodes", "3000", "--epochs", "1",
        "--batch-size", "128", "--cache", "10M",
    ],
    "examples/big_graph_single_chip.py": [
        "--nodes", "3000", "--deg", "8", "--dim", "16",
        "--batch-size", "64", "--steps", "4",
        "--graph-budget", "60K", "--feature-budget", "100K",
    ],
}


@pytest.mark.parametrize("script", sorted(EXAMPLES))
def test_example_runs(script, monkeypatch):
    monkeypatch.setattr(sys, "argv", [script] + EXAMPLES[script])
    runpy.run_path(f"/root/repo/{script}", run_name="__main__")
